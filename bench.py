#!/usr/bin/env python
"""Benchmark: training throughput + MFU for the flagship config on real hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The BASELINE.json target is >=50% MFU on the 124M GPT-2 config;
`vs_baseline` is measured_MFU / 0.50 (1.0 = target met). Metrics with no
reference baseline at all (decode, serving — the reference publishes
neither) carry `vs_baseline: null`, never a 0.0 sentinel.

Resilience: the TPU backend here is reached through a tunnel that can return
transient UNAVAILABLE errors or hang outright during init. JAX caches a failed
backend for the life of the process, so retrying in-process is useless —
instead the default entry point is a thin wrapper that re-execs itself with
``--_inner`` per attempt, each attempt a fresh process under a hard timeout,
with exponential backoff on transient failures until ``--timeout-budget``
seconds are spent. Self-diagnosis (VERDICT r2 #1): before any budget is
spent, a 1-matmul CANARY subprocess classifies the environment — a dead
tunnel emits ``{"error": "environment: backend unreachable", ...,
"environment_error": true}`` instead of an unattributable hang; the inner
run stamps phases to stderr (backend up → state built → compile → steps) so
a killed attempt names its phase. A default gpt2-124m train run RACES an
ordered candidate list — newest remat policy first, then the proven-safe
ladder (``full`` remat, finally ``--attention naive``) with reserved budget
shares — and reports the best success: one pathological policy can cost a
bounded attempt, never the round's number. On final failure it prints a
structured JSON error line (never a traceback) so the driver always gets
parseable output.

Usage:
  python bench.py             # full run (gpt2-124m, auto batch)
  python bench.py --quick     # fewer steps, for smoke testing
  python bench.py --preset gpt2-350m-dp --batch 8
  python bench.py --timeout-budget 1200
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="gpt2-124m")
    parser.add_argument(
        "--batch", type=int, default=0,
        help="global batch (0 = bench auto: the measured-best batch for the "
        "preset on this chip, e.g. 24 for gpt2-124m; pass the preset's own "
        "training batch explicitly to reproduce it)",
    )
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--mode", default="train", choices=["train", "decode", "trainer",
                                            "serving", "serving-slo",
                                            "serving-fleet", "kernel"],
        help="train: tokens/sec + MFU of the train step (the driver metric); "
        "decode: KV-cached generation tokens/sec; trainer: the FULL Trainer "
        "loop incl. the input pipeline (measures host-sampling overlap — "
        "compare --prefetch 0 vs 2); serving: continuous-batching paged "
        "engine throughput (mixed-length requests through a fixed row set); "
        "serving-slo: ONLINE latency under Poisson load through the "
        "frontend EngineLoop — p50/p99 TTFT and goodput-under-SLO, not "
        "offline throughput; serving-fleet: the same Poisson load through "
        "the N-replica fleet Router while a --fleet-scenario disturbance "
        "runs (replica kill mid-burst, rolling restart, skewed hot-prefix "
        "affinity) — measures goodput and redrive cost under failure; "
        "kernel: ragged paged-attention microbench sweeping (B, T, pages, "
        "window, int8) lanes over the {gather, ragged, ragged+split, "
        "ragged+amla} variants — runs anywhere (CPU numbers are interpret-"
        "mode and labeled cpu_interpret), so kernel-level wins bank even "
        "while the TPU backend is unreachable",
    )
    parser.add_argument(
        "--steps-per-sched", type=int, default=0,
        help="serving mode: decode steps per device dispatch (multi-step "
        "scheduling window; 1 = reap/admit every token; 0 = default 8)",
    )
    parser.add_argument(
        "--prefetch", type=int, default=-1,
        help="trainer mode: data.prefetch depth override (-1 = preset value)",
    )
    parser.add_argument(
        "--ragged", action="store_true",
        help="decode mode: serving-shaped batch with per-row prompt lengths "
        "(one lockstep ragged program)",
    )
    parser.add_argument(
        "--optimizer", default="", choices=["", "adamw", "adafactor", "muon"],
        help="train mode: optimizer override (adafactor's factored second "
        "moments fit 1B+ configs on one chip)",
    )
    parser.add_argument(
        "--grad-dtype", default="", choices=["", "float32", "bfloat16"],
        help="train mode: gradient storage dtype override (bfloat16 halves "
        "the ~4 bytes/param gradient tree — the 1B batch-knee lever; "
        "norm/clip/optimizer math still reduces in fp32 per leaf)",
    )
    parser.add_argument(
        "--kv-dtype", default="", choices=["", "compute", "int8"],
        help="decode mode: KV-cache element type override (int8 = quantized "
        "persistent cache, ~1.9x smaller at Dh=64)",
    )
    parser.add_argument("--attention", default="", choices=["", "naive", "flash"])
    parser.add_argument("--ce", default="", choices=["", "chunked", "fused", "dense"])
    parser.add_argument(
        "--remat", default="", choices=["", "none", "full", "dots_saveable", "save_attn", "save_attn_res", "save_qkv_attn", "save_big"]
    )
    parser.add_argument("--unroll", type=int, default=0, help="scan_unroll override")
    parser.add_argument(
        "--context", type=int, default=0,
        help="train mode: context_length override (long-context probes; "
        "RoPE presets extrapolate — learned-position presets are rejected "
        "since their tables are sized by the original context)",
    )
    parser.add_argument(
        "--cache-layout", default="", choices=["", "stacked", "unstacked"],
        help="decode mode: KV-cache container layout override. 'unstacked' "
        "(the model default; measured 6,856 vs 4,129 tok/s on v5e "
        "2026-08-01) = per-layer caches updated in place on the token-scan "
        "carry; 'stacked' = the historical (L, ...) baseline series.",
    )
    parser.add_argument(
        "--decode-unroll", action="store_true",
        help="decode mode: fully unroll the depth scan for single-token "
        "steps (decode_unroll_layers=True) — removes the inner while loop "
        "whose boundary copies the whole KV cache every step (AOT-measured "
        "~140 MB/step at gpt2-124m b8). Unproven kernel-config class on "
        "this backend; probe via the risky capture tier only.",
    )
    parser.add_argument(
        "--block-q", type=int, default=0,
        help="flash kernel q-block override (0 = auto). WARNING: measured "
        "2026-07-31 on the axon v5e backend, 512x512 blocks at T=1024 HUNG "
        "the chip (Mosaic-class wedge, multi-hour backend outage after the "
        "kill) — the auto block size is the only proven-safe layout there.",
    )
    parser.add_argument(
        "--block-kv", type=int, default=0,
        help="flash kernel kv-block override (same hang warning as --block-q)"
    )
    parser.add_argument(
        "--timeout-budget",
        type=float,
        default=1800.0,
        help="total seconds across all attempts before giving up with a JSON error",
    )
    parser.add_argument(
        "--attempt-timeout",
        type=float,
        default=700.0,
        help="hard wall-clock cap for a single attempt (compile can take minutes on TPU)",
    )
    parser.add_argument(
        "--race-repeats", type=int, default=3,
        help="total same-config samples of the race WINNER to collect "
        "(budget permitting) so the banked record carries a same-session "
        "median, not a single best-of-one reading (VERDICT #1). 1 = no "
        "repeat runs (the historical single-sample behavior)",
    )
    parser.add_argument(
        "--no-pipeline", action="store_true",
        help="serving mode: disable the pipelined scheduler (A/B "
        "baseline; the pipelined run loop is the default)",
    )
    parser.add_argument(
        "--pipeline-depth", type=int, default=0,
        help="serving mode: in-flight decode-window queue depth (0 = "
        "engine default 2; 1 = the classic double-buffered scheduler). "
        "Host scheduling only — greedy outputs identical at every depth",
    )
    parser.add_argument(
        "--admit-batch", type=int, default=0,
        help="serving mode: accumulate waiting prefills until this many "
        "can be admitted in ONE batched prefill (0/1 = admit eagerly "
        "every window boundary)",
    )
    parser.add_argument(
        "--paged-attn", default="", choices=["", "gather", "kernel"],
        help="serving mode: paged decode attention impl (kernel = the "
        "Pallas block-table kernel, gather = XLA pool[tables] assembly)",
    )
    parser.add_argument(
        "--quantize", default="", choices=["", "none", "int8", "int8-kv"],
        help="serving/serving-slo mode: int8 serving quantization. 'int8' "
        "= per-channel int8 weights (attention/FFN projections, bf16 "
        "accumulation); 'int8-kv' additionally packs the KV pool as int8 "
        "pages with bf16 per-token scales (~1.9x block capacity at "
        "head_dim 64 for the same HBM budget). Records gain a "
        "'quantization' block with model-bytes and KV-bytes-per-token",
    )
    parser.add_argument(
        "--spec-draft", default="", choices=["", "self"],
        help="serving mode: speculative decoding draft. 'self' uses the "
        "TARGET as its own draft — acceptance ~100%%, measuring the "
        "dispatch-amortization UPPER BOUND (no trained draft ships with "
        "the bench); real deployments pass a trained draft via "
        "scripts/serve.py --draft_model_path",
    )
    parser.add_argument(
        "--spec-k", type=int, default=4,
        help="serving mode: draft proposals per speculative round",
    )
    parser.add_argument(
        "--rate-rps", type=float, default=4.0,
        help="serving-slo mode: open-loop Poisson arrival rate",
    )
    parser.add_argument(
        "--slo-ttft-s", type=float, default=1.0,
        help="serving-slo mode: TTFT bound a request must meet to count "
        "toward goodput (0 = no TTFT bound)",
    )
    parser.add_argument(
        "--slo-e2e-s", type=float, default=10.0,
        help="serving-slo mode: end-to-end bound for goodput (0 = none)",
    )
    parser.add_argument(
        "--n-requests", type=int, default=0,
        help="serving-slo mode: workload size (0 = 3x max_batch)",
    )
    parser.add_argument(
        "--prefix-cache", action="store_true",
        help="serving/serving-slo mode: cross-request prefix cache "
        "(content-addressed shared KV blocks; greedy outputs unchanged)",
    )
    parser.add_argument(
        "--prefix-pool-size", type=int, default=0,
        help="serving-slo mode: hot-prefix scenario — pool of shared "
        "prefixes each request draws from (0 = off)",
    )
    parser.add_argument(
        "--prefix-len", type=int, default=0,
        help="serving-slo mode: shared-prefix length in tokens "
        "(0 = 2x block_size when a pool is set)",
    )
    parser.add_argument(
        "--prefix-zipf", type=float, default=1.0,
        help="serving-slo mode: zipf skew over prefix-pool rank "
        "(0 = uniform, larger = hotter head)",
    )
    parser.add_argument(
        "--prefill-chunk-tokens", type=int, default=0,
        help="serving/serving-slo mode: chunked prefill — stream prompts "
        "into the pool in chunks of at most this many tokens, interleaved "
        "with decode windows, instead of one monolithic prefill per "
        "admission (0 = off; greedy outputs identical either way). In "
        "serving-slo mode also runs a monolithic-prefill baseline pass "
        "and records the TTFT-p99 before/after delta",
    )
    parser.add_argument(
        "--replicas", type=int, default=2,
        help="serving-fleet mode: in-process engine replicas behind the "
        "router",
    )
    parser.add_argument(
        "--fleet-scenario", default="kill",
        choices=[
            "kill", "rolling", "hotprefix", "upgrade", "proc-kill",
            "partition", "disagg", "decode-sat",
        ],
        help="serving-fleet mode: kill = deterministic replica_crash on "
        "replica 0 one third into the burst (redrive drill); rolling = "
        "drain/restore each replica in turn under load; hotprefix = "
        "zipf-skewed shared-prefix traffic, measuring prefix-affinity "
        "placement (per-replica spread, no faults); upgrade = probe-vetted "
        "rolling weight upgrade of every replica while the burst runs "
        "(zero client-visible errors expected); proc-kill = out-of-process "
        "worker fleet (RemoteReplica), SIGKILL worker 0 mid-burst and "
        "measure redrive + relaunch across a real process death; "
        "partition = out-of-process fleet, blackhole worker 0 mid-decode "
        "(reads hang, writes buffer — no RST), lease expiry redrives its "
        "work, heal after redrive and count the stale-generation frames "
        "the fence filter drops (zero lost + zero duplicated invariants "
        "recorded); disagg = disaggregated tiers — replica 0 serves only "
        "prefill legs, the rest only decode, zipf-skewed shared-prefix "
        "traffic migrates KV pages prefill->decode and the record is the "
        "decode tier's TTFT while the prefill tier absorbs the prefill "
        "burst (kv migration counters recorded); decode-sat = same "
        "disaggregated tiers but the offered load is 4x --rate-rps so "
        "the DECODE tier saturates — a live SLO engine (rolling "
        "percentile sketches per replica) rides the fleet bus and the "
        "record asserts prefill-tier isolation: the prefill replica's "
        "latency distribution stays flat while decode queue-wait "
        "inflates (sketch summaries + fired alerts recorded)",
    )
    parser.add_argument("--_inner", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--_canary", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument(
        "--canary-timeout",
        type=float,
        default=150.0,
        help="seconds the 1-matmul environment canary may take before the "
        "backend is declared unreachable (first TPU compile ~20-40s)",
    )
    parser.add_argument(
        "--skip-canary", action="store_true",
        help="skip the environment canary (e.g. on a known-good local backend)",
    )
    return parser.parse_args(argv)


def _stamp(msg: str) -> None:
    """Phase stamp to stderr: a killed attempt is attributable to a phase
    (backend init vs compile vs steps), and a dead tunnel is distinguishable
    from a framework regression (VERDICT r2 weak #1)."""
    print(f"[bench-inner {time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.monotonic()


def canary_main() -> int:
    """Minimal environment probe: acquire the backend, jit ONE matmul.

    Success proves the tunnel/backend is alive and compiles run; any hang or
    error here is an ENVIRONMENT failure, not a framework regression. Runs in
    its own subprocess (JAX pins a failed backend for the process lifetime).
    """
    from pretraining_llm_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    _stamp("canary: importing jax")
    import jax
    import jax.numpy as jnp

    _stamp("canary: acquiring devices")
    devs = jax.devices()
    _stamp(f"canary: backend up: {jax.default_backend()} x{len(devs)} ({devs[0].device_kind})")
    x = jnp.ones((512, 512), jnp.bfloat16)
    y = jax.jit(lambda a: a @ a)(x)
    val = float(jax.device_get(y[0, 0]))
    _stamp(f"canary: matmul done ({val})")
    print(json.dumps({"ok": True, "platform": jax.default_backend(),
                      "device": devs[0].device_kind, "n_devices": len(devs)}))
    return 0


def run_decode_bench(args: argparse.Namespace) -> dict:
    """KV-cached generation throughput: tokens/sec for batched decode.

    The reference's generate re-forwards the whole window per token — O(n*T^2)
    with no cache (SURVEY §3.2); this measures the redesigned O(n*T) path
    (prefill + lax.scan single-token steps) end to end.
    """
    import jax

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.generation.generate import generate
    from pretraining_llm_tpu.models import transformer

    cfg = get_preset(args.preset).model
    # Train-only knobs are rejected, not ignored: a decode record emitted
    # after `--block-q 256` or `--optimizer adafactor` would be
    # indistinguishable from the default run while the operator believes
    # they measured a different config. (--attention: the KV-cached forward
    # always attends via the masked einsum path — per-step shapes are tiny,
    # flash targets training.)
    noop = {
        "--attention": args.attention, "--remat": args.remat, "--ce": args.ce,
        "--optimizer": args.optimizer, "--unroll": args.unroll,
        "--block-q": args.block_q, "--block-kv": args.block_kv,
        "--steps-per-sched": args.steps_per_sched,
        "--context": args.context, "--paged-attn": args.paged_attn,
        "--spec-draft": args.spec_draft, "--no-pipeline": args.no_pipeline,
        "--pipeline-depth": args.pipeline_depth,
        "--admit-batch": args.admit_batch,
        "--grad-dtype": args.grad_dtype,
        "--prefix-cache": args.prefix_cache,
        "--prefix-pool-size": args.prefix_pool_size,
        "--prefix-len": args.prefix_len,
        "--prefill-chunk-tokens": args.prefill_chunk_tokens,
        "--quantize": args.quantize,
    }
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(
            f"{', '.join(bad)} have no effect on the cached decode path"
        )
    if args.kv_dtype:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=args.kv_dtype)
    if args.cache_layout:
        cfg = dataclasses.replace(cfg, decode_cache_layout=args.cache_layout)
    if args.decode_unroll:
        # Raises unless --cache-layout stacked accompanied it (config
        # validation): unroll only exists on the stacked depth scan.
        cfg = dataclasses.replace(cfg, decode_unroll_layers=True)
    batch = args.batch or 8
    if args.quick:
        batch = min(batch, 4)
    from pretraining_llm_tpu.generation.generate import decode_bench_workload

    cfg, params, prompt, new_tokens = decode_bench_workload(
        cfg, batch, quick=args.quick
    )
    prompt_len = int(prompt.shape[1])
    # --ragged: serving-shaped batch — per-row prompt lengths spread over
    # [prompt_len/4, prompt_len], decoded in the one lockstep ragged program.
    lengths = None
    if args.ragged:
        import numpy as _np

        rng = _np.random.default_rng(0)
        lengths = rng.integers(
            max(prompt_len // 4, 1), prompt_len + 1, size=batch
        ).astype(_np.int32)

    def run(seed):
        out = generate(
            params, cfg, prompt, new_tokens, jax.random.key(seed),
            temperature=1.0, prompt_lengths=lengths,
        )
        # device_get, not block_until_ready: the latter does not actually
        # synchronize on the tunneled-TPU backend (same protocol as the
        # train bench's loss fetch).
        return jax.device_get(out)

    run(0)  # compile + warm
    t0 = time.perf_counter()
    n_runs = 2 if args.quick else 4
    for s in range(1, n_runs + 1):
        run(s)
    dt = (time.perf_counter() - t0) / n_runs
    tps = batch * new_tokens / dt
    rec = {
        "metric": f"decode_tokens_per_sec_{args.preset}",
        "value": round(tps, 1),
        "unit": "tokens_per_sec",
        "vs_baseline": None,  # the reference publishes no decode numbers
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "ms_per_token_step": round(dt / new_tokens * 1e3, 3),
        "attention": "naive (cached-decode path)",
        "kv_cache_dtype": cfg.kv_cache_dtype,
        "device": jax.devices()[0].device_kind,
    }
    if lengths is not None:
        rec["metric"] += "_ragged"
        rec["prompt_lengths"] = [int(x) for x in lengths]
    if cfg.kv_cache_dtype == "int8":
        rec["metric"] += "_kvint8"  # distinct series vs the bf16-cache baseline
    if cfg.decode_unroll_layers:
        rec["metric"] += "_unroll"  # distinct series vs the rolled-scan baseline
        rec["decode_unroll_layers"] = True
    if cfg.decode_cache_layout == "unstacked":
        rec["metric"] += "_unstacked"  # distinct series vs the stacked layout
        rec["decode_cache_layout"] = "unstacked"
    return rec


def run_kernel_bench(args: argparse.Namespace) -> dict:
    """Ragged paged-attention kernel microbench: the four variants the
    speed push pits against each other — XLA gather reference, classic
    single-pass ragged kernel, FA2 KV-split partitioning, and AMLA
    MUL-by-ADD rescaling — swept over (B, T, pages, window, int8) lanes.

    Runs on whatever backend is up: on TPU the numbers are compiled-
    kernel wall times; anywhere else the kernel runs in interpret mode
    and the record carries ``cpu_interpret: true`` — relative variant
    ordering under interpret is NOT hardware truth, but the record keeps
    the series alive (and the identity grid honest) while the TPU
    backend is unreachable. The headline value is the classic ragged
    kernel's ms on the reference lane; per-variant and per-lane times
    ride the same record.
    """
    import numpy as np

    # Every other mode's knob is rejected, not ignored (same discipline
    # as the decode guard): the sweep is shape-driven, so a --batch or
    # --kv-dtype that silently did nothing would mislabel the record.
    noop = {
        "--batch": args.batch, "--attention": args.attention,
        "--remat": args.remat, "--ce": args.ce,
        "--optimizer": args.optimizer, "--unroll": args.unroll,
        "--block-q": args.block_q, "--block-kv": args.block_kv,
        "--steps-per-sched": args.steps_per_sched,
        "--context": args.context, "--paged-attn": args.paged_attn,
        "--spec-draft": args.spec_draft, "--no-pipeline": args.no_pipeline,
        "--pipeline-depth": args.pipeline_depth,
        "--admit-batch": args.admit_batch,
        "--grad-dtype": args.grad_dtype, "--ragged": args.ragged,
        "--kv-dtype": args.kv_dtype,
        "--cache-layout": args.cache_layout,
        "--decode-unroll": args.decode_unroll,
        "--prefix-cache": args.prefix_cache,
        "--prefix-pool-size": args.prefix_pool_size,
        "--prefix-len": args.prefix_len,
        "--prefill-chunk-tokens": args.prefill_chunk_tokens,
        "--quantize": args.quantize,
    }
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(
            f"{', '.join(bad)} have no effect on the kernel microbench"
        )

    import jax
    import jax.numpy as jnp

    from pretraining_llm_tpu.ops.pallas_ragged import (
        ragged_gather_attention,
        ragged_paged_attention,
    )

    interpret = jax.devices()[0].platform != "tpu"
    h, g, d, bs = 4, 2, 32, 8
    # (name, B, T, pages, window, int8) — T mixes decode-like (small) and
    # chunk-like (T) q_lens inside each lane, pages sets the per-row scan
    # length the KV split partitions.
    lanes = [
        ("mixed", 4, 8, 8, 0, False),
        ("long_row", 2, 4, 16, 0, False),
        ("windowed", 4, 8, 8, 24, False),
        ("int8", 4, 8, 8, 0, True),
    ]
    if args.quick:
        lanes = lanes[:1]
    reps = 2 if args.quick else 4
    gather_jit = jax.jit(
        ragged_gather_attention, static_argnames=("window",)
    )

    def _time(fn):
        jax.block_until_ready(fn())  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn())
        return (time.perf_counter() - t0) / reps * 1e3

    rng = np.random.default_rng(0)
    lane_recs = []
    for name, b, t, pages, window, int8 in lanes:
        n_blocks = pages * 3
        q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        kp = jnp.asarray(
            rng.normal(size=(n_blocks, bs, g, d)), jnp.float32
        )
        vp = jnp.asarray(
            rng.normal(size=(n_blocks, bs, g, d)), jnp.float32
        )
        tbl = jnp.asarray(
            rng.integers(1, n_blocks, size=(b, pages)), jnp.int32
        )
        cap = pages * bs
        seq = jnp.asarray(
            rng.integers(cap // 2, cap - t, size=(b,)), jnp.int32
        )
        # Ragged q_lens: half the rows decode-like (1), half chunk-like.
        ql = jnp.asarray(
            [1 if i % 2 == 0 else t for i in range(b)], jnp.int32
        )
        scales = {}
        if int8:
            amax = jnp.max(jnp.abs(kp), axis=-1, keepdims=True)
            ks = jnp.where(amax == 0, 1.0, amax)
            kp = jnp.clip(
                jnp.round(kp / ks * 127.0), -127, 127
            ).astype(jnp.int8)
            amax = jnp.max(jnp.abs(vp), axis=-1, keepdims=True)
            vs = jnp.where(amax == 0, 1.0, amax)
            vp = jnp.clip(
                jnp.round(vp / vs * 127.0), -127, 127
            ).astype(jnp.int8)
            scales = {"k_scale": ks, "v_scale": vs}
        common = dict(window=window, **scales)
        splits = max(2, min(4, pages // 2))
        variants = {
            "gather": lambda: gather_jit(
                q, kp, vp, tbl, seq, ql, **common
            ),
            "ragged": lambda: ragged_paged_attention(
                q, kp, vp, tbl, seq, ql, kv_splits=1, **common
            ),
            "ragged_split": lambda: ragged_paged_attention(
                q, kp, vp, tbl, seq, ql, kv_splits=splits, **common
            ),
            "ragged_amla": lambda: ragged_paged_attention(
                q, kp, vp, tbl, seq, ql, kv_splits=1, amla=True, **common
            ),
        }
        times = {k: round(_time(fn), 3) for k, fn in variants.items()}
        lane_recs.append({
            "lane": name, "B": b, "T": t, "pages": pages,
            "window": window, "int8": int8, "kv_splits": splits,
            "ms": times,
        })
        _stamp(f"kernel lane {name}: {times}")
    ref = lane_recs[0]
    return {
        "metric": "kernel_ragged_microbench_ms",
        "value": ref["ms"]["ragged"],
        "unit": "ms",
        "vs_baseline": None,
        # CPU interpret numbers are NOT hardware perf — consumers
        # (bank_results, BASELINE tables) must label the series.
        "cpu_interpret": interpret,
        "device": jax.devices()[0].device_kind,
        "variants": dict(ref["ms"]),
        "lanes": lane_recs,
        "shape": {"heads": h, "kv_heads": g, "head_dim": d,
                  "block_size": bs},
    }


_QUANT_SUFFIX = {"int8": "_q8", "int8-kv": "_q8kv"}


def _quantization_block(eng, raw_params) -> dict:
    """Model-bytes / KV-bytes-per-token estimate block for serving records:
    the capacity-planning numbers a quantize before/after comparison needs
    next to its tok/s and TPOT. ``raw_params`` is the pre-quantize tree so
    the bf16 model footprint rides the same record."""
    from pretraining_llm_tpu.models import quantize as quantize_mod

    info = eng.pool_info()
    bsz = info["block_size"]
    return {
        "quantize": info["quantize"],
        "kv_dtype": info["kv_dtype"],
        "kv_scale_dtype": info["kv_scale_dtype"],
        "model_bytes": quantize_mod.param_bytes(eng.params),
        "model_bytes_unquantized": quantize_mod.param_bytes(raw_params),
        "kv_pool_bytes": info["pool_bytes"],
        "kv_bytes_per_block": info["bytes_per_block"],
        "kv_bytes_per_token": round(info["bytes_per_block"] / bsz, 1),
    }


def run_serving_bench(args: argparse.Namespace) -> dict:
    """Continuous-batching throughput: mixed-length requests served through
    the paged engine (generation.serving.ServingEngine). Measures what an
    online deployment sustains — admission, prefill, multi-step decode
    windows, reaping — not just the steady-state decode scan (--mode
    decode). The reference has no serving path at all (batch-1 fixed-count
    generate, SURVEY §3.2)."""
    import numpy as _np

    import jax

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.generation.generate import decode_bench_workload
    from pretraining_llm_tpu.generation.serving import ServingEngine

    noop = {
        "--attention": args.attention, "--remat": args.remat, "--ce": args.ce,
        "--optimizer": args.optimizer, "--unroll": args.unroll,
        "--block-q": args.block_q, "--block-kv": args.block_kv,
        "--ragged": args.ragged, "--decode-unroll": args.decode_unroll,
        "--context": args.context, "--grad-dtype": args.grad_dtype,
        # Hot-prefix traffic shaping lives in the SLO loadgen; this
        # mode's fixed request set would silently ignore it.
        "--prefix-pool-size": args.prefix_pool_size,
        "--prefix-len": args.prefix_len,
    }
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(f"{', '.join(bad)} have no effect on the serving path")

    cfg = get_preset(args.preset).model
    if args.kv_dtype:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=args.kv_dtype)
    if args.paged_attn:
        cfg = dataclasses.replace(cfg, paged_attention_impl=args.paged_attn)
    if args.cache_layout:
        # Controls the POOL container too (make_paged_kv_pool honors
        # decode_cache_layout) — 'stacked' reproduces the historical
        # serving series.
        cfg = dataclasses.replace(cfg, decode_cache_layout=args.cache_layout)
    max_batch = args.batch or 8
    if args.quick:
        max_batch = min(max_batch, 4)
    # Same canonical model/params as the decode bench; its prompt_len
    # bounds the request lengths so any context fits (the returned dense
    # prompt itself is unused — serving builds a mixed-length set).
    cfg, params, canon_prompt, new_tokens = decode_bench_workload(
        cfg, max_batch, quick=args.quick
    )
    prompt_len = int(canon_prompt.shape[1])
    block_size = min(64, cfg.context_length)
    n_requests = 3 * max_batch
    rng = _np.random.default_rng(0)
    lengths = rng.integers(max(1, prompt_len // 4), prompt_len + 1,
                           size=n_requests)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=int(n)).tolist() for n in lengths
    ]
    pages_per_req = -(-(prompt_len + new_tokens) // block_size)
    n_blocks = max_batch * pages_per_req + max_batch + 1

    sps = args.steps_per_sched or 8
    depth = args.pipeline_depth or 2

    spec = {}
    if args.spec_draft == "self":
        spec = dict(draft_params=params, draft_cfg=cfg, spec_k=args.spec_k)

    def serve():
        eng = ServingEngine(
            params, cfg, max_batch=max_batch, n_blocks=n_blocks,
            block_size=block_size,
            # Spec serving is temperature-only; greedy keeps the self-
            # draft acceptance at its upper bound. Plain serving keeps
            # the historical temperature=1.0 series.
            temperature=0.0 if spec else 1.0,
            steps_per_sched=sps, pipeline_depth=depth,
            admit_batch=args.admit_batch,
            prefix_cache=args.prefix_cache,
            prefill_chunk_tokens=args.prefill_chunk_tokens,
            quantize=args.quantize or "none", **spec,
        )
        rids = [eng.submit(p, new_tokens) for p in prompts]
        out = eng.run(pipeline=not args.no_pipeline)
        return sum(len(out[r]) for r in rids), eng.stats, eng

    serve()  # compile + warm (prefill buckets + the window program)
    t0 = time.perf_counter()
    n_tok, stats, eng = serve()
    dt = time.perf_counter() - t0
    # The fraction of the serving wall the host spent BLOCKED on a
    # window readback — the quantity the in-flight queue exists to
    # shrink (0 would mean the device never waited on the host sync).
    reaped = stats.get("windows_reaped", 0)
    blocked_s = stats.get("host_blocked_s", 0.0)
    rec = {
        "metric": f"serving_tokens_per_sec_{args.preset}",
        "value": round(n_tok / dt, 1),
        "unit": "generated_tokens_per_sec",
        "vs_baseline": None,  # the reference has no serving stack
        "max_batch": max_batch,
        "n_requests": n_requests,
        "new_tokens_per_request": new_tokens,
        "steps_per_sched": sps,
        "pipeline": not args.no_pipeline,
        "pipeline_depth": depth if not args.no_pipeline else 0,
        "admit_batch": args.admit_batch,
        "host_blocked_frac": round(blocked_s / dt, 4) if dt > 0 else None,
        "host_blocked_ms_per_window": (
            round(1e3 * blocked_s / reaped, 3) if reaped else None
        ),
        "paged_attention_impl": cfg.paged_attention_impl,
        "block_size": block_size,
        "n_blocks": n_blocks,
        "kv_cache_dtype": cfg.kv_cache_dtype,
        "engine_stats": stats,
        "quantization": _quantization_block(eng, params),
        "wall_s": round(dt, 2),
        "device": jax.devices()[0].device_kind,
    }
    if args.quantize in _QUANT_SUFFIX:
        rec["metric"] += _QUANT_SUFFIX[args.quantize]  # distinct series
    if spec:
        rec["metric"] += "_spec"  # self-draft upper-bound series
        rec["spec_k"] = args.spec_k
    if args.prefix_cache:
        rec["metric"] += "_pfx"  # distinct series vs the cache-off baseline
        rec["prefix_cache"] = True
    if args.prefill_chunk_tokens:
        rec["metric"] += "_chunked"  # distinct series vs monolithic prefill
        rec["prefill_chunk_tokens"] = args.prefill_chunk_tokens
    if cfg.kv_cache_dtype == "int8":
        rec["metric"] += "_kvint8"
    if cfg.decode_cache_layout == "unstacked":
        rec["metric"] += "_unstacked"  # distinct series vs stacked pools
        rec["decode_cache_layout"] = "unstacked"
    return rec


def run_serving_slo_bench(args: argparse.Namespace) -> dict:
    """Online serving latency under load: seeded Poisson arrivals through
    the frontend EngineLoop (the same continuous loop the HTTP gateway
    drives), reporting p50/p99 TTFT, TPOT and e2e plus goodput-under-SLO —
    completed requests that met the SLO bounds, per second. --mode serving
    measures what the engine sustains offline; this measures what a CLIENT
    experiences while requests arrive mid-decode."""
    import jax

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.frontend.admission import AdmissionController
    from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
    from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_engine_loop
    from pretraining_llm_tpu.generation.generate import decode_bench_workload
    from pretraining_llm_tpu.generation.serving import ServingEngine

    noop = {
        "--attention": args.attention, "--remat": args.remat, "--ce": args.ce,
        "--optimizer": args.optimizer, "--unroll": args.unroll,
        "--block-q": args.block_q, "--block-kv": args.block_kv,
        "--ragged": args.ragged, "--decode-unroll": args.decode_unroll,
        "--grad-dtype": args.grad_dtype,
        "--spec-draft": args.spec_draft, "--no-pipeline": args.no_pipeline,
    }
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(f"{', '.join(bad)} have no effect on the serving-slo path")

    cfg = get_preset(args.preset).model
    if args.context:
        # Long-prompt workloads: stretch the context (and with it the
        # loadgen's prompt-length ceiling below). Positional params are
        # re-initialized for the new length — this is a random-init
        # microbench, not a checkpoint eval.
        cfg = dataclasses.replace(cfg, context_length=args.context)
    if args.kv_dtype:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=args.kv_dtype)
    if args.paged_attn:
        cfg = dataclasses.replace(cfg, paged_attention_impl=args.paged_attn)
    if args.cache_layout:
        cfg = dataclasses.replace(cfg, decode_cache_layout=args.cache_layout)
    max_batch = args.batch or 8
    if args.quick:
        max_batch = min(max_batch, 4)
    cfg, params, canon_prompt, new_tokens = decode_bench_workload(
        cfg, max_batch, quick=args.quick
    )
    prompt_len = int(canon_prompt.shape[1])
    block_size = min(64, cfg.context_length)
    n_requests = args.n_requests or 3 * max_batch
    # Hot-prefix scenario: each request prepends a shared prefix drawn
    # zipf-skewed from a fixed pool — the workload the prefix cache is
    # built for. Shrink the private-prompt range if the prefix would
    # otherwise push requests past the context window.
    pfx_pool = args.prefix_pool_size
    pfx_len = 0
    if pfx_pool:
        # Shared prefixes only pay off when they span whole pool blocks;
        # with small contexts the default 64-token pages would make every
        # prompt a single block (the cache caps hits one token short of
        # the prompt, so a one-block prompt can never hit). Shrink pages
        # so a prefix + private prompt + generation spans several.
        block_size = min(block_size, max(8, cfg.context_length // 8))
        pfx_len = args.prefix_len or 2 * block_size
        room = cfg.context_length - new_tokens - pfx_len
        if room < 1:
            raise ValueError(
                f"--prefix-len {pfx_len} leaves no room for prompts "
                f"(context {cfg.context_length}, new_tokens {new_tokens})"
            )
        prompt_len = min(prompt_len, room)
    if args.prefill_chunk_tokens:
        # The chunked-vs-monolithic comparison is defined on a LONG-prompt
        # + decode mix: stretch the arrival mix's ceiling to the full
        # context so a monolithic prefill genuinely convoys the decode
        # rows (and queued short requests) behind it. The short end of
        # the mix below stays at prompt_len // 4, so decode-dominated
        # requests still share the engine with the long prefills.
        prompt_len = max(
            prompt_len, cfg.context_length - new_tokens - pfx_len
        )
    pages_per_req = -(-(pfx_len + prompt_len + new_tokens) // block_size)
    n_blocks = max_batch * pages_per_req + max_batch + 1

    sps = args.steps_per_sched or 8
    depth = args.pipeline_depth or 2

    spec = LoadSpec(
        n_requests=n_requests, mode="open", rate_rps=args.rate_rps,
        vocab_size=cfg.vocab_size,
        prompt_len_min=max(1, prompt_len // 4), prompt_len_max=prompt_len,
        max_new_min=new_tokens, max_new_max=new_tokens,
        slo_ttft_s=args.slo_ttft_s, slo_e2e_s=args.slo_e2e_s, seed=0,
        prefix_pool_size=pfx_pool, prefix_len=pfx_len,
        prefix_zipf=args.prefix_zipf,
    )

    def run_once(chunk_tokens: int):
        eng = ServingEngine(
            params, cfg, max_batch=max_batch, n_blocks=n_blocks,
            block_size=block_size, temperature=0.0,
            steps_per_sched=sps, pipeline_depth=depth,
            admit_batch=args.admit_batch,
            prefix_cache=args.prefix_cache,
            prefill_chunk_tokens=chunk_tokens,
            quantize=args.quantize or "none",
        )
        admission = AdmissionController(max_queue_depth=4 * max_batch)
        loop = EngineLoop(eng, admission=admission)
        with loop:
            # Warm the compiled programs (prefill buckets + the window
            # program) outside the measured window, like the other modes'
            # warmup pass.
            warm = loop.submit([1] * prompt_len, new_tokens)
            warm.result()
            report = run_engine_loop(loop, spec)
        return eng, admission, loop, report

    baseline = None
    if args.prefill_chunk_tokens:
        # Monolithic-prefill baseline over the SAME seeded arrival process
        # first — the before/after TTFT-p99 comparison the chunk lane
        # exists for (head-of-line prefill blocking vs. interleaving).
        _, _, _, base_report = run_once(0)
        baseline = base_report.summary()
    eng, admission, loop, report = run_once(args.prefill_chunk_tokens)
    s = report.summary()
    rec = {
        "metric": f"serving_slo_goodput_{args.preset}",
        "value": round(s["goodput_rps"], 3),
        "unit": "slo_ok_requests_per_sec",
        "vs_baseline": None,  # the reference has no serving stack
        "slo_attainment": round(s["slo_attainment"], 4),
        "counts": s["counts"],
        "n_requests": n_requests,
        "rate_rps": args.rate_rps,
        "slo_ttft_s": args.slo_ttft_s,
        "slo_e2e_s": args.slo_e2e_s,
        "ttft_p50_s": round(s["ttft"]["p50"], 4),
        "ttft_p99_s": round(s["ttft"]["p99"], 4),
        "tpot_p50_s": round(s["tpot"]["p50"], 5),
        "e2e_p50_s": round(s["e2e"]["p50"], 4),
        "e2e_p99_s": round(s["e2e"]["p99"], 4),
        "throughput_tok_s": round(s["throughput_tok_s"], 1),
        "max_batch": max_batch,
        "new_tokens_per_request": new_tokens,
        "steps_per_sched": sps,
        "pipeline_depth": depth,
        "block_size": block_size,
        "n_blocks": n_blocks,
        "wall_s": round(report.wall_s, 2),
        "quantization": _quantization_block(eng, params),
        "device": jax.devices()[0].device_kind,
    }
    if args.quantize in _QUANT_SUFFIX:
        rec["metric"] += _QUANT_SUFFIX[args.quantize]  # distinct series
    if args.context:
        rec["metric"] += f"_ctx{args.context}"  # distinct series per context
    if pfx_pool:
        rec["metric"] += "_hotprefix"  # distinct series vs i.i.d. prompts
        rec["prefix_pool_size"] = pfx_pool
        rec["prefix_len"] = pfx_len
        rec["prefix_zipf"] = args.prefix_zipf
    if args.prefix_cache:
        rec["metric"] += "_pfx"  # distinct series vs the cache-off baseline
        hit_tok = eng.stats.get("prefix_cache_hit_tokens", 0)
        prefill_tok = eng.stats.get("prefill_tokens", 0)
        rec["prefix_cache"] = {
            "hits": eng.stats.get("prefix_cache_hits", 0),
            "misses": eng.stats.get("prefix_cache_misses", 0),
            "hit_tokens": hit_tok,
            "prefill_tokens": prefill_tok,
            "evicted_blocks": eng.stats.get("prefix_cache_evicted_blocks", 0),
            # Fraction of prompt tokens served from cache instead of
            # prefill — the headline win on hot-prefix traffic.
            "prefill_reduction": (
                round(hit_tok / (hit_tok + prefill_tok), 4)
                if hit_tok + prefill_tok else 0.0
            ),
            "cached_tokens_total": s["cached_tokens_total"],
        }
    if args.prefill_chunk_tokens:
        rec["metric"] += "_chunked"  # distinct series vs monolithic prefill
        rec["prefill_chunk_tokens"] = args.prefill_chunk_tokens
        base_ttft = baseline["ttft"]["p99"]
        base_tpot = baseline["tpot"]["p50"]
        rec["chunked_prefill"] = {
            "prefill_chunks": eng.stats.get("prefill_chunks", 0),
            "prefill_chunk_tokens": eng.stats.get("prefill_chunk_tokens", 0),
            "chunk_windows_interleaved": eng.stats.get(
                "chunk_windows_interleaved", 0
            ),
            "chunk_windows_dedicated": eng.stats.get(
                "chunk_windows_dedicated", 0
            ),
            "chunk_deferrals": eng.stats.get("chunk_deferrals", 0),
            # Before/after on the same seeded arrivals (the baseline pass
            # above ran chunking OFF): the headline TTFT-tail win, plus
            # the TPOT numbers guarding against decode regression.
            "ttft_p99_monolithic_s": round(base_ttft, 4),
            "ttft_p99_chunked_s": round(s["ttft"]["p99"], 4),
            "ttft_p99_reduction": (
                round(1.0 - s["ttft"]["p99"] / base_ttft, 4)
                if base_ttft > 0 else None
            ),
            "tpot_p50_monolithic_s": round(base_tpot, 5),
            "tpot_p50_chunked_s": round(s["tpot"]["p50"], 5),
            "tpot_p50_regression": (
                round(s["tpot"]["p50"] / base_tpot - 1.0, 4)
                if base_tpot > 0 else None
            ),
        }
    # Preemption/rework accounting next to the prefix_cache block: how
    # much of the run's prefill was recompute-on-resume, and what the
    # frontend shed on deadline grounds (admission rejects vs. mid-flight
    # expiries) — the counters the capacity report attributes offline.
    rec["preemption"] = {
        "preemptions": eng.stats.get("preemptions", 0),
        "preempted_tokens_recomputed": eng.stats.get(
            "preempted_tokens_recomputed", 0
        ),
        "deadline_shed": {
            "admission": admission.stats.get("rejected_infeasible", 0),
            "inflight": loop.counters.get("expired", 0),
        },
    }
    return rec


def run_serving_fleet_bench(args: argparse.Namespace) -> dict:
    """Online latency under load through the N-replica fleet Router while
    a scenario disturbance runs: 'kill' crashes replica 0 mid-burst (the
    router ejects it, redrives its in-flight requests to survivors and
    relaunches it), 'rolling' drains/restores every replica in turn,
    'hotprefix' sends zipf-skewed shared-prefix traffic to measure
    prefix-affinity placement, 'upgrade' rolls a probe-vetted weight
    upgrade across every replica under load, 'proc-kill' runs the
    fleet as out-of-process workers and SIGKILLs one mid-burst, and
    'partition' blackholes an out-of-process worker's socket mid-decode
    (the lease detects it, redrive moves its work, a scheduled heal
    floods the fence filter with stale frames). Reports goodput plus
    the fleet-only numbers: redrive count/cost, ejects, per-replica
    request spread — and for 'partition' the zero-lost /
    zero-duplicate invariants plus lease/fence counters."""
    import jax

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.frontend.admission import AdmissionController
    from pretraining_llm_tpu.frontend.loadgen import (
        FleetAction, LoadSpec, rolling_restart_plan, run_engine_loop,
        run_fleet_plan,
    )
    from pretraining_llm_tpu.frontend.replica import Replica
    from pretraining_llm_tpu.frontend.router import Router
    from pretraining_llm_tpu.generation.generate import decode_bench_workload
    from pretraining_llm_tpu.generation.serving import ServingEngine
    from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

    noop = {
        "--attention": args.attention, "--remat": args.remat, "--ce": args.ce,
        "--optimizer": args.optimizer, "--unroll": args.unroll,
        "--block-q": args.block_q, "--block-kv": args.block_kv,
        "--ragged": args.ragged, "--decode-unroll": args.decode_unroll,
        "--context": args.context, "--grad-dtype": args.grad_dtype,
        "--spec-draft": args.spec_draft, "--no-pipeline": args.no_pipeline,
        # Per-replica engine knobs not yet plumbed through the fleet
        # launcher; rejected rather than silently ignored.
        "--prefill-chunk-tokens": args.prefill_chunk_tokens,
        "--quantize": args.quantize,
    }
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(
            f"{', '.join(bad)} have no effect on the serving-fleet path"
        )
    if args.replicas < 2:
        raise ValueError("serving-fleet mode needs --replicas >= 2")

    cfg = get_preset(args.preset).model
    if args.kv_dtype:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=args.kv_dtype)
    if args.paged_attn:
        cfg = dataclasses.replace(cfg, paged_attention_impl=args.paged_attn)
    if args.cache_layout:
        cfg = dataclasses.replace(cfg, decode_cache_layout=args.cache_layout)
    max_batch = args.batch or 4  # per replica; the fleet multiplies it
    if args.quick:
        max_batch = min(max_batch, 4)
    cfg, params, canon_prompt, new_tokens = decode_bench_workload(
        cfg, max_batch, quick=args.quick
    )
    prompt_len = int(canon_prompt.shape[1])
    block_size = min(64, cfg.context_length)
    n_requests = args.n_requests or 4 * max_batch * args.replicas
    pfx_pool = args.prefix_pool_size
    pfx_len = 0
    if args.fleet_scenario in ("hotprefix", "disagg", "decode-sat"):
        pfx_pool = pfx_pool or 2 * args.replicas
        block_size = min(block_size, max(8, cfg.context_length // 8))
        pfx_len = args.prefix_len or 2 * block_size
        room = cfg.context_length - new_tokens - pfx_len
        if room < 1:
            raise ValueError(
                f"--prefix-len {pfx_len} leaves no room for prompts "
                f"(context {cfg.context_length}, new_tokens {new_tokens})"
            )
        prompt_len = min(prompt_len, room)
    pages_per_req = -(-(pfx_len + prompt_len + new_tokens) // block_size)
    n_blocks = max_batch * pages_per_req + max_batch + 1
    sps = args.steps_per_sched or 8
    depth = args.pipeline_depth or 2

    # The disagg scenario is meaningless without a prefix cache (there
    # would be nothing to snapshot) and enables kv_checksum so migrated
    # pages carry + verify their integrity identity, as in production.
    # decode-sat reuses the full disagg topology (replica 0 = prefill
    # tier) and layers a live SLO engine + 4x offered load on top.
    decode_sat = args.fleet_scenario == "decode-sat"
    disagg = args.fleet_scenario == "disagg" or decode_sat

    def make_engine():
        return ServingEngine(
            params, cfg, max_batch=max_batch, n_blocks=n_blocks,
            block_size=block_size, temperature=0.0,
            steps_per_sched=sps, pipeline_depth=depth,
            admit_batch=args.admit_batch,
            prefix_cache=args.prefix_cache or disagg,
            kv_checksum=disagg,
        )

    # decode-sat: the live SLO engine subscribes to the fleet bus; every
    # replica-tagged terminal feeds its per-replica rolling sketches. The
    # window is sized past the whole burst so nothing rotates out and the
    # tier comparison below covers every request.
    bus = slo = None
    if decode_sat:
        from pretraining_llm_tpu.observability.events import EventBus
        from pretraining_llm_tpu.observability.slo import (
            SLOEngine, default_slo_classes,
        )

        bus = EventBus()
        slo = SLOEngine(
            classes=default_slo_classes(
                ttft_s=args.slo_ttft_s, e2e_s=args.slo_e2e_s
            ),
            bus=bus, window_s=600.0,
        )

    faults = None
    kill_at = max(2, n_requests // (3 * args.replicas))
    if args.fleet_scenario == "kill":
        # Crash replica 0 when it accepts its (n/3)th request — mid-burst
        # by construction, deterministic under the seeded schedule.
        faults = ServingFaultInjector(f"replica_crash@req{kill_at}:r0")

    if args.fleet_scenario in ("proc-kill", "partition"):
        # Out-of-process fleet: each replica is a worker subprocess that
        # inits the SAME params from the same (preset, init_seed=0) the
        # parent's decode_bench_workload used, so redriven requests land
        # on bit-identical weights. worker_kill is a real SIGKILL,
        # executed by the parent injector right after replica 0 acks its
        # kill_at'th submit; partition blackholes replica 0's socket at
        # the same trigger (detection is then the lease, not the fd).
        from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica

        fault_kind = (
            "partition" if args.fleet_scenario == "partition"
            else "worker_kill"
        )
        faults = ServingFaultInjector(f"{fault_kind}@req{kill_at}:r0")
        worker_spec = {
            "preset": args.preset,
            "init_seed": 0,
            "model_overrides": {
                "attention_impl": cfg.attention_impl,
                "sequence_parallel": cfg.sequence_parallel,
                "kv_cache_dtype": cfg.kv_cache_dtype,
                "paged_attention_impl": cfg.paged_attention_impl,
                "decode_cache_layout": cfg.decode_cache_layout,
            },
            "engine": {
                "max_batch": max_batch, "n_blocks": n_blocks,
                "block_size": block_size, "temperature": 0.0,
                "steps_per_sched": sps, "pipeline_depth": depth,
                "admit_batch": args.admit_batch,
                "prefix_cache": args.prefix_cache,
            },
            "admission": {"max_queue_depth": 4 * max_batch},
        }
        # The partition drill needs a short lease so detection (and thus
        # redrive) lands well inside the burst; proc-kill keeps the
        # default stdin-orphan + conn-EOF detection path.
        rep_kw = (
            {"lease_s": 1.0} if args.fleet_scenario == "partition" else {}
        )
        replicas = [
            RemoteReplica(i, worker_spec, fault_injector=faults, **rep_kw)
            for i in range(args.replicas)
        ]
    else:
        replicas = [
            Replica(
                i, make_engine, fault_injector=faults, bus=bus,
                # disagg: replica 0 is the dedicated prefill tier (no
                # client traffic), everyone else decodes migrated pages.
                role=(
                    ("prefill" if i == 0 else "decode") if disagg
                    else "both"
                ),
                admission_factory=lambda reg: AdmissionController(
                    max_queue_depth=4 * max_batch, registry=reg
                ),
            )
            for i in range(args.replicas)
        ]
    router = Router(
        replicas,
        admission=AdmissionController(
            max_queue_depth=4 * max_batch * args.replicas
        ),
        bus=bus, slo=slo,
        # For the partition drill the backoff must outlast the scheduled
        # heal: relaunch tears down the blackholed gate, and with it the
        # kernel backlog whose post-heal flush exercises the fence
        # filter. Everywhere else a fast relaunch is the point.
        eject_backoff_s=(
            3.0 if args.fleet_scenario == "partition" else 0.2
        ),
        # The upgrade drill vets new weights against golden probes before
        # they take traffic; a pinned probe set requires the sentinel to
        # be on (interval far beyond the burst keeps it out of the way).
        probe_interval_s=(
            60.0 if args.fleet_scenario == "upgrade" else 0.0
        ),
    )
    spec = LoadSpec(
        n_requests=n_requests, mode="open",
        # decode-sat: offered load deliberately outruns the decode
        # tier's service rate so its queues build — arrivals stay open
        # loop, so the backlog shows up as queue-wait, not lower rps.
        rate_rps=args.rate_rps * (4.0 if decode_sat else 1.0),
        vocab_size=cfg.vocab_size,
        prompt_len_min=max(1, prompt_len // 4), prompt_len_max=prompt_len,
        max_new_min=new_tokens, max_new_max=new_tokens,
        slo_ttft_s=args.slo_ttft_s, slo_e2e_s=args.slo_e2e_s, seed=0,
        prefix_pool_size=pfx_pool, prefix_len=pfx_len,
        prefix_zipf=args.prefix_zipf,
    )
    router.start()
    try:
        # Warm each replica's compiled programs outside the measured window.
        warm = [
            rep.submit([1] * prompt_len, new_tokens) for rep in replicas
        ]
        for w in warm:
            w.result()
        plan_th = None
        if args.fleet_scenario == "rolling":
            est_wall = n_requests / args.rate_rps
            plan_th = run_fleet_plan(
                router,
                rolling_restart_plan(
                    args.replicas,
                    start_s=0.25 * est_wall,
                    step_s=max(0.5, 0.5 * est_wall / args.replicas),
                ),
            )
        elif args.fleet_scenario == "upgrade":
            # Probe-vetted rolling upgrade of every replica, staggered
            # across the middle of the burst (update=None relaunches the
            # same factory — the vetting machinery still runs in full).
            est_wall = n_requests / args.rate_rps
            plan_th = run_fleet_plan(
                router,
                [
                    FleetAction(
                        at_s=0.25 * est_wall
                        + i * max(0.5, 0.4 * est_wall / args.replicas),
                        kind="upgrade", replica=i,
                    )
                    for i in range(args.replicas)
                ],
            )
        elif args.fleet_scenario == "partition":
            # Heal replica 0 after the lease has expired and the router
            # has redriven + ejected (fence bumped): the flushed backlog
            # then arrives stamped with the old generation and every
            # frame must be counted and dropped, never streamed.
            kill_est = kill_at * args.replicas / args.rate_rps
            plan_th = run_fleet_plan(
                router,
                [FleetAction(at_s=kill_est + 2.5, kind="heal", replica=0)],
            )
        report = run_engine_loop(router, spec)
        if plan_th is not None:
            plan_th.join(timeout=60.0)
        per_replica = {rep.index: rep.submits for rep in replicas}
        counters = dict(router.counters)
        lease_expiries = sum(
            int(getattr(rep, "_c_lease", None).value)
            if getattr(rep, "_c_lease", None) is not None else 0
            for rep in replicas
        )
        fenced_frames = sum(
            int(getattr(rep, "_c_fenced", None).value)
            if getattr(rep, "_c_fenced", None) is not None else 0
            for rep in replicas
        )
        # Snapshot the live surfaces while the fleet is still up:
        # fleet_health() polls each replica's health_pull.
        slo_snap = slo.snapshot() if slo is not None else None
        fleet_health = router.fleet_health() if decode_sat else None
    finally:
        router.stop()
    s = report.summary()
    # Zero-lost invariant: every scheduled request must come back with SOME
    # terminal outcome (done/expired/rejected/error), disturbance or not.
    lost = spec.n_requests - len(report.outcomes)
    rec = {
        "metric": f"serving_fleet_{args.fleet_scenario}_{args.preset}",
        "value": round(s["goodput_rps"], 3),
        "unit": "slo_ok_requests_per_sec",
        "vs_baseline": None,  # the reference has no serving stack
        "scenario": args.fleet_scenario,
        "replicas": args.replicas,
        "slo_attainment": round(s["slo_attainment"], 4),
        "counts": s["counts"],
        "n_requests": n_requests,
        "rate_rps": args.rate_rps,
        "redrives_total": s["redrives_total"],
        "router": {
            "redrives": counters.get("redrives", 0),
            "ejects": counters.get("ejects", 0),
            "brownout_shed": counters.get("brownout_shed", 0),
            "errors": counters.get("errors", 0),
            "relaunches": counters.get("relaunches", 0),
            "upgrades": counters.get("upgrades", 0),
            "upgrades_refused": counters.get("upgrades_refused", 0),
        },
        "replica_mode": (
            "process"
            if args.fleet_scenario in ("proc-kill", "partition")
            else "inproc"
        ),
        "per_replica_submits": per_replica,
        "lost_requests": lost,
        "ttft_p50_s": round(s["ttft"]["p50"], 4),
        "ttft_p99_s": round(s["ttft"]["p99"], 4),
        "e2e_p50_s": round(s["e2e"]["p50"], 4),
        "e2e_p99_s": round(s["e2e"]["p99"], 4),
        "throughput_tok_s": round(s["throughput_tok_s"], 1),
        "max_batch_per_replica": max_batch,
        "new_tokens_per_request": new_tokens,
        "steps_per_sched": sps,
        "pipeline_depth": depth,
        "block_size": block_size,
        "n_blocks": n_blocks,
        "wall_s": round(report.wall_s, 2),
        "device": jax.devices()[0].device_kind,
    }
    if args.fleet_scenario in ("hotprefix", "disagg", "decode-sat"):
        rec["prefix_pool_size"] = pfx_pool
        rec["prefix_len"] = pfx_len
        rec["prefix_zipf"] = args.prefix_zipf
    if disagg:
        # Decode-tier latency under prefill-tier load: every client
        # request is served by a decode replica (the prefill tier takes
        # only migration legs), so the TTFT percentiles above ARE the
        # decode tier's.
        rec["prefill_replicas"] = 1
        rec["kv_migrations"] = counters.get("kv_migrations", 0)
        rec["kv_pages_migrated"] = counters.get("kv_pages_migrated", 0)
        rec["kv_migration_rejects"] = counters.get(
            "kv_migration_rejects", 0
        )
    if args.fleet_scenario == "partition":
        # Partition-heal invariants: nothing lost (every scheduled
        # request got a terminal), nothing duplicated (no done request
        # overran its token budget — the fence filter dropped the
        # blackholed attempt's late frames instead of appending them).
        rec["lease_expiries"] = lease_expiries
        rec["fenced_frames"] = fenced_frames
        rec["duplicate_overruns"] = sum(
            1 for o in report.outcomes
            if o.status == "done" and o.n_tokens > new_tokens
        )
    if decode_sat and slo_snap is not None:
        # Tier comparison from the live sketches. Client requests all
        # terminate on decode replicas; the prefill replica's terminals
        # are the migration legs — its e2e distribution IS the prefill
        # tier's service time. Isolation holds when that distribution
        # stays inside the TTFT objective even though the decode tier's
        # queue wait has blown past it.
        lat = slo_snap["latency"]["replicas"]
        prefill_lat = lat.get("0", {})
        decode_qw_p99 = max(
            (
                s.get("queue_wait_s", {}).get("p99", 0.0)
                for i, s in lat.items() if i != "0"
            ),
            default=0.0,
        )
        prefill_e2e_p99 = prefill_lat.get("e2e_s", {}).get("p99")
        rec["rate_rps_offered"] = spec.rate_rps
        rec["slo_fleet_ttft"] = slo_snap["latency"]["fleet"]["ttft_s"]
        rec["prefill_tier_e2e"] = prefill_lat.get("e2e_s", {})
        rec["prefill_tier_queue"] = prefill_lat.get("queue_wait_s", {})
        rec["decode_tier_queue_p99_s"] = round(decode_qw_p99, 4)
        rec["slo_alerts_fired"] = slo_snap["alerts"]["fired_total"]
        rec["slo_alerts_active"] = len(slo_snap["alerts"]["active"])
        rec["prefill_isolated"] = bool(
            prefill_e2e_p99 is not None
            and prefill_e2e_p99 <= args.slo_ttft_s
        )
        if fleet_health is not None:
            rec["fleet_gauges"] = fleet_health["fleet"].get("gauges", {})
    return rec


def run_trainer_bench(args: argparse.Namespace) -> dict:
    """Tokens/sec of the FULL Trainer loop (synthetic data): step dispatch +
    host sampling + H2D, i.e. what the train CLI actually sustains. The
    delta between --prefetch 0 and --prefetch 2 is the input-pipeline
    overlap win (VERDICT r2 #8's queued on-chip measurement)."""
    noop = {"--ragged": args.ragged, "--kv-dtype": args.kv_dtype,
            "--decode-unroll": args.decode_unroll,
            "--steps-per-sched": args.steps_per_sched,
            "--cache-layout": args.cache_layout,
            "--context": args.context, "--paged-attn": args.paged_attn,
            "--spec-draft": args.spec_draft, "--no-pipeline": args.no_pipeline,
            "--pipeline-depth": args.pipeline_depth,
            "--admit-batch": args.admit_batch,
            "--prefix-cache": args.prefix_cache,
            "--prefix-pool-size": args.prefix_pool_size,
            "--prefix-len": args.prefix_len,
            "--prefill-chunk-tokens": args.prefill_chunk_tokens,
            "--quantize": args.quantize}
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(f"{', '.join(bad)} have no effect on the trainer path")

    import dataclasses as dc

    import jax

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.training.trainer import Trainer
    from pretraining_llm_tpu.utils.hardware import device_peak_flops

    cfg = get_preset(args.preset)
    model = cfg.model
    if model.attention_impl == "ring":
        model = dc.replace(model, attention_impl="flash", sequence_parallel=False)
    if args.remat:
        model = dc.replace(model, remat=args.remat)
    elif model.remat == "none":
        model = dc.replace(model, remat="save_attn")
    if args.ce:
        model = dc.replace(model, ce_impl=args.ce)
    if args.unroll:
        model = dc.replace(model, scan_unroll=args.unroll)
    if args.block_q or args.block_kv:
        model = dc.replace(
            model, flash_block_q=args.block_q, flash_block_kv=args.block_kv
        )
    batch = args.batch or (16 if args.preset == "gpt2-124m" else cfg.train.batch_size)
    steps = 8 if args.quick else max(args.steps, 10)
    if args.quick:
        batch = min(batch, 4)
    data = cfg.data
    if args.prefetch >= 0:
        data = dc.replace(data, prefetch=args.prefetch)
    import tempfile

    cfg = cfg.replace(
        model=model,
        data=data,
        train=dc.replace(
            cfg.train,
            optimizer=args.optimizer or cfg.train.optimizer,
            grad_dtype=args.grad_dtype or cfg.train.grad_dtype,
            batch_size=batch,
            train_steps=steps,
            checkpoint_interval=0,
            # No end-of-run checkpoint: a synchronous full-state write would
            # land INSIDE the timed region (swamping the prefetch delta this
            # mode measures) and leave resumable bench state behind.
            save_final=False,
            checkpoint_dir=tempfile.mkdtemp(prefix="bench_trainer_"),
            eval_interval=0,
            log_interval=max(steps // 2, 1),
            metrics_path="",
        ),
    )
    _stamp(f"trainer bench: prefetch={cfg.data.prefetch}, batch={batch}, steps={steps}")

    class _Quiet:
        def log(self, rec):
            pass

    t = Trainer(cfg, synthetic_data=True, resume=False, logger=_Quiet())
    _stamp("trainer built; warm step + compile")
    t.train(steps=max(2, steps // 4))  # compile + warm
    _stamp("warm done; timing full loop")
    t0 = time.perf_counter()
    last = t.train(steps=steps)
    # The loop's last logged metrics already synced the device.
    dt = time.perf_counter() - t0
    tok_per_sec = batch * model.context_length * steps / dt
    n_dev = jax.device_count()
    mfu = tok_per_sec * model.flops_per_token() / (device_peak_flops() * n_dev)
    return {
        "metric": f"trainer_tokens_per_sec_{cfg.name}",
        "value": round(tok_per_sec / n_dev, 1),
        "unit": "tokens_per_sec_chip",
        "vs_baseline": round(mfu / 0.50, 4),  # same north-star ratio as the mfu record
        "mfu": round(mfu, 4),
        "prefetch": cfg.data.prefetch,
        "batch": batch,
        "steps": steps,
        "loss_finite": bool(last.get("loss", 0.0) == last.get("loss", 0.0)) if last else True,
        "device": jax.devices()[0].device_kind,
        "n_devices": n_dev,
    }


def run_bench(args: argparse.Namespace) -> dict:
    """One in-process bench attempt. May raise / hang on backend trouble —
    the wrapper owns retries and timeouts."""
    from pretraining_llm_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    if args.mode == "decode":
        return run_decode_bench(args)
    if args.mode == "trainer":
        return run_trainer_bench(args)
    if args.mode == "serving":
        return run_serving_bench(args)
    if args.mode == "serving-slo":
        return run_serving_slo_bench(args)
    if args.mode == "serving-fleet":
        return run_serving_fleet_bench(args)
    if args.mode == "kernel":
        return run_kernel_bench(args)

    # Decode-only knobs are REJECTED on the train path (mirror of the
    # decode-mode noop guard): a silently-ignored flag would emit a record
    # indistinguishable from the baseline while the operator believes they
    # measured the override config.
    noop = {"--ragged": args.ragged, "--kv-dtype": args.kv_dtype,
            "--decode-unroll": args.decode_unroll,
            "--steps-per-sched": args.steps_per_sched,
            "--cache-layout": args.cache_layout,
            "--paged-attn": args.paged_attn,
            "--spec-draft": args.spec_draft, "--no-pipeline": args.no_pipeline,
            "--pipeline-depth": args.pipeline_depth,
            "--admit-batch": args.admit_batch,
            "--prefix-cache": args.prefix_cache,
            "--prefix-pool-size": args.prefix_pool_size,
            "--prefix-len": args.prefix_len,
            "--prefill-chunk-tokens": args.prefill_chunk_tokens,
            "--quantize": args.quantize}
    bad = [k for k, v in noop.items() if v]
    if bad:
        raise ValueError(f"{', '.join(bad)} have no effect on the train path")

    _stamp("importing jax")
    import jax
    import jax.numpy as jnp

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.parallel.mesh import build_mesh
    from pretraining_llm_tpu.training import train_step as ts
    from pretraining_llm_tpu.utils.hardware import device_peak_flops

    cfg = get_preset(args.preset)
    model = cfg.model
    if args.context:
        if model.pos_embed != "rope":
            raise ValueError(
                "--context requires a RoPE preset (learned position tables "
                "are sized by the original context_length)"
            )
        if args.context == model.context_length:
            args.context = 0  # preset default: same series, no _ctx suffix
        else:
            model = dataclasses.replace(model, context_length=args.context)
    if args.attention:
        model = dataclasses.replace(model, attention_impl=args.attention)
    elif model.attention_impl == "ring":
        model = dataclasses.replace(model, attention_impl="flash", sequence_parallel=False)
    if args.unroll:
        model = dataclasses.replace(model, scan_unroll=args.unroll)
    if args.block_q or args.block_kv:
        model = dataclasses.replace(
            model, flash_block_q=args.block_q, flash_block_kv=args.block_kv
        )
    if args.ce:
        model = dataclasses.replace(model, ce_impl=args.ce)
    if args.remat:
        model = dataclasses.replace(model, remat=args.remat)
    elif model.remat == "none":
        # Best measured v5e policy sweep at gpt2-124m: save_attn@batch24
        # 40.68% MFU > full@batch24 40.2% > dots_saveable (the saved
        # attention output spares the flash-forward rerun; saving more cuts
        # HBM traffic less than the recompute it avoids costs).
        model = dataclasses.replace(model, remat="save_attn")
    if args.optimizer:
        cfg = cfg.replace(
            train=dataclasses.replace(cfg.train, optimizer=args.optimizer)
        )
    if args.grad_dtype:
        cfg = cfg.replace(
            train=dataclasses.replace(cfg.train, grad_dtype=args.grad_dtype)
        )
    batch = args.batch or cfg.train.batch_size
    if args.batch == 0 and args.preset == "gpt2-124m":
        # Driver default run: the measured-best batch for this chip, not the
        # preset's training default (v5e sweep 2026-07-31: b16 41.6% MFU >
        # b24 40.6% > b32 40.1% at save_attn/chunked).
        batch = 16
    if args.quick:
        args.steps, args.warmup, batch = 5, 2, min(batch, 4)
    cfg = cfg.replace(model=model, train=dataclasses.replace(cfg.train, batch_size=batch))

    n_dev = jax.device_count()  # first device touch: backend init happens HERE
    _stamp(f"backend up: {jax.default_backend()} x{n_dev} ({jax.devices()[0].device_kind})")
    mesh = build_mesh(cfg.mesh) if n_dev > 1 else None
    state = ts.init_train_state(cfg, jax.random.key(0))
    if mesh is not None:
        # cfg is REQUIRED here: it decides the baked interleaved-PP layout
        # that build_train_step(cfg, mesh) will assume.
        state = ts.shard_train_state(state, mesh, cfg)
    step = ts.build_train_step(cfg, mesh)
    _stamp(f"state built (remat={model.remat}, attn={model.attention_impl}, "
           f"ce={model.ce_impl}, batch={batch})")

    it = loader.synthetic_iterator(model.vocab_size, model.context_length, batch, seed=0)
    x, y = next(it)
    batch_dev = (jnp.asarray(x), jnp.asarray(y))

    # Timing protocol for a possibly-remote device (the axon TPU tunnel):
    # `block_until_ready` does not actually synchronize there, and each
    # dispatch pays a network round trip. So (a) run N steps inside ONE
    # compiled lax.scan -> one dispatch; (b) synchronize by device_get of the
    # scalar loss; (c) time two run lengths and take the slope, cancelling
    # the fixed dispatch + transfer overhead.
    def make_runner(n: int):
        def run(state, b):
            def body(s, _):
                s2, m = step(s, b)
                return s2, m["loss"]

            state, losses = jax.lax.scan(body, state, None, length=n)
            return state, losses[-1]

        return jax.jit(run, donate_argnums=0)

    n2 = max(args.steps, 2)
    n1 = max(n2 // 4, 1)
    run1, run2 = make_runner(n1), make_runner(n2)

    # Compile + warm both programs.
    _stamp(f"compile start (scan lengths {n1}, {n2})")
    state, loss = run1(state, batch_dev)
    float(jax.device_get(loss))
    _stamp(f"compile 1/2 done + {n1} steps ran")
    state, loss = run2(state, batch_dev)
    float(jax.device_get(loss))
    _stamp(f"compile 2/2 done + {n2} steps ran")
    for _ in range(max(args.warmup - 1, 0)):
        state, loss = run1(state, batch_dev)
        float(jax.device_get(loss))
    _stamp("warmup done; timing")

    t0 = time.perf_counter()
    state, loss = run1(state, batch_dev)
    loss_v = float(jax.device_get(loss))
    t1 = time.perf_counter() - t0

    t0 = time.perf_counter()
    state, loss = run2(state, batch_dev)
    loss_v = float(jax.device_get(loss))
    t2 = time.perf_counter() - t0

    dt_per_step = (t2 - t1) / (n2 - n1)
    if dt_per_step <= 0:  # noisy short run; fall back to the long run alone
        dt_per_step = t2 / n2
    tok_per_sec = batch * model.context_length / dt_per_step
    flops_per_token = model.flops_per_token()
    peak = device_peak_flops() * n_dev
    mfu = tok_per_sec * flops_per_token / peak

    return {
        "metric": f"mfu_{cfg.name}_train"
        + (f"_ctx{model.context_length}" if args.context else ""),
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / 0.50, 4),
        "tokens_per_sec_chip": round(tok_per_sec / n_dev, 1),
        "step_ms": round(dt_per_step * 1e3, 2),
        "batch": batch,
        "context_length": model.context_length,
        "params_m": round(model.num_params() / 1e6, 1),
        "attention": model.attention_impl,
        "remat": model.remat,
        "ce_impl": model.ce_impl,
        "grad_dtype": cfg.train.grad_dtype,
        "device": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "loss_finite": bool(jnp.isfinite(loss_v)),
    }


def error_result(args: argparse.Namespace, msg: str, attempts: int) -> dict:
    # Metric names MUST mirror the success paths exactly (run_decode_bench's
    # _ragged/_kvint8 suffixes, run_trainer_bench's trainer_ prefix): the
    # error record's metric keys the last_banked lookup, and a collapsed
    # name would cite banked evidence from a DIFFERENT series.
    if args.mode == "decode":
        metric, unit = f"decode_tokens_per_sec_{args.preset}", "tokens_per_sec"
        if args.ragged:
            metric += "_ragged"
        if args.kv_dtype == "int8":
            metric += "_kvint8"
        if args.decode_unroll:
            metric += "_unroll"
        # Effective layout: the model default is 'unstacked' (no preset
        # overrides it), so only an explicit --cache-layout stacked lands
        # in the historical unsuffixed series — failure records must file
        # under the same series as the successes of the same invocation.
        if args.cache_layout != "stacked":
            metric += "_unstacked"
    elif args.mode == "trainer":
        metric, unit = f"trainer_tokens_per_sec_{args.preset}", "tokens_per_sec_chip"
    elif args.mode == "serving":
        metric = f"serving_tokens_per_sec_{args.preset}"
        if args.kv_dtype == "int8":
            metric += "_kvint8"
        if args.cache_layout != "stacked":  # effective default: unstacked
            metric += "_unstacked"
        unit = "generated_tokens_per_sec"
    elif args.mode == "serving-slo":
        metric = f"serving_slo_goodput_{args.preset}"
        unit = "slo_ok_requests_per_sec"
    elif args.mode == "kernel":
        metric, unit = "kernel_ragged_microbench_ms", "ms"
    else:
        metric, unit = f"mfu_{args.preset}_train", "fraction_of_peak_bf16"
        if args.context:
            metric += f"_ctx{args.context}"
    return {
        "metric": metric,
        "value": 0.0,
        "unit": unit,
        # Same null contract as the success path: decode/serving have no
        # reference baseline, so their failure records carry null too.
        "vs_baseline": None
        if args.mode in ("decode", "serving", "serving-slo", "kernel")
        else 0.0,
        "error": msg[:800],
        "attempts": attempts,
    }


def _file_commit(repo: str, relpath: str) -> str:
    """`<short-hash> <committer-date>` of the last commit touching relpath
    ("" if unknown/uncommitted)."""
    try:
        return subprocess.run(
            ["git", "-C", repo, "log", "-1", "--format=%h %cI", "--", relpath],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=10,
        ).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        return ""


def _last_banked(metric: str, repo: str | None = None) -> dict | None:
    """Best committed on-chip capture record for `metric` (VERDICT r3 #8),
    plus FRESHNESS (VERDICT r5 #8): the most recent `mfu-refresh*` record
    for the same metric rides along as ``latest_refresh`` (value +
    timestamp), so a dead-backend round end shows the driver the
    end-of-session state — not just a possibly-stale peak.

    When the backend is dead at bench time, the driver's JSON is the round's
    only visible number — so the environment-error record must point at the
    banked evidence (value + capture-file path + commit) instead of leaving
    a bare 0.0. Scans the campaign JSONLs (live + committed); a record
    counts only if its stage succeeded (rc == 0), carries this metric with
    a positive value, and has no error field.
    """
    repo = repo or os.path.dirname(os.path.abspath(__file__))
    # Committed captures first: on equal values the committed record wins
    # (it can carry a commit hash; the live root JSONL is uncommitted).
    paths = sorted(
        glob.glob(os.path.join(repo, "data", "captures", "*.jsonl"))
    ) + [os.path.join(repo, "tpu_capture.jsonl")]
    best = None
    latest_refresh = None
    for path in paths:
        # Refresh records themselves rarely carry "ts"; the file's
        # campaign-start records do — the last one seen before a refresh
        # line is the session the refresh ran in.
        file_ts = None
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec.get("ts"), str):
                        file_ts = rec["ts"]
                    if (
                        rec.get("rc") != 0
                        or rec.get("metric") != metric
                        or rec.get("error")
                        or not isinstance(rec.get("value"), (int, float))
                        or rec["value"] <= 0
                    ):
                        continue
                    relpath = os.path.relpath(path, repo)
                    # Files scan oldest-to-newest (sorted rounds, live
                    # last), lines likewise: the last match IS the most
                    # recent refresh.
                    if str(rec.get("stage", "")).startswith("mfu-refresh"):
                        latest_refresh = {
                            "value": rec["value"],
                            "stage": rec.get("stage"),
                            "capture_path": relpath,
                            "ts": rec.get("ts") or file_ts,
                        }
                    if best is None or rec["value"] > best["value"]:
                        best = {
                            "metric": metric,
                            "value": rec["value"],
                            "unit": rec.get("unit"),
                            "stage": rec.get("stage"),
                            "capture_path": relpath,
                        }
                        for k in ("tokens_per_sec_chip", "batch", "remat",
                                  "ce_impl", "ts"):
                            if k in rec:
                                best[k] = rec[k]
        except OSError:
            continue
    if best is not None:
        commit = _file_commit(repo, best["capture_path"])
        if commit:
            best["commit"] = commit
        if latest_refresh is not None:
            if latest_refresh["ts"] is None:
                # Last resort: the capture file's commit date bounds when
                # the refresh ran.
                commit = _file_commit(repo, latest_refresh["capture_path"])
                if commit:
                    latest_refresh["ts"] = commit.split(" ", 1)[-1]
            best["latest_refresh"] = latest_refresh
    return best


def _run_canary(timeout: float):
    """Probe the environment in a fresh subprocess. Returns (ok, detail)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--_canary"]
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, timeout=timeout, text=True
        )
    except subprocess.TimeoutExpired:
        return False, f"canary hung past {timeout:.0f}s (backend unreachable)"
    lines = [ln for ln in (proc.stdout or "").splitlines() if ln.strip()]
    if proc.returncode == 0 and lines:
        try:
            return True, json.loads(lines[-1])
        except json.JSONDecodeError:
            pass
    tail = lines[-1][:200] if lines else "(no output)"
    return False, f"canary failed rc={proc.returncode}: {tail}"


def _attempt(args: argparse.Namespace, remat: str, timeout: float, attention: str = "",
             batch_override: int = 0, ce_override: str = ""):
    """One fresh-subprocess inner run. Returns (json_dict|None, err_str).

    ``batch_override``: per-candidate batch for race rungs whose measured
    best lives at a different batch than the preset default (e.g.
    remat=none fits only at small batch); 0 = use args.batch.
    ``ce_override``: per-candidate CE head (e.g. the none@8+dense rung);
    "" = use args.ce. The race drops ce-overridden rungs when an explicit
    --ce is given, so a nonempty ce_override never coexists with args.ce.
    """
    cmd = [
        sys.executable, os.path.abspath(__file__), "--_inner",
        "--preset", args.preset,
        "--batch", str(batch_override or args.batch),
        "--steps", str(args.steps),
        "--warmup", str(args.warmup),
    ]
    if args.quick:
        cmd.append("--quick")
    if args.mode != "train":
        cmd += ["--mode", args.mode]
    if args.prefetch >= 0:
        cmd += ["--prefetch", str(args.prefetch)]
    if args.ragged:
        cmd.append("--ragged")
    if args.kv_dtype:
        cmd += ["--kv-dtype", args.kv_dtype]
    if args.decode_unroll:
        cmd.append("--decode-unroll")
    if args.steps_per_sched:
        cmd += ["--steps-per-sched", str(args.steps_per_sched)]
    if args.no_pipeline:
        cmd.append("--no-pipeline")
    if args.pipeline_depth:
        cmd += ["--pipeline-depth", str(args.pipeline_depth)]
    if args.admit_batch:
        cmd += ["--admit-batch", str(args.admit_batch)]
    if args.paged_attn:
        cmd += ["--paged-attn", args.paged_attn]
    if args.spec_draft:
        cmd += ["--spec-draft", args.spec_draft, "--spec-k", str(args.spec_k)]
    if args.prefix_cache:
        cmd.append("--prefix-cache")
    if args.prefill_chunk_tokens:
        cmd += ["--prefill-chunk-tokens", str(args.prefill_chunk_tokens)]
    if args.quantize:
        cmd += ["--quantize", args.quantize]
    if args.mode == "serving-fleet":
        cmd += [
            "--replicas", str(args.replicas),
            "--fleet-scenario", args.fleet_scenario,
            "--rate-rps", str(args.rate_rps),
        ]
        if args.n_requests:
            cmd += ["--n-requests", str(args.n_requests)]
    if args.mode == "serving-slo":
        cmd += [
            "--rate-rps", str(args.rate_rps),
            "--slo-ttft-s", str(args.slo_ttft_s),
            "--slo-e2e-s", str(args.slo_e2e_s),
            "--n-requests", str(args.n_requests),
        ]
        if args.prefix_pool_size:
            cmd += [
                "--prefix-pool-size", str(args.prefix_pool_size),
                "--prefix-zipf", str(args.prefix_zipf),
            ]
            if args.prefix_len:
                cmd += ["--prefix-len", str(args.prefix_len)]
    if args.cache_layout:
        cmd += ["--cache-layout", args.cache_layout]
    if args.context:
        cmd += ["--context", str(args.context)]
    if args.attention or attention:
        cmd += ["--attention", args.attention or attention]
    if args.ce or ce_override:
        cmd += ["--ce", ce_override or args.ce]
    if remat:
        cmd += ["--remat", remat]
    if args.optimizer:
        cmd += ["--optimizer", args.optimizer]
    if args.grad_dtype:
        cmd += ["--grad-dtype", args.grad_dtype]
    if args.unroll:
        cmd += ["--unroll", str(args.unroll)]
    if args.block_q:
        cmd += ["--block-q", str(args.block_q)]
    if args.block_kv:
        cmd += ["--block-kv", str(args.block_kv)]
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, timeout=timeout, text=True
        )
    except subprocess.TimeoutExpired:
        return None, f"hung past {timeout:.0f}s (killed)"
    out_lines = [ln for ln in (proc.stdout or "").splitlines() if ln.strip()]
    if not out_lines:
        return None, f"rc={proc.returncode}: (no output)"
    try:
        rec = json.loads(out_lines[-1])
    except json.JSONDecodeError:
        return None, f"rc={proc.returncode}: non-JSON output: {out_lines[-1][:200]}"
    if proc.returncode == 0:
        return rec, ""
    # Parseable structured error from the inner run: hand it back so the
    # caller can relay the full diagnostic rather than a truncated tail.
    return rec, f"rc={proc.returncode}: {out_lines[-1][:300]}"


def wrapper_main(args: argparse.Namespace) -> int:
    """Candidate-racing retry loop.

    Fresh subprocess per attempt (JAX pins a failed backend for the whole
    process), hard per-attempt timeout (init can hang, not just raise),
    structured JSON error on final failure. When no explicit --remat is
    given for a train run, races an ordered remat-candidate list — the
    newest (fastest-expected) policy first, the proven-safe one last — and
    reports the BEST successful result: a policy that trips a compiler
    pathology costs one bounded attempt, never the round's number.
    """
    deadline = time.monotonic() + args.timeout_budget

    # Environment canary FIRST (VERDICT r2 next #1b): a dead tunnel must be
    # distinguishable from a framework regression, and must not burn the
    # whole budget. One retry — a single canary hang could still be a flake.
    canary_info = None
    if not args.skip_canary:
        for i in range(2):
            t_c = time.monotonic()
            ok, detail = _run_canary(args.canary_timeout)
            if ok:
                canary_info = detail
                canary_info["canary_s"] = round(time.monotonic() - t_c, 1)
                print(f"[bench] canary ok: {json.dumps(detail)}", file=sys.stderr)
                break
            print(f"[bench] {detail} (try {i + 1}/2)", file=sys.stderr)
        else:
            rec = error_result(args, f"environment: backend unreachable ({detail})", 0)
            rec["environment_error"] = True
            banked = _last_banked(rec["metric"])
            if banked is not None:
                rec["last_banked"] = banked
            print(json.dumps(rec))
            return 1

    # Race only on the preset the candidate list was measured at; every
    # other preset keeps its own tuned remat (passed through untouched).
    race = (
        not args.remat
        and not args.attention
        and args.mode == "train"
        and not args.quick
        and args.preset == "gpt2-124m"
    )
    if race:
        # (remat, attention, batch_override) candidates, measured-best
        # first (v5e on-chip sweep 2026-07-31: save_attn > save_qkv_attn >
        # save_big at every batch). Second rung: remat=none at batch 8 —
        # ZERO recompute, so the honest-MFU ceiling rises by the ~25%
        # save_attn charges to recomputation; CPU AOT says it fits (true
        # peak ~14.5 GiB of 16; a clean OOM costs one bounded attempt).
        # The tail is the KNOWN-GOOD ladder (VERDICT r2 next #1c): 'full'
        # remat + flash is the round-1-measured-safe config, and naive
        # attention last — a pathology in any one policy can cost bounded
        # attempts, never the round's number. The race reports the BEST
        # success, so `python bench.py` reproduces whichever rung wins.
        # Fields: (remat, attention, batch_override, ce_override,
        # contender). Contenders (could be the best number) are always
        # raced; fallbacks (measured-slower safety rungs) run only while no
        # result is banked. none@8+dense is the analytic projection of the
        # >=50% bar: zero block recompute AND zero CE-logits recompute;
        # none@8+chunked backs it up in case the dense head has an
        # unexpected pathology at this shape.
        # save_attn@16+dense: the measured-best remat/batch with the CE
        # logits-recompute (~10% of analytic step FLOPs) removed — the
        # cheapest projected step past 41.6%; saved logits at b16 are
        # ~1.65 GB, well within budget on top of save_attn's footprint.
        candidates = [
            # save_attn_res (r5): saves the flash VJP's (o, lse) outputs so
            # the kernel never reruns in backward — the r4 profile showed
            # the flash forward running TWICE under save_attn (same memory
            # class, +4 bytes/token/head for lse). Newest policy leads.
            ("save_attn_res", "", 0, "dense", True),
            ("save_attn", "", 0, "dense", True),
            ("save_attn", "", 0, "", True),
            ("none", "", 8, "dense", True),
            ("none", "", 8, "", True),
            ("save_big", "", 0, "", False), ("full", "", 0, "", False),
            ("full", "naive", 0, "", False),
        ]
        if args.batch:
            # An explicit --batch is a series point the caller chose; a rung
            # that would silently answer it at a DIFFERENT batch is dropped
            # (remat=none at a large explicit batch would only OOM anyway).
            # A rung whose override equals the request stays — so a banked
            # none@8 win is reproducible via `bench.py --batch 8`.
            candidates = [
                c for c in candidates if not c[2] or c[2] == args.batch
            ]
        if args.ce:
            # An explicit --ce applies to EVERY rung (the plain rungs all
            # inherit it), so a ce-overridden rung is either a duplicate of
            # its plain sibling (--ce dense) or a mislabeled contradiction
            # of the caller's choice (--ce chunked/fused): drop them all.
            candidates = [c for c in candidates if not c[3]]
    else:
        candidates = [(args.remat, "", 0, "", True)]
    last_contender = max(i for i, c in enumerate(candidates) if c[4])
    attempts = 0
    last_err = "no attempts made (timeout budget too small?)"
    best = None
    best_cand = None
    rungs = []
    last_error_rec = None
    wedged = False
    transient_markers = (
        "UNAVAILABLE", "DEADLINE", "unavailable", "backend",
        "Socket", "socket", "connect", "RESOURCE_EXHAUSTED",
    )
    for ci, (remat, attention, batch_over, ce_over, _contender) in enumerate(candidates):
        # Reserve budget up front: a pathological first candidate may spend
        # at most its fair share, never the safe fallback's — but the share
        # is floored at one full attempt (+margin) when the budget allows:
        # adding fallback rungs must not shrink the HEADLINE rung's window
        # below a legitimate TPU compile+run, whose mid-step kill is itself
        # the wedge trigger (round-3 lesson).
        remaining = deadline - time.monotonic()
        share = remaining / (len(candidates) - ci)
        if _contender:
            # Floor CONTENDER rungs only: fallbacks keep strict fair-share,
            # so cascading failures cannot geometrically starve the
            # known-good tail below a viable attempt.
            share = max(share, min(args.attempt_timeout + 60, remaining / 2))
        cand_deadline = time.monotonic() + share
        backoff = 10.0
        cand_hangs = 0
        while True:
            remaining = cand_deadline - time.monotonic()
            if remaining <= 5:
                break
            attempts += 1
            rec, err = _attempt(args, remat, min(args.attempt_timeout, remaining), attention,
                                batch_over, ce_over)
            if rec is not None and not err:
                # Per-rung evidence: the final JSON carries only the winner,
                # so losing rungs' measurements would be unrecoverable from a
                # campaign log (round-4 lesson: the remat=none contenders ran
                # clean but their values vanished). Collected onto the
                # winner's "rungs" list, which flows into the campaign JSONL.
                print(
                    "[bench] rung "
                    f"remat={rec.get('remat')} ce={rec.get('ce_impl')} "
                    f"batch={rec.get('batch')} -> "
                    f"mfu={rec.get('value')} tok/s={rec.get('tokens_per_sec_chip')} "
                    f"step_ms={rec.get('step_ms')}",
                    file=sys.stderr,
                )
                rungs.append({k: rec.get(k) for k in (
                    "remat", "ce_impl", "batch", "value",
                    "tokens_per_sec_chip", "step_ms")})
                if best is None or rec.get("value", 0) > best.get("value", 0):
                    best = rec
                    best_cand = (remat, attention, batch_over, ce_over)
                break  # this candidate succeeded; next candidate
            last_err = (
                f"attempt {attempts} (remat={remat or 'default'}"
                + (f", attention={attention}" if attention else "")
                + (f", batch={batch_over}" if batch_over else "")
                + (f", ce={ce_over}" if ce_over else "")
                + f"): {err}"
            )
            if rec is not None:
                last_error_rec = rec
            print(f"[bench] {last_err}", file=sys.stderr)
            if "hung" in err:
                cand_hangs += 1
                # Measured-on-chip failure mode (round 3): killing a client
                # that hung MID-STEP leaves the backend unacquirable — every
                # later attempt then hangs at device acquisition and burns
                # its full timeout learning nothing. Classify with a cheap
                # canary before spending more budget.
                ok, detail = _run_canary(min(args.canary_timeout, max(deadline - time.monotonic(), 30)))
                if not ok:
                    if best is not None:
                        # A result is already banked: report it NOW rather
                        # than polling a wedged backend for the rest of the
                        # budget (the remaining candidates could only have
                        # improved the number, not rescued the round).
                        print(f"[bench] post-hang canary: {detail} — backend "
                              "wedged; reporting the already-banked result",
                              file=sys.stderr)
                        # Mark the banked record: callers chaining further
                        # --skip-canary runs (scripts/tpu_capture.py) must
                        # know the backend was left dead despite rc=0.
                        best["backend_wedged"] = True
                        wedged = True
                        break
                    print(f"[bench] post-hang canary: {detail} — backend wedged; "
                          "polling for recovery instead of burning attempts",
                          file=sys.stderr)
                    # Poll cheap canaries (not full attempts) until the
                    # backend answers or the whole budget is gone.
                    while time.monotonic() + 60 < deadline:
                        time.sleep(45)
                        ok, detail = _run_canary(
                            min(args.canary_timeout, max(deadline - time.monotonic(), 30)))
                        if ok:
                            print("[bench] backend recovered; resuming", file=sys.stderr)
                            break
                    if not ok:
                        wedged = True
                        last_err += " (backend wedged after the kill; never recovered in budget)"
                        break
                    if cand_hangs >= 2:
                        break  # hung twice: this program is the problem
                    continue  # recovered: one retry of this candidate
                # Canary alive: the hang was this program or a transient
                # stall, not the backend. One retry (budget share permitting);
                # a second hang abandons the candidate.
                if cand_hangs >= 2:
                    break
                continue
            # OOM is DETERMINISTIC despite surfacing as RESOURCE_EXHAUSTED
            # (XLA's allocator status code): retrying the identical compile
            # can only drain the rung's budget share. The marginal probe
            # rungs (remat=none ladder, mfu-1b b4) are sized to sometimes
            # OOM — each must cost exactly one bounded attempt.
            oom = any(m in err for m in (
                "Out of memory", "out of memory", "OOM",
                "Attempting to reserve",
            ))
            transient = not oom and any(m in err for m in transient_markers)
            if not transient:
                break
            if time.monotonic() + backoff >= cand_deadline:
                break
            time.sleep(backoff)
            backoff = min(backoff * 2, 120.0)
        if wedged:
            break
        if best is not None and ci >= last_contender:
            break  # every contender has run: remaining fallbacks are slower
    if race and best is not None and not wedged:
        # Same-session median-of-N (VERDICT #1): a single winning reading is
        # not a reproduction — re-run the WINNER's exact config until
        # --race-repeats same-config samples exist or the budget is gone,
        # then bank {best, median, n, spread}. The headline `value` stays
        # the best sample (the historical series semantics); `value_median`
        # is the defensible same-session number.
        race_values = [best["value"]]
        r_remat, r_attention, r_batch, r_ce = best_cand
        while len(race_values) < args.race_repeats:
            remaining = deadline - time.monotonic()
            if remaining <= 5:
                print(f"[bench] race repeats: budget exhausted at "
                      f"n={len(race_values)}", file=sys.stderr)
                break
            attempts += 1
            rec, err = _attempt(args, r_remat,
                                min(args.attempt_timeout, remaining),
                                r_attention, r_batch, r_ce)
            if rec is not None and not err:
                race_values.append(rec["value"])
                rungs.append({k: rec.get(k) for k in (
                    "remat", "ce_impl", "batch", "value",
                    "tokens_per_sec_chip", "step_ms")})
                if rec.get("value", 0) > best.get("value", 0):
                    best = rec
                continue
            print(f"[bench] race repeat failed: {err}", file=sys.stderr)
            if "hung" in err:
                # A hung repeat can wedge the chip like any other kill: one
                # cheap canary classifies it so chained --skip-canary
                # callers know. Either way repeats stop — the median is
                # computed over whatever samples exist.
                ok, detail = _run_canary(min(
                    args.canary_timeout,
                    max(deadline - time.monotonic(), 30)))
                if not ok:
                    print(f"[bench] post-hang canary: {detail} — backend "
                          "wedged; reporting collected samples",
                          file=sys.stderr)
                    best["backend_wedged"] = True
            break  # deterministic failure: stop sampling, keep what exists
        best["race"] = {
            "best": max(race_values),
            "median": round(statistics.median(race_values), 5),
            "n": len(race_values),
            "spread": round(max(race_values) - min(race_values), 5),
            "values": race_values,
        }
        best["value_median"] = best["race"]["median"]
    if best is not None:
        if canary_info is not None:
            best.setdefault("canary_s", canary_info.get("canary_s"))
        if len(rungs) > 1:
            best["rungs"] = rungs
        print(json.dumps(best))
        return 0
    if last_error_rec is not None and not wedged:
        # Relay the inner run's full structured error line untouched —
        # race or not (ADVICE r2 low #3).
        print(json.dumps(last_error_rec))
        return 1
    rec = error_result(args, last_err, attempts)
    if wedged:
        rec["environment_error"] = True
        banked = _last_banked(rec["metric"])
        if banked is not None:
            rec["last_banked"] = banked
    print(json.dumps(rec))
    return 1


def inner_main(args: argparse.Namespace) -> int:
    try:
        print(json.dumps(run_bench(args)))
        return 0
    except Exception as exc:  # noqa: BLE001 — wrapper parses this line
        print(json.dumps(error_result(args, f"{type(exc).__name__}: {exc}", 1)))
        return 1


if __name__ == "__main__":
    _args = parse_args()
    if _args._canary:
        sys.exit(canary_main())
    sys.exit(inner_main(_args) if _args._inner else wrapper_main(_args))
