#!/usr/bin/env python
"""Benchmark: training throughput + MFU for the flagship config on real hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The BASELINE.json target is >=50% MFU on the 124M GPT-2 config;
`vs_baseline` is measured_MFU / 0.50 (1.0 = target met).

Usage:
  python bench.py             # full run (gpt2-124m, auto batch)
  python bench.py --quick     # fewer steps, for smoke testing
  python bench.py --preset gpt2-350m-dp --batch 8
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.data import loader
from pretraining_llm_tpu.parallel.mesh import build_mesh
from pretraining_llm_tpu.training import train_step as ts
from pretraining_llm_tpu.utils.hardware import device_peak_flops


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="gpt2-124m")
    parser.add_argument("--batch", type=int, default=0, help="global batch (0 = preset default)")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--attention", default="", choices=["", "naive", "flash"])
    parser.add_argument(
        "--remat", default="", choices=["", "none", "full", "dots_saveable", "save_attn"]
    )
    args = parser.parse_args()

    cfg = get_preset(args.preset)
    model = cfg.model
    if args.attention:
        model = dataclasses.replace(model, attention_impl=args.attention)
    elif model.attention_impl == "ring":
        model = dataclasses.replace(model, attention_impl="flash", sequence_parallel=False)
    if args.remat:
        model = dataclasses.replace(model, remat=args.remat)
    elif model.remat == "none":
        # Measured faster AND leaner on v5e: saving fewer activations cuts
        # HBM traffic by more than the recompute costs (full remat beats
        # dots_saveable 129.8ms vs 132.8ms at gpt2-124m/batch 12).
        model = dataclasses.replace(model, remat="full")
    batch = args.batch or cfg.train.batch_size
    if args.quick:
        args.steps, args.warmup, batch = 5, 2, min(batch, 4)
    cfg = cfg.replace(model=model, train=dataclasses.replace(cfg.train, batch_size=batch))

    n_dev = jax.device_count()
    mesh = build_mesh(cfg.mesh) if n_dev > 1 else None
    state = ts.init_train_state(cfg, jax.random.key(0))
    if mesh is not None:
        state = ts.shard_train_state(state, mesh)
    step = ts.build_train_step(cfg, mesh)

    it = loader.synthetic_iterator(model.vocab_size, model.context_length, batch, seed=0)
    x, y = next(it)
    batch_dev = (jnp.asarray(x), jnp.asarray(y))

    # Timing protocol for a possibly-remote device (the axon TPU tunnel):
    # `block_until_ready` does not actually synchronize there, and each
    # dispatch pays a network round trip. So (a) run N steps inside ONE
    # compiled lax.scan -> one dispatch; (b) synchronize by device_get of the
    # scalar loss; (c) time two run lengths and take the slope, cancelling
    # the fixed dispatch + transfer overhead.
    def make_runner(n: int):
        def run(state, b):
            def body(s, _):
                s2, m = step(s, b)
                return s2, m["loss"]

            state, losses = jax.lax.scan(body, state, None, length=n)
            return state, losses[-1]

        return jax.jit(run, donate_argnums=0)

    n2 = max(args.steps, 2)
    n1 = max(n2 // 4, 1)
    run1, run2 = make_runner(n1), make_runner(n2)

    # Compile + warm both programs.
    state, loss = run1(state, batch_dev)
    float(jax.device_get(loss))
    state, loss = run2(state, batch_dev)
    float(jax.device_get(loss))
    for _ in range(max(args.warmup - 1, 0)):
        state, loss = run1(state, batch_dev)
        float(jax.device_get(loss))

    t0 = time.perf_counter()
    state, loss = run1(state, batch_dev)
    loss_v = float(jax.device_get(loss))
    t1 = time.perf_counter() - t0

    t0 = time.perf_counter()
    state, loss = run2(state, batch_dev)
    loss_v = float(jax.device_get(loss))
    t2 = time.perf_counter() - t0

    dt_per_step = (t2 - t1) / (n2 - n1)
    if dt_per_step <= 0:  # noisy short run; fall back to the long run alone
        dt_per_step = t2 / n2
    tok_per_sec = batch * model.context_length / dt_per_step
    flops_per_token = model.flops_per_token()
    peak = device_peak_flops() * n_dev
    mfu = tok_per_sec * flops_per_token / peak

    result = {
        "metric": f"mfu_{cfg.name}_train",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / 0.50, 4),
        "tokens_per_sec_chip": round(tok_per_sec / n_dev, 1),
        "step_ms": round(dt_per_step * 1e3, 2),
        "batch": batch,
        "context_length": model.context_length,
        "params_m": round(model.num_params() / 1e6, 1),
        "attention": model.attention_impl,
        "device": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "loss_finite": bool(jnp.isfinite(loss_v)),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
