// Native data batcher: mmap'd token files -> (x, y) int32 batches.
//
// The runtime-side counterpart of the reference's data path, which leans on
// numpy's C memmap + per-sample Python-level gathers
// (/root/reference/data_loader/data_loader.py:38-52). Here the whole batch is
// produced by one native call:
//
//   - the token file is mmap'd once (MAP_SHARED, readahead-advised);
//   - crop starts come from a counter-based splitmix64 PRNG, so sampling is
//     stateless: batch k of seed s is a pure function of (s, k) — exact
//     checkpoint resume needs only the step counter;
//   - rows are gathered uint16 -> int32 by a small thread pool directly into
//     caller-provided buffers (x and the shifted-by-one y in one pass);
//   - contiguous-block host sharding mirrors the Python loader.
//
// Exposed as plain C for ctypes (no pybind11 dependency).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Batcher {
  const uint16_t* data = nullptr;  // shard view into the mapping
  size_t n_tokens = 0;             // tokens in the shard view
  const void* map_base = nullptr;  // for munmap
  size_t map_len = 0;
  int64_t context_length = 0;
  int n_threads = 1;
};

// splitmix64: counter-based, statistically solid for crop sampling.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

}  // namespace

extern "C" {

// Returns an opaque handle (heap pointer) or null on failure.
// Shards the token stream into contiguous blocks with context_length overlap,
// matching pretraining_llm_tpu/data/loader.py::MemmapTokens.
void* batcher_open(const char* path, int64_t context_length, int32_t shard_index,
                   int32_t shard_count, int32_t n_threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 2) {
    ::close(fd);
    return nullptr;
  }
  size_t total = static_cast<size_t>(st.st_size) / sizeof(uint16_t);
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);  // mapping persists
  if (base == MAP_FAILED) return nullptr;
  madvise(base, st.st_size, MADV_RANDOM);

  size_t lo = 0, hi = total;
  if (shard_count > 1) {
    lo = (total * static_cast<size_t>(shard_index)) / shard_count;
    hi = (total * static_cast<size_t>(shard_index + 1)) / shard_count +
         static_cast<size_t>(context_length);
    if (hi > total) hi = total;
  }
  if (hi - lo < static_cast<size_t>(context_length) + 1) {
    munmap(base, st.st_size);
    return nullptr;
  }
  auto* b = new Batcher();
  b->map_base = base;
  b->map_len = st.st_size;
  b->data = static_cast<const uint16_t*>(base) + lo;
  b->n_tokens = hi - lo;
  b->context_length = context_length;
  b->n_threads = n_threads > 0 ? n_threads : 1;
  return b;
}

int64_t batcher_num_tokens(void* handle) {
  return static_cast<Batcher*>(handle)->n_tokens;
}

// Fill x, y (each batch_size * context_length int32) for batch number
// `counter` of stream `seed`. Deterministic: no internal state.
void batcher_sample(void* handle, uint64_t seed, uint64_t counter,
                    int32_t batch_size, int32_t* x, int32_t* y) {
  auto* b = static_cast<Batcher*>(handle);
  const int64_t t = b->context_length;
  const uint64_t n_starts = b->n_tokens - t;  // starts 0 .. n_starts-1

  auto fill_rows = [&](int32_t row_begin, int32_t row_end) {
    for (int32_t r = row_begin; r < row_end; ++r) {
      uint64_t rnd = splitmix64(seed * 0x100000001b3ULL + counter * 0x9e3779b9ULL + r);
      uint64_t start = rnd % n_starts;
      const uint16_t* src = b->data + start;
      int32_t* xr = x + static_cast<int64_t>(r) * t;
      int32_t* yr = y + static_cast<int64_t>(r) * t;
      for (int64_t i = 0; i < t; ++i) {
        xr[i] = static_cast<int32_t>(src[i]);
        yr[i] = static_cast<int32_t>(src[i + 1]);
      }
    }
  };

  int threads = b->n_threads;
  // Thread spawn costs ~50us each: only fan out when each thread gets enough
  // copying (>=1M tokens) to amortize it.
  if (threads <= 1 || static_cast<int64_t>(batch_size) * t < threads * (1 << 20)) {
    fill_rows(0, batch_size);
    return;
  }
  std::vector<std::thread> pool;
  int32_t per = (batch_size + threads - 1) / threads;
  for (int i = 0; i < threads; ++i) {
    int32_t lo = i * per;
    int32_t hi = lo + per > batch_size ? batch_size : lo + per;
    if (lo >= hi) break;
    pool.emplace_back(fill_rows, lo, hi);
  }
  for (auto& th : pool) th.join();
}

void batcher_close(void* handle) {
  auto* b = static_cast<Batcher*>(handle);
  munmap(const_cast<void*>(b->map_base), b->map_len);
  delete b;
}

}  // extern "C"
