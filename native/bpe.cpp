// Native byte-level BPE encoder for the data-prep pipeline.
//
// The reference outsources its hot tokenize loop to tiktoken's native (Rust)
// BPE (reference: scripts/data_preprocess.py:29-34); this supplies the
// equivalent native capability for the in-repo tokenizer (data/bpe.py).
//
// Algorithm: greedy lowest-rank-first pair merging over a doubly linked list
// with a lazy min-heap of candidate pairs — O(n log n) per document vs the
// pure-Python O(n * n_merges) sweep. Produces bit-identical output to
// BPETokenizer.encode_ordinary: the heap orders by (rank, position), and
// because a merge with rank r only ever creates pairs of rank > r (merge i
// can only reference ids < 256+i), pending same-rank occurrences are always
// consumed left-to-right before any newly created pair, exactly like the
// Python sweep.
//
// C ABI (ctypes-friendly, no exceptions across the boundary):
//   bpe_create(a, b, n)       -> handle; merge i is (a[i], b[i]) -> 256+i
//   bpe_encode(h, text, n, out) -> token count; out must hold n int32s
//   bpe_destroy(h)
#include <cstdint>
#include <queue>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

struct Bpe {
  std::unordered_map<uint64_t, int32_t> ranks;
};

// (rank, left-position): min-heap pops lowest rank, then leftmost.
using Entry = std::pair<int64_t, int64_t>;

}  // namespace

extern "C" {

void* bpe_create(const int32_t* a, const int32_t* b, int32_t n_merges) {
  Bpe* t = new (std::nothrow) Bpe();
  if (t == nullptr) return nullptr;
  t->ranks.reserve(n_merges * 2);
  for (int32_t i = 0; i < n_merges; ++i) {
    // operator[]: last index wins on duplicate pairs, matching the Python
    // ranks dict built by enumerate() (bpe.py).
    t->ranks[pair_key(a[i], b[i])] = i;
  }
  return t;
}

void bpe_destroy(void* handle) { delete static_cast<Bpe*>(handle); }

int64_t bpe_encode(void* handle, const uint8_t* text, int64_t n, int32_t* out) {
  const Bpe* t = static_cast<const Bpe*>(handle);
  if (n <= 0) return 0;
  std::vector<int32_t> ids(text, text + n);
  std::vector<int64_t> next(n), prev(n);
  std::vector<char> alive(n, 1);
  for (int64_t i = 0; i < n; ++i) {
    prev[i] = i - 1;
    next[i] = i + 1;
  }

  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap;
  auto maybe_push = [&](int64_t left) {
    int64_t right = next[left];
    if (right >= n) return;
    auto it = t->ranks.find(pair_key(ids[left], ids[right]));
    if (it != t->ranks.end()) heap.emplace(it->second, left);
  };
  for (int64_t i = 0; i + 1 < n; ++i) maybe_push(i);

  while (!heap.empty()) {
    auto [rank, i] = heap.top();
    heap.pop();
    if (!alive[i]) continue;
    int64_t j = next[i];
    if (j >= n) continue;
    // Lazy validation: the pair may have been consumed or changed since push.
    auto it = t->ranks.find(pair_key(ids[i], ids[j]));
    if (it == t->ranks.end() || it->second != rank) continue;
    // Merge: right element folds into the left.
    ids[i] = 256 + static_cast<int32_t>(rank);
    alive[j] = 0;
    int64_t k = next[j];
    next[i] = k;
    if (k < n) prev[k] = i;
    if (prev[i] >= 0) maybe_push(prev[i]);
    maybe_push(i);
  }

  int64_t m = 0;
  for (int64_t i = 0; i < n; i = next[i]) out[m++] = ids[i];
  return m;
}

}  // extern "C"
