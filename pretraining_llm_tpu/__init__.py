"""pretraining_llm_tpu — a TPU-native LLM pretraining framework.

A from-scratch JAX/XLA/Pallas/pjit framework with the capabilities of the
reference PyTorch stack (`Flink-ddd/pretraining-llm`): GPT-2 BPE data pipeline
(uint16 memmap shards), decoder-only transformer pretraining with AdamW, data/
FSDP/tensor/sequence parallelism over a `jax.sharding.Mesh`, Pallas flash
attention, ring attention for long context, sharded checkpoints with exact
resume, and KV-cached autoregressive generation.

Design principles (TPU-first, not a port):
  - One compiled SPMD train step (`pjit`): forward, backward, grad reduce,
    optimizer update, and metrics all fuse into a single XLA program.
  - Pure functional model: params are pytrees, blocks are stacked and scanned
    (`jax.lax.scan`) so the program is O(1) in depth for XLA.
  - Parallelism is expressed as `PartitionSpec`s over a named mesh
    (data/fsdp/tensor/seq); XLA inserts the ICI/DCN collectives.
  - bf16 compute on the MXU with fp32 master params; no loss scaling needed.
"""

__version__ = "0.1.0"

from pretraining_llm_tpu.config import (  # noqa: F401
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
    get_preset,
    list_presets,
)
