"""Typed configuration for models, data, training, and the device mesh.

Replaces the reference's flat constants dict (`/root/reference/config/config.py:29-47`)
with validated dataclasses. The reference ships with five config keys that are
consumed but never defined (SURVEY.md Appendix B) — this module fails fast at
construction time instead: every field is typed, defaulted, and checked in
``__post_init__``/``validate``.

Presets cover the five BASELINE.json configs plus the reference's own default
3.16B shape (``reference-3b``) for parity accounting.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

_ACTIVATIONS = ("relu", "gelu", "swiglu")
_NORMS = ("layernorm", "rmsnorm")
_POS_EMBEDS = ("learned", "rope")
_ATTN_IMPLS = ("naive", "flash", "ring", "ulysses")
_REMAT_POLICIES = ("none", "full", "dots_saveable", "save_attn",
                   "save_attn_res", "save_qkv_attn", "save_big")


@dataclass(frozen=True)
class ModelConfig:
    """Architecture of a decoder-only transformer.

    The pluggable knobs (``activation``, ``norm``, ``pos_embed``,
    ``use_output_proj``, ``tie_embeddings``) span the reference's exact
    architecture (SURVEY.md §2.5: pre-LN, learned-absolute positions, ReLU MLP,
    no attention output projection, untied biased lm_head) and the standard
    GPT-2 / Llama shapes required by BASELINE.json configs #1-#5.
    """

    vocab_size: int = 50304
    context_length: int = 1024
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_head: Optional[int] = None  # defaults to d_model // n_heads
    # Grouped-query attention: number of KV heads (None = n_heads, i.e. MHA;
    # 1 = MQA). Shrinks KV-cache memory and KV projection params by
    # n_heads/n_kv_heads.
    n_kv_heads: Optional[int] = None
    mlp_ratio: float = 4.0
    activation: str = "gelu"  # relu | gelu | swiglu
    norm: str = "layernorm"  # layernorm | rmsnorm
    pos_embed: str = "learned"  # learned | rope
    rope_theta: float = 10000.0
    use_output_proj: bool = True  # reference has none (attention.py:95)
    tie_embeddings: bool = True  # reference unties (transformer.py:37-38)
    lm_head_bias: bool = False  # reference has bias on lm_head
    qkv_bias: bool = False  # reference: biasless K/Q/V (attention.py:29-31)
    mlp_bias: bool = True  # reference: biases in MLP (mlp.py:24-26)
    norm_eps: float = 1e-5
    # Numerics
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # Attention implementation: naive einsum | pallas flash | ring (seq-parallel)
    attention_impl: str = "naive"
    # Sequence distribution for ring attention: "zigzag" pairs chunk i with
    # chunk 2n-1-i per device so causal work balances across the ring
    # (utilization ~1.0 vs (n+1)/2n contiguous); loss_fn applies the matching
    # token permutation automatically. "contiguous" keeps plain sharding.
    ring_layout: str = "zigzag"
    # Flash-attention block sizes (tuned for TPU MXU/VMEM; 0 = auto)
    flash_block_q: int = 0
    flash_block_kv: int = 0
    # Heads-major (B, H, T, Dh) q/k/v for the flash TRAINING path: produced
    # straight from the projection einsum so the kernel fold is a reshape,
    # not a transpose. Default OFF: the op-level profile showed ~6% of the
    # step in relayout copies around the pallas calls, but the heads-major
    # program measured consistently ~1% SLOWER on v5e (2026-08-01:
    # 124m 43.1 vs 43.8, 1B 46.6 vs 47.0, 350M 42.6 vs 43.0) — XLA moves
    # the layout pressure into the out-projection/residual side. Kept as a
    # probe knob for other hardware/shapes.
    flash_heads_major: bool = False
    # Rematerialization policy applied to each scanned block — see
    # ops/remat.py for what each saves.
    remat: str = "none"  # none | full | dots_saveable | save_attn | save_attn_res | save_qkv_attn | save_big
    # CE head implementation: "chunked" scans token chunks, backward
    # recomputes each chunk's logits (default; handles bias + vocab-sharded
    # TP heads); "dense" SAVES the compute-dtype logits so backward
    # recomputes nothing — S*V*2 bytes of head memory for zero recompute
    # FLOPs (the right trade at small batch or remat="none"; won the 124M
    # race post CE-scatter fix). "fused" is an EXPERIMENT, not a product
    # path: the Pallas online-logsumexp kernel (ops/pallas_ce.py) is
    # interpret-mode correct but hung the v5e chip three times across two
    # remat configs (2026-07/08, multi-hour backend wedges) and measured
    # SLOWER everywhere it completed (29.9-31.5% vs 40+% MFU at 124M);
    # it is excluded from every capture campaign as a wedge class. Keep
    # chunked/dense for real runs; degrades loudly to chunked for biased
    # or tensor-sharded heads.
    ce_impl: str = "chunked"  # chunked | fused | dense
    # z-loss coefficient (PaLM/ST-MoE): adds z * mean(logsumexp(logits)^2)
    # to the training loss, pinning the softmax normalizer near 0 —
    # stabilizes large-scale bf16 training. 0 = off. chunked/dense CE
    # heads only (the fused Pallas kernel does not implement it).
    z_loss_coef: float = 0.0
    # Unroll factor for the depth scan (1 = fully rolled). Unrolling lets XLA
    # fuse across layer boundaries at the cost of compile time.
    scan_unroll: int = 1
    # Fully unroll the depth scan for SINGLE-TOKEN cached decode steps.
    # The rolled layer scan nests a while loop inside the token-decode scan,
    # and XLA inserts full-cache copies at the loop boundary every decode
    # step (measured via AOT HLO: 4 cache-shaped copies/step at gpt2-124m
    # b8/320 slots — ~140 MB/step of pure copy traffic — plus ~110 MB temp;
    # unrolling removes the inner loop and ALL cache copies, letting the
    # token scan update the cache in place). Decode-only: prefill (Tq>1)
    # and training keep scan_unroll. Default off until measured on-chip —
    # scan-unroll is an unproven kernel-config class on this backend
    # (tpu_capture RISKY_STAGES).
    decode_unroll_layers: bool = False
    # Decode KV-cache container layout. 'unstacked' (default): a tuple of
    # per-layer (B, T, G, Dh) caches with a trace-time python loop over
    # layers — each leaf is updated in place via one dynamic-update-slice
    # on the token-scan carry (the aliasable pattern). 'stacked': one
    # (L, B, T, G, Dh) array per field riding the depth scan — profiled on
    # v5e at ~50% of the decode step in pure cache MOVEMENT (the scan's
    # ys-stacking makes a fresh (L, ...) buffer every token step, so the
    # token-scan carry cannot alias and XLA copies the whole cache back
    # in, plus per-layer slice/update-slice relayouts). Measured
    # 2026-08-01 at gpt2-124m b8: unstacked 6,856 tok/s vs stacked 4,129
    # (+66%). Semantics identical (tested: greedy/ragged/int8).
    decode_cache_layout: str = "unstacked"
    # Unstacked-layout dispatch boundary: multi-token cached forwards with
    # Tq <= this take the in-place per-layer loop (single-token decode
    # steps and speculative-decoding verify rounds, where per-call
    # re-stack copies would claw back the unstacked win); larger Tq
    # (prefill buckets start at 16) re-stacks once and runs the rolled
    # scan so the prefill program stays O(1) in depth. Raise it if you
    # run speculative decoding with spec_k >= this value.
    decode_loop_max_tokens: int = 8
    # Shard activations' sequence dim over the 'seq' mesh axis (Megatron-SP)
    sequence_parallel: bool = False
    # Sliding-window attention (Mistral-style): each query attends only the
    # last `sliding_window` positions (0 = full causal attention). The
    # flash kernel SKIPS blocks entirely outside the window (compute drops
    # from O(T^2) to O(T*W) at long context); cached decode masks old
    # slots. naive/flash paths; ring/ulysses rejected at validation.
    sliding_window: int = 0
    # Packed-document attention masking: >= 0 names the document-separator
    # token id (the EOT the preprocessor appends per document); attention
    # then never crosses a document boundary. Segment ids are derived
    # IN-MODEL from the token stream (exclusive running count of
    # separators) — no data-pipeline change. -1 = off (the reference, and
    # GPT-2/3-style packing, attend across document boundaries).
    # Training/eval only; naive + flash attention paths (ring/ulysses/
    # pipeline compositions are rejected at validation).
    doc_mask_token: int = -1
    # Mixture-of-experts MLP (0 = dense). Experts shard over the 'expert' mesh
    # axis; routing is dense einsum dispatch with a per-expert capacity bound.
    n_experts: int = 0
    experts_per_token: int = 2
    expert_capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # Tokens per routing group: capacity pools are per-group so dispatch
    # memory is O(S * k * C_group), linear in batch, not O(S^2). Group count
    # derives from the token count only (mesh-independent routing). 0 = one
    # global group (tiny-shape/testing escape hatch).
    moe_group_size: int = 2048
    # Pipeline parallelism: split the layer stack into stages over the 'pipe'
    # mesh axis, GPipe microbatch schedule via ppermute. 1 = off.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4
    # Virtual stages per rank (Megatron-style interleaving): each rank hosts
    # this many round-robin depth chunks, shrinking the pipeline bubble by
    # the same factor. 1 = plain GPipe. Requires microbatches >= stages.
    pipeline_interleave: int = 1
    # KV-cache element type for decode: "compute" stores compute_dtype;
    # "int8" quantizes K/V per (token, head) with an fp32 amax scale —
    # halves persistent cache HBM vs bf16 (the serving memory term that
    # scales with L*B*T). Prefill attention always runs on the unquantized
    # local block; only decode-step reads dequantize.
    kv_cache_dtype: str = "compute"  # compute | int8
    # Paged (serving) decode attention: "gather" assembles each row's KV
    # with pool[tables] before a masked einsum (proven path); "kernel"
    # runs the Pallas block-table kernel (ops/pallas_paged.py) that reads
    # pool pages directly — no gathered copy is ever written, cutting the
    # per-layer decode KV traffic ~3x at large batch*context. int8 pools
    # compose with both: "gather" dequantizes after the pool gather,
    # "kernel" fuses the scale-page dequant into the ragged kernel's page
    # loop (only int8 bytes + scales cross HBM).
    paged_attention_impl: str = "gather"  # gather | kernel
    # Ragged-kernel speed knobs (paged_attention_impl="kernel" only; the
    # gather path ignores both). `ragged_kv_splits` partitions each row's
    # page range across that many parallel grid lanes (FA2 work
    # partitioning with a log-sum-exp combine): 1 = single-pass kernel
    # (the pre-split default, bit-compatible), 0 = auto-tune from
    # (max_pages, B), >1 = forced count. `ragged_amla` switches the
    # online softmax to AMLA's exp2 MUL-by-ADD rescale (per-page
    # correction as an exponent-field add; int8 dequant scales absorbed
    # into the same restructure). Defaults keep the proven numerics —
    # flips are bench-gated (BASELINE.md re-race procedure).
    ragged_kv_splits: int = 1  # 0 = auto | 1 = off | >1 = forced
    ragged_amla: bool = False

    def __post_init__(self) -> None:
        if self.kv_cache_dtype not in ("compute", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'compute' or 'int8', got "
                f"{self.kv_cache_dtype!r}"
            )
        if self.paged_attention_impl not in ("gather", "kernel"):
            raise ValueError(
                f"paged_attention_impl must be 'gather' or 'kernel', got "
                f"{self.paged_attention_impl!r}"
            )
        # int8 pools work with BOTH paged impls: "gather" dequantizes
        # after the pool gather, "kernel" routes every query shape through
        # the ragged kernel, which fuses the scale-page dequant into its
        # page loop (ops/pallas_ragged.py).
        if self.ragged_kv_splits < 0:
            raise ValueError(
                f"ragged_kv_splits must be >= 0 (0 = auto), got "
                f"{self.ragged_kv_splits}"
            )
        if self.activation not in _ACTIVATIONS:
            raise ValueError(f"activation must be one of {_ACTIVATIONS}, got {self.activation!r}")
        if self.norm not in _NORMS:
            raise ValueError(f"norm must be one of {_NORMS}, got {self.norm!r}")
        if self.pos_embed not in _POS_EMBEDS:
            raise ValueError(f"pos_embed must be one of {_POS_EMBEDS}, got {self.pos_embed!r}")
        if self.attention_impl not in _ATTN_IMPLS:
            raise ValueError(
                f"attention_impl must be one of {_ATTN_IMPLS}, got {self.attention_impl!r}"
            )
        if self.remat not in _REMAT_POLICIES:
            raise ValueError(f"remat must be one of {_REMAT_POLICIES}, got {self.remat!r}")
        if self.decode_cache_layout not in ("stacked", "unstacked"):
            raise ValueError(
                "decode_cache_layout must be 'stacked' or 'unstacked', got "
                f"{self.decode_cache_layout!r}"
            )
        if self.decode_loop_max_tokens < 1:
            raise ValueError(
                f"decode_loop_max_tokens must be >= 1, got "
                f"{self.decode_loop_max_tokens}"
            )
        if self.decode_unroll_layers and self.decode_cache_layout != "stacked":
            # The unroll knob only means something on the stacked depth
            # scan; silently ignoring it under the unstacked layout would
            # bank mislabeled measurements.
            raise ValueError(
                "decode_unroll_layers requires decode_cache_layout="
                "'stacked' (the unstacked layout has no depth scan to "
                "unroll)"
            )
        if self.ce_impl not in ("chunked", "fused", "dense"):
            raise ValueError(
                f"ce_impl must be 'chunked', 'fused' or 'dense', got {self.ce_impl!r}"
            )
        if self.ring_layout not in ("contiguous", "zigzag"):
            raise ValueError(
                f"ring_layout must be 'contiguous' or 'zigzag', got {self.ring_layout!r}"
            )
        if self.d_model % self.n_heads != 0 and self.d_head is None:
            raise ValueError(
                f"d_model={self.d_model} not divisible by n_heads={self.n_heads}; set d_head"
            )
        if self.n_kv_heads is not None and (
            not 1 <= self.n_kv_heads <= self.n_heads
            or self.n_heads % self.n_kv_heads != 0
        ):
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must divide n_heads={self.n_heads}"
            )
        if not self.use_output_proj and self.head_dim * self.n_heads != self.d_model:
            raise ValueError("use_output_proj=False requires n_heads*d_head == d_model")
        if self.tie_embeddings and self.lm_head_bias:
            raise ValueError("tie_embeddings is incompatible with lm_head_bias")
        if self.n_experts:
            if not 1 <= self.experts_per_token <= self.n_experts:
                raise ValueError(
                    f"experts_per_token={self.experts_per_token} must be in "
                    f"[1, n_experts={self.n_experts}]"
                )
            if self.expert_capacity_factor <= 0:
                raise ValueError("expert_capacity_factor must be positive")
            if self.moe_group_size < 0:
                raise ValueError("moe_group_size must be >= 0 (0 = one global group)")
        if self.pipeline_stages < 1 or self.n_layers % self.pipeline_stages != 0:
            raise ValueError(
                f"pipeline_stages={self.pipeline_stages} must divide "
                f"n_layers={self.n_layers}"
            )
        if self.pipeline_microbatches < 1:
            raise ValueError("pipeline_microbatches must be >= 1")
        if self.pipeline_interleave < 1 or (
            self.n_layers % (self.pipeline_stages * self.pipeline_interleave) != 0
        ):
            raise ValueError(
                f"pipeline_interleave={self.pipeline_interleave} x "
                f"pipeline_stages={self.pipeline_stages} must divide "
                f"n_layers={self.n_layers}"
            )
        if self.pipeline_interleave > 1:
            if self.pipeline_stages == 1:
                raise ValueError(
                    "pipeline_interleave > 1 does nothing without "
                    "pipeline_stages > 1"
                )
            if self.pipeline_microbatches < self.pipeline_stages:
                raise ValueError(
                    "pipeline_interleave > 1 requires pipeline_microbatches >= "
                    f"pipeline_stages ({self.pipeline_microbatches} < "
                    f"{self.pipeline_stages})"
                )
        if self.pipeline_stages > 1 and (
            self.attention_impl in ("ring", "ulysses") or self.sequence_parallel
        ):
            raise ValueError(
                "pipeline parallelism does not compose with sequence/context "
                "parallelism (ring/ulysses attention or sequence_parallel)"
            )
        if self.z_loss_coef < 0:
            raise ValueError("z_loss_coef must be >= 0")
        if self.z_loss_coef > 0 and self.ce_impl == "fused":
            raise ValueError(
                "z_loss_coef requires ce_impl='chunked' or 'dense' (the "
                "fused Pallas CE kernel does not implement the z term)"
            )
        if self.sliding_window < 0:
            raise ValueError("sliding_window must be >= 0 (0 = full causal)")
        if self.sliding_window > 0 and self.attention_impl in ("ring", "ulysses"):
            raise ValueError(
                "sliding_window is not supported by ring/ulysses attention "
                "(the rotating/all-to-all layouts assume full causal KV)"
            )
        if self.doc_mask_token >= 0:
            if self.attention_impl in ("ring", "ulysses"):
                raise ValueError(
                    "doc_mask_token (packed-document masking) is not "
                    "supported by ring/ulysses attention — segment ids are "
                    "not threaded through their collectives"
                )
            if self.pipeline_stages > 1:
                raise ValueError(
                    "doc_mask_token does not compose with pipeline "
                    "parallelism (segments are not threaded through the "
                    "pipelined block path)"
                )
            if self.doc_mask_token >= self.vocab_size:
                raise ValueError(
                    f"doc_mask_token={self.doc_mask_token} is outside the "
                    f"vocabulary (vocab_size={self.vocab_size})"
                )

    @property
    def head_dim(self) -> int:
        return self.d_head if self.d_head is not None else self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def d_ff(self) -> int:
        return int(self.mlp_ratio * self.d_model)

    def num_params(self) -> int:
        """Analytic parameter count (matches init_params exactly; tested)."""
        d, h, dh, f, v, t = (
            self.d_model,
            self.n_heads,
            self.head_dim,
            self.d_ff,
            self.vocab_size,
            self.context_length,
        )
        n = v * d  # token embedding
        if self.pos_embed == "learned":
            n += t * d
        g = self.kv_heads
        per_block = 0
        per_block += 2 * self._norm_params()  # ln1, ln2
        per_block += d * h * dh + 2 * d * g * dh  # wqkv (or wq + wkv for GQA)
        if self.qkv_bias:
            per_block += h * dh + 2 * g * dh
        if self.use_output_proj:
            per_block += h * dh * d + d  # wo + bias
        per_expert = self._per_expert_params()
        if self.n_experts:
            per_block += d * self.n_experts  # router
            per_block += self.n_experts * per_expert
        else:
            per_block += per_expert
        n += self.n_layers * per_block
        n += self._norm_params()  # final norm
        if not self.tie_embeddings:
            n += d * v
            if self.lm_head_bias:
                n += v
        return n

    def _norm_params(self) -> int:
        return 2 * self.d_model if self.norm == "layernorm" else self.d_model

    def _per_expert_params(self) -> int:
        """One FFN's parameter count (the dense MLP, or one MoE expert)."""
        d, f = self.d_model, self.d_ff
        if self.activation == "swiglu":
            return d * 2 * f + f * d + ((2 * f + d) if self.mlp_bias else 0)
        return d * f + f * d + ((f + d) if self.mlp_bias else 0)

    def num_active_params(self) -> int:
        """Params a single token's forward actually touches.

        Equal to num_params for dense models; for MoE only experts_per_token
        of the n_experts FFNs execute per token, so MFU/throughput math must
        not count the inactive experts' weights.
        """
        n = self.num_params()
        if self.n_experts:
            inactive = self.n_experts - self.experts_per_token
            n -= self.n_layers * inactive * self._per_expert_params()
        return n

    def flops_per_token(self) -> int:
        """Forward+backward training FLOPs per token (6N_active + attention).

        Standard approximation used for MFU: 6 * active params for matmul
        parameters plus the attention score/value matmul term (the O(T^2)
        part). Per layer per token the QK^T and attn@V matmuls each cost
        2*T*(n_heads*d_head) forward FLOPs, x3 for fwd+bwd = 12*T*d_attn —
        note d_attn is the *query* attention width ``n_heads * d_head``
        (GQA shrinks KV projections, not the score matmuls), which differs
        from d_model whenever d_head is set explicitly. Causal attention
        computes only ~half the score matrix (and our flash kernel really
        does skip masked blocks), so the O(T^2) term carries a 1/2 factor —
        counting the full square would overstate MFU on long contexts.
        MoE counts only the experts_per_token experts a token executes.
        """
        d_attn = self.n_heads * self.head_dim
        return (
            6 * self.num_active_params()
            + 12 * self.n_layers * d_attn * self.context_length // 2
        )


# ---------------------------------------------------------------------------
# Mesh
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh: (data, fsdp, tensor, seq, expert, pipe) axes.

    Replaces the reference's DDP process-group bootstrap
    (`/root/reference/scripts/train_transformer.py:15-29`). One axis per
    parallelism strategy; axes of size 1 cost nothing. ``data=-1`` absorbs all
    remaining devices.
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    axis_names: Tuple[str, ...] = ("data", "fsdp", "tensor", "seq", "expert", "pipe")

    def sizes(self, n_devices: int) -> Tuple[int, ...]:
        fixed = self.fsdp * self.tensor * self.seq * self.expert * self.pipe
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fsdp*tensor*seq*expert*pipe={fixed}"
                )
            data = n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.tensor}x{self.seq}"
                f"x{self.expert}x{self.pipe} != {n_devices} devices"
            )
        return (data, self.fsdp, self.tensor, self.seq, self.expert, self.pipe)


# ---------------------------------------------------------------------------
# Data
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DataConfig:
    """Data pipeline config.

    Token files are flat uint16 memmaps — the same on-disk format as the
    reference's preprocessor output (`/root/reference/scripts/data_preprocess.py:47-62`)
    so existing datasets drop in unchanged.
    """

    train_path: str = "data/train.bin"
    val_path: str = "data/val.bin"
    dataset_name: str = "openwebtext"
    tokenizer_name: str = "gpt2"
    val_fraction: float = 0.0005
    split_seed: int = 42
    sample_seed: int = 1337  # reference uses unseeded torch.randint (Q1) — we seed
    prefetch: int = 2  # double-buffered device_put prefetch depth
    use_native_batcher: bool = True  # C++ batch gather when the extension is built


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------

_LR_SCHEDULES = ("warmup_constant", "warmup_cosine", "warmup_stable_decay")


@dataclass(frozen=True)
class TrainConfig:
    batch_size: int = 32  # global batch (sequences per optimizer step)
    microbatches: int = 1  # gradient accumulation via lax.scan
    train_steps: int = 200_000
    eval_interval: int = 1000
    eval_iters: int = 250
    lr: float = 3e-4
    lr_schedule: str = "warmup_cosine"  # reference: 10% warmup then constant
    # "adamw" (reference behavior), "adafactor" (factored second moments,
    # ~0.3 bytes/param optimizer state vs Adam's 8 — fits 1B+ models on one
    # chip), or "muon" (momentum + Newton-Schulz orthogonalization for
    # hidden weight matrices, AdamW for embeddings/head/vectors — batched
    # matmul iterations, MXU-native; see training/optimizer.py).
    optimizer: str = "adamw"
    muon_momentum: float = 0.95  # muon only: nesterov momentum coefficient
    warmup_frac: float = 0.1
    min_lr_frac: float = 0.1  # cosine/decay floor as a fraction of lr
    # warmup_stable_decay (WSD) only: fraction of train_steps spent in the
    # final linear decay phase (warmup -> constant lr -> linear to
    # min_lr_frac*lr). The stable phase makes mid-run checkpoints
    # continuation-friendly (no cosine horizon baked in).
    decay_frac: float = 0.1
    weight_decay: float = 0.1
    adam_b1: float = 0.9
    adam_b2: float = 0.95
    adam_eps: float = 1e-8
    grad_clip: float = 1.0  # 0 disables
    # Gradient STORAGE dtype. "float32" (default): the backward's output
    # tree materializes in fp32 — exact, but at 1B it is ~5 GB of the
    # 16 GB chip, the term that pins the batch knee at b8 when the
    # end-of-backward state is the peak. "bfloat16": each gradient leaf
    # is cast to bf16 as the backward produces it (XLA fuses the convert
    # into the producer), so the gradient tree and the microbatch
    # accumulator store 2 bytes/param; the fp32 cotangent chain is
    # unchanged — grads are the fp32-path values rounded once. Norm/clip
    # math and every optimizer update still reduce in fp32 per-leaf.
    # Precision note: bf16 grad storage shifts training numerics
    # slightly (Adafactor's RMS normalization absorbs most of it);
    # parity/golden runs keep float32. (Implementation note: the
    # alternative — differentiating a bf16 param VIEW — pins a full
    # bf16 param copy across the backward, AOT-measured +2.8 GiB at 1B,
    # cancelling the saving; this knob uses the cast-after-grad form.)
    grad_dtype: str = "float32"  # float32 | bfloat16
    # Exponential moving average of the params (0 = off): a fp32 shadow
    # updated after every optimizer step (ema = d*ema + (1-d)*params),
    # stored at state["ema"], checkpointed/sharded like the params.
    # Consume via `evaluate.py --ema`, `generate_text.py --ema`, or the
    # `--ema` flag on the torch/HF exporters. Typical d: 0.999-0.9999.
    ema_decay: float = 0.0
    seed: int = 0
    checkpoint_dir: str = "checkpoints"
    checkpoint_interval: int = 1000  # reference saves only once at the end
    keep_checkpoints: int = 3
    # Write checkpoint files on a background thread so the step loop never
    # stalls on disk IO (the device->host snapshot stays synchronous for
    # exactness). Single-process only: multi-host saves keep the internal
    # barrier on the main thread.
    checkpoint_async: bool = False
    # Write a final checkpoint when the run ends off a checkpoint boundary
    # (the reference's end-of-run save). False for throwaway runs —
    # benchmarks, smoke tests — that must not leave resumable state behind
    # or pay a synchronous full-state write inside a timed region.
    save_final: bool = True
    log_interval: int = 10
    metrics_path: str = ""  # JSONL sink; "" = stdout only
    debug_nans: bool = False  # op-level NaN detection (slow; debugging only)
    profile_dir: str = ""  # capture a profiler trace window into this dir
    profile_start: int = 10  # first step of the trace window
    profile_steps: int = 5  # trace window length

    def __post_init__(self) -> None:
        if self.lr_schedule not in _LR_SCHEDULES:
            raise ValueError(f"lr_schedule must be one of {_LR_SCHEDULES}")
        if self.optimizer not in ("adamw", "adafactor", "muon"):
            raise ValueError(
                "optimizer must be 'adamw', 'adafactor', or 'muon', "
                f"got {self.optimizer!r}"
            )
        if not 0.0 < self.decay_frac <= 1.0:
            raise ValueError(
                f"decay_frac must be in (0, 1], got {self.decay_frac}"
            )
        if not 0.0 <= self.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in [0, 1), got {self.ema_decay}"
            )
        if self.batch_size % self.microbatches != 0:
            raise ValueError(
                f"batch_size={self.batch_size} not divisible by microbatches={self.microbatches}"
            )
        if self.grad_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"grad_dtype must be 'float32' or 'bfloat16', got "
                f"{self.grad_dtype!r}"
            )


# ---------------------------------------------------------------------------
# Resilience
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance knobs: anomaly detection, rollback, watchdog, faults.

    Everything here is host-side and off the hot path — the detector reads
    the metrics the trainer already fetched at log boundaries, the watchdog
    is one idle thread, and fault injection is a no-op unless ``faults`` is
    set. See resilience/ for the machinery and README "Fault tolerance" for
    the operational story (return codes, supervisor).
    """

    # --- anomaly detection (log-boundary metrics; free on the hot path) ----
    anomaly_detection: bool = False
    # Rolling window (in log-boundary samples) the spike baselines are
    # computed over. NaN/Inf detection needs no history and is always armed.
    anomaly_window: int = 32
    # Samples required before the relative-spike rules arm — an empty
    # baseline would flag ordinary early-training noise.
    anomaly_min_history: int = 5
    # loss > factor * rolling-median(loss) => anomaly ("loss_spike").
    loss_spike_factor: float = 3.0
    # grad_norm > factor * rolling-median(grad_norm) => anomaly ("grad_spike").
    grad_spike_factor: float = 10.0
    # --- rollback ----------------------------------------------------------
    # Max automatic checkpoint rollbacks per train() call; the next anomaly
    # past the budget ends the run with exit_reason="anomaly_budget"
    # (EXIT_ANOMALY, which the supervisor treats as fatal).
    rollback_budget: int = 3
    # Steps after a rollback during which new anomalies are suppressed
    # (logged, not acted on) while the detector rebuilds its baseline.
    cooldown_steps: int = 0
    # Extra batches to skip PAST the poison window on rollback. The window
    # itself (anomaly step - restored step batches) is always skipped; this
    # adds margin when the offending data region is wider than one window.
    skip_batches: int = 0
    # --- watchdog ----------------------------------------------------------
    # Host seconds without a completed step before the watchdog declares the
    # step wedged (stuck collective / hung chip), dumps all thread stacks,
    # attempts an emergency checkpoint, and exits EXIT_WEDGED. 0 = off.
    # Arms only after the first step completes, so compile time is excluded.
    watchdog_timeout_s: float = 0.0
    # --- fault injection (tests/drills only) -------------------------------
    # Deterministic fault plan, e.g. "nan@20,sigterm@50,hang@30,
    # ckpt_truncate@40": each entry fires once, right before the named step
    # executes. A resumed run does not re-fire faults at or below its start
    # step. "" = disabled.
    faults: str = ""

    def __post_init__(self) -> None:
        if self.anomaly_window < 2:
            raise ValueError(
                f"anomaly_window must be >= 2, got {self.anomaly_window}"
            )
        if self.anomaly_min_history < 1:
            raise ValueError(
                f"anomaly_min_history must be >= 1, got {self.anomaly_min_history}"
            )
        if self.loss_spike_factor <= 1.0 or self.grad_spike_factor <= 1.0:
            raise ValueError(
                "spike factors must be > 1 (a factor <= 1 flags every step): "
                f"loss={self.loss_spike_factor}, grad={self.grad_spike_factor}"
            )
        if self.rollback_budget < 0:
            raise ValueError(
                f"rollback_budget must be >= 0, got {self.rollback_budget}"
            )
        if self.cooldown_steps < 0 or self.skip_batches < 0:
            raise ValueError("cooldown_steps and skip_batches must be >= 0")
        if self.watchdog_timeout_s < 0:
            raise ValueError(
                f"watchdog_timeout_s must be >= 0, got {self.watchdog_timeout_s}"
            )
        if self.faults:
            # Fail fast on a malformed plan (lazy import: resilience.faults
            # has no config dependency, but config loads first in the
            # package import order).
            from pretraining_llm_tpu.resilience.faults import parse_faults

            parse_faults(self.faults)


# ---------------------------------------------------------------------------
# Observability
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ObservabilityConfig:
    """Run-wide telemetry knobs: events, spans, goodput export, HBM samples.

    The in-memory pieces (event bus, goodput accounting, compile counting)
    always run — they cost a few host-side dict updates per LOG BOUNDARY and
    nothing per step. The fields here gate the file sinks and samplers. See
    observability/ for the machinery and README "Observability" for usage.
    """

    # Run-event JSONL sink ("" = in-memory only). Events still reach the
    # goodput accountant and the metrics logger's `goodput` field without it;
    # the file is what scripts/obs_report.py and multi-run folds consume.
    events_path: str = ""
    # Chrome trace-event JSON of host-side spans, written at train() exit
    # ("" = off). Open in Perfetto alongside the --profile xplane dumps.
    spans_path: str = ""
    # Prometheus textfile (node-exporter textfile-collector format),
    # atomically rewritten at every log boundary and at run end ("" = off).
    prometheus_path: str = ""
    # Sample per-device HBM (Device.memory_stats) every N log boundaries
    # (0 = off). A host-side allocator query — no device sync.
    device_memory_interval: int = 0
    # Count backend compiles via jax.monitoring; compiles after the first
    # completed step become `recompile` events (a recompile storm shows up
    # in the stream instead of only as lost MFU).
    compile_telemetry: bool = True

    def __post_init__(self) -> None:
        if self.device_memory_interval < 0:
            raise ValueError(
                f"device_memory_interval must be >= 0, got "
                f"{self.device_memory_interval}"
            )


@dataclass(frozen=True)
class ServingConfig:
    """Decode-serving scheduler knobs (generation/serving.py).

    These gate host-side scheduling only — they never change emitted tokens
    (the greedy output contract in ServingEngine.run holds at every depth).
    """

    # In-flight decode-window queue depth for the pipelined scheduler:
    # how many dispatched-but-unreaped windows the engine keeps queued
    # before it blocks on the oldest. 1 reproduces the classic
    # double-buffered scheduler (reap window k-1 right after dispatching
    # window k); 2 lets the host reap/consume/admit a full window behind
    # the device, hiding the host work of one boundary entirely.
    pipeline_depth: int = 2
    # Cross-window admission batching: defer waiting prefills until at
    # least this many could be admitted in one batched prefill (0 or 1 =
    # admit eagerly every boundary). Deferral only happens while the
    # device still has active rows — an idle engine always admits
    # whatever fits, so batching can never deadlock the queue.
    admit_batch: int = 0
    # Cross-request prefix cache (generation/prefix_cache.py): finished
    # requests publish their full KV blocks into a content-addressed
    # index; new admissions map the longest cached block-aligned prefix
    # read-only and prefill only the uncached suffix. Cold cached blocks
    # are LRU-evicted under pool pressure, before any live preemption.
    # Off by default; greedy outputs are bit-identical either way.
    prefix_cache: bool = False
    # Shortest cached prefix (in blocks) worth mapping — below this the
    # table-sharing bookkeeping outweighs the prefill saved.
    prefix_cache_min_blocks: int = 1
    # Chunked prefill: split admitted prompts into chunks of at most this
    # many tokens and stream them in alongside decode windows instead of
    # running one monolithic prefill per admission. Caps how long any
    # single prefill dispatch can stall in-flight decode rows, which is
    # the dominant TTFT head-of-line term under long-prompt mixes. The
    # same budget bounds total chunk tokens per scheduler tick, so decode
    # TPOT is protected. 0 disables (monolithic prefill at admission);
    # greedy outputs are bit-identical either way.
    prefill_chunk_tokens: int = 0
    # KV-page integrity checksums (resilience/integrity.py): record a
    # digest of each published prefix-cache block's pool bytes and verify
    # it when a later request acquires the block — a corrupted shared page
    # is dropped and that request re-prefills privately instead of every
    # future hit inheriting the poison. Digests pull page bytes only at
    # publish/acquire boundaries, never per decode window. Off by default
    # (the zero-device-sync path).
    kv_checksum: bool = False
    # Quantized serving mode (models/quantize.py + the int8 KV pool):
    #   "none"    — bf16 weights, pool dtype per model.kv_cache_dtype.
    #   "int8"    — per-channel int8 block projections (attention + FFN;
    #               embeddings/lm_head/norms/biases stay bf16), dequantized
    #               at each use site with fp32 scales and bf16 accumulation.
    #   "int8-kv" — "int8" PLUS the int8 KV pool with bf16 scale pages:
    #               per-slot bytes drop from 2*Dh to Dh+2, so the pool
    #               holds ~1.94x (Dh=64) the blocks of a bf16 pool at the
    #               same HBM budget. Greedy outputs are deterministic
    #               run-to-run WITHIN the quantized graph (the integrity
    #               sentinel re-pins its golden probes there), but differ
    #               from the bf16 graph — don't mix quantized and exact
    #               replicas behind one sentinel.
    quantize: str = "none"  # none | int8 | int8-kv

    def __post_init__(self) -> None:
        if self.quantize not in ("none", "int8", "int8-kv"):
            raise ValueError(
                "serving.quantize must be 'none', 'int8' or 'int8-kv', "
                f"got {self.quantize!r}"
            )
        if self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {self.pipeline_depth}"
            )
        if self.admit_batch < 0:
            raise ValueError(f"admit_batch must be >= 0, got {self.admit_batch}")
        if self.prefix_cache_min_blocks < 1:
            raise ValueError(
                "prefix_cache_min_blocks must be >= 1, got "
                f"{self.prefix_cache_min_blocks}"
            )
        if self.prefill_chunk_tokens < 0:
            raise ValueError(
                "prefill_chunk_tokens must be >= 0, got "
                f"{self.prefill_chunk_tokens}"
            )


@dataclass(frozen=True)
class FrontendConfig:
    """Online serving gateway knobs (frontend/).

    All host-side: none of these change emitted tokens. They bound what the
    HTTP frontend ADMITS, not how the engine schedules what was admitted.
    """

    # Gateway bind address. Port 0 binds an ephemeral port (tests read it
    # back from ServingGateway.port).
    host: str = "127.0.0.1"
    port: int = 8000
    # Backpressure: max requests admitted and not yet terminal; excess gets
    # HTTP 429 + Retry-After instead of an unbounded queue wait.
    max_queue_depth: int = 64
    # Outstanding-token budget (sum of prompt + max_new over live
    # requests); 0 = unlimited. A depth bound alone cannot tell ten tiny
    # requests from one huge one.
    max_outstanding_tokens: int = 0
    # Retry-After hint (seconds) attached to 429 responses.
    retry_after_s: float = 1.0
    # Reject requests whose optimistic service estimate (decode-only TPOT
    # EWMA) already exceeds their deadline, instead of admitting them to
    # miss it (HTTP 504 at submit time).
    shed_infeasible: bool = True
    # Default per-request deadline applied when the client sends none;
    # 0 = no default deadline.
    default_deadline_s: float = 0.0
    # How long the idle engine-loop thread sleeps between inbox polls.
    idle_wait_s: float = 0.005
    # Per-request tracing: head-sampling fraction for requests without an
    # inbound ``traceparent`` (whose own sampled flag is honored). 0 =
    # tracing off — the default, and the zero-cost path.
    trace_sample: float = 0.0
    # Chrome-trace JSON export path, written at gateway shutdown when
    # tracing is on ("" = no export).
    trace_path: str = ""
    # /healthz returns 503 once the engine loop has gone this many
    # seconds without completing a scheduler turn. 0 disables — the
    # default, because a cold-start jit compile legitimately holds the
    # loop thread for minutes on slow hosts.
    healthz_stale_after_s: float = 0.0
    # Capacity observability ring size: per-window occupancy samples and
    # scheduler decision records kept live for /debug/* (the event-bus
    # JSONL keeps everything regardless). 0 disables the layer.
    capacity_ring: int = 512
    # ---- fleet (frontend/router.py); replicas=1 keeps the single
    # EngineLoop path with zero router overhead. -----------------------
    # Number of engine replicas behind the router tier.
    replicas: int = 1
    # Where each replica's engine lives: "inproc" (an EngineLoop thread
    # in the gateway process) or "process" (one worker subprocess per
    # replica — frontend/worker.py — behind a socket, so a kill -9 or a
    # dropped connection is a REAL fault domain, not a simulated one).
    # The router/sentinel/gateway contract is identical in both modes.
    replica_mode: str = "inproc"
    # Prefix-affinity routing: prompt tokens hashed for placement. 0
    # disables affinity (pure least-loaded).
    affinity_tokens: int = 32
    # Spill off the affinity choice when it carries this many more
    # in-system requests than the least-loaded replica.
    spill_margin: int = 4
    # Watchdog: eject a replica whose loop has active requests but has
    # not completed a scheduler turn for this long. 0 disables (same
    # cold-jit rationale as healthz_stale_after_s).
    wedged_after_s: float = 0.0
    # Relaunch backoff for ejected replicas: initial and cap (doubles).
    eject_backoff_s: float = 0.5
    eject_backoff_max_s: float = 8.0
    # Max failovers per request before it errors out (renamed from
    # ``redrive_max`` — see MIGRATION.md): a request that kills every
    # replica it lands on gets a clean terminal error after this many
    # attempts instead of fueling a redrive storm.
    redrive_max_attempts: int = 3
    # Brownout: when the healthy fraction of the fleet drops below this,
    # shed low-priority / long-deadline work with 429. 0 disables.
    brownout_min_healthy_frac: float = 0.0
    # Under brownout: shed requests with priority below this ...
    brownout_min_priority: int = 1
    # ... or deadline longer than this (0 = don't shed on deadline).
    brownout_max_deadline_s: float = 0.0
    # ---- multi-host fleet (replica_mode="process" only). -------------
    # Attach to pre-spawned workers (``worker.py --listen host:port``)
    # instead of spawning subprocesses: comma-separated "host:port" list,
    # one address per replica ("" = spawn locally). Attached workers are
    # detached (never killed) at teardown, and the stdin-orphan watch is
    # replaced by heartbeat leases.
    worker_attach: str = ""
    # Shared secret for the attach handshake: the first frame on a new
    # connection must be a hello carrying this token or the worker drops
    # the connection ("" = no auth; spawn mode ignores it).
    attach_token: str = ""
    # Heartbeat lease: a worker that hears nothing from its router for
    # this long stops admitting, drains, and parks; the router, hearing
    # nothing back, redrives the worker's in-flight work. 0 disables
    # (spawn mode's stdin-orphan + conn-EOF detection still applies).
    lease_s: float = 0.0
    # Write-ahead fleet journal (append-only JSONL): membership, fence
    # generations, and per-request committed frontiers, enough for a
    # restarted router to re-attach survivors, fence the old generation,
    # and redrive in-flight requests bit-identically ("" = no journal).
    journal_path: str = ""
    # Journal compaction threshold in MB: once the JSONL grows past this,
    # the journal rewrites itself down to its recovery_plan fold (fences,
    # live request frontiers, next_frid) via an atomic tmp+rename. 0
    # disables rotation (the journal grows without bound).
    journal_rotate_mb: float = 64.0
    # Serving-path fault plan, e.g. "replica_crash@req3:r0,slow_window@req5"
    # ("" = none). See resilience.faults.parse_serving_faults.
    serving_faults: str = ""
    # Retry-After jitter: 429/503 headers carry base * U[1, 1+frac],
    # drawn from a PRNG seeded with retry_jitter_seed (deterministic for
    # tests; decorrelates client retry herds in prod).
    retry_jitter_frac: float = 0.25
    retry_jitter_seed: int = 0
    # ---- output-integrity sentinel (resilience/integrity.py). All off
    # by default: probes, fingerprints, and checksums add zero device
    # work until a knob turns them on. ---------------------------------
    # Golden-probe period: every interval the router injects a pinned
    # greedy probe into each active replica at strict-lowest priority and
    # quarantines any replica whose output diverges from the reference
    # pinned at startup. 0 disables the sentinel entirely.
    probe_interval_s: float = 0.0
    # How many distinct probes to pin (round-robined across intervals).
    probe_count: int = 2
    # Tokens each probe decodes; longer probes catch subtler divergence
    # at proportionally higher (lowest-priority) cost.
    probe_max_new: int = 4
    # Per-replica weight fingerprint recompute period (computed on each
    # loop thread between scheduler turns; compared by the sentinel
    # against the value pinned at launch). 0 disables.
    weight_fingerprint_interval_s: float = 0.0

    def __post_init__(self) -> None:
        if not 0 <= self.port <= 65535:
            raise ValueError(f"port must be in [0, 65535], got {self.port}")
        if not 0.0 <= self.trace_sample <= 1.0:
            raise ValueError(
                f"trace_sample must be in [0, 1], got {self.trace_sample}"
            )
        if self.healthz_stale_after_s < 0:
            raise ValueError(
                f"healthz_stale_after_s must be >= 0, got "
                f"{self.healthz_stale_after_s}"
            )
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}"
            )
        if self.max_outstanding_tokens < 0:
            raise ValueError(
                f"max_outstanding_tokens must be >= 0, got "
                f"{self.max_outstanding_tokens}"
            )
        if self.retry_after_s <= 0:
            raise ValueError(
                f"retry_after_s must be > 0, got {self.retry_after_s}"
            )
        if self.default_deadline_s < 0:
            raise ValueError(
                f"default_deadline_s must be >= 0, got {self.default_deadline_s}"
            )
        if self.idle_wait_s <= 0:
            raise ValueError(f"idle_wait_s must be > 0, got {self.idle_wait_s}")
        if self.capacity_ring < 0:
            raise ValueError(
                f"capacity_ring must be >= 0 (0 disables), got "
                f"{self.capacity_ring}"
            )
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.affinity_tokens < 0:
            raise ValueError(
                f"affinity_tokens must be >= 0, got {self.affinity_tokens}"
            )
        if self.spill_margin < 1:
            raise ValueError(
                f"spill_margin must be >= 1, got {self.spill_margin}"
            )
        if self.wedged_after_s < 0:
            raise ValueError(
                f"wedged_after_s must be >= 0, got {self.wedged_after_s}"
            )
        if self.eject_backoff_s <= 0:
            raise ValueError(
                f"eject_backoff_s must be > 0, got {self.eject_backoff_s}"
            )
        if self.eject_backoff_max_s < self.eject_backoff_s:
            raise ValueError(
                "eject_backoff_max_s must be >= eject_backoff_s, got "
                f"{self.eject_backoff_max_s} < {self.eject_backoff_s}"
            )
        if self.replica_mode not in ("inproc", "process"):
            raise ValueError(
                f"replica_mode must be 'inproc' or 'process', got "
                f"{self.replica_mode!r}"
            )
        if self.redrive_max_attempts < 0:
            raise ValueError(
                f"redrive_max_attempts must be >= 0, got "
                f"{self.redrive_max_attempts}"
            )
        if self.lease_s < 0:
            raise ValueError(f"lease_s must be >= 0, got {self.lease_s}")
        if self.journal_rotate_mb < 0:
            raise ValueError(
                f"journal_rotate_mb must be >= 0 (0 disables rotation), "
                f"got {self.journal_rotate_mb}"
            )
        if self.worker_attach:
            if self.replica_mode != "process":
                raise ValueError(
                    "worker_attach needs replica_mode='process', got "
                    f"{self.replica_mode!r}"
                )
            addrs = [a.strip() for a in self.worker_attach.split(",")]
            if len(addrs) != self.replicas:
                raise ValueError(
                    f"worker_attach lists {len(addrs)} addresses for "
                    f"{self.replicas} replicas"
                )
            for a in addrs:
                host, _, port_s = a.rpartition(":")
                if not host or not port_s.isdigit():
                    raise ValueError(
                        f"worker_attach address {a!r} is not host:port"
                    )
        if self.attach_token and not self.worker_attach:
            raise ValueError("attach_token needs worker_attach addresses")
        if not 0.0 <= self.brownout_min_healthy_frac <= 1.0:
            raise ValueError(
                "brownout_min_healthy_frac must be in [0, 1], got "
                f"{self.brownout_min_healthy_frac}"
            )
        if self.brownout_max_deadline_s < 0:
            raise ValueError(
                "brownout_max_deadline_s must be >= 0, got "
                f"{self.brownout_max_deadline_s}"
            )
        if not 0.0 <= self.retry_jitter_frac <= 1.0:
            raise ValueError(
                "retry_jitter_frac must be in [0, 1], got "
                f"{self.retry_jitter_frac}"
            )
        if self.probe_interval_s < 0:
            raise ValueError(
                f"probe_interval_s must be >= 0, got {self.probe_interval_s}"
            )
        if self.probe_count < 1:
            raise ValueError(
                f"probe_count must be >= 1, got {self.probe_count}"
            )
        if self.probe_max_new < 1:
            raise ValueError(
                f"probe_max_new must be >= 1, got {self.probe_max_new}"
            )
        if self.weight_fingerprint_interval_s < 0:
            raise ValueError(
                "weight_fingerprint_interval_s must be >= 0, got "
                f"{self.weight_fingerprint_interval_s}"
            )


# ---------------------------------------------------------------------------
# Top-level
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Config:
    model: ModelConfig = field(default_factory=ModelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    obs: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    frontend: FrontendConfig = field(default_factory=FrontendConfig)
    name: str = "custom"

    # NOTE: pipeline stage assignment (P('pipe', ...) on the stacked layer
    # dim) COMPOSES with the per-weight expert/tensor/fsdp specs — no mesh-
    # combination restriction needed here (seq/ring composition is rejected
    # in ModelConfig).

    def replace(self, **sections: Any) -> "Config":
        return dataclasses.replace(self, **sections)

    def with_overrides(self, overrides: Dict[str, Any]) -> "Config":
        """Apply dotted-path overrides, e.g. {"model.n_layers": 4}.

        Unknown keys raise — the exact failure class the reference ships with
        (SURVEY.md Appendix B) is rejected at startup.
        """
        sections: Dict[str, Dict[str, Any]] = {}
        top: Dict[str, Any] = {}
        for key, value in overrides.items():
            if "." in key:
                section, fname = key.split(".", 1)
                if section not in ("model", "mesh", "data", "train", "resilience", "obs", "serving", "frontend"):
                    raise KeyError(f"unknown config section {section!r} in override {key!r}")
                sections.setdefault(section, {})[fname] = value
            else:
                if key != "name":
                    raise KeyError(f"unknown top-level config key {key!r}")
                top[key] = value
        new = self
        for section, kw in sections.items():
            old = getattr(new, section)
            valid = {f.name for f in dataclasses.fields(old)}
            for k in kw:
                if k not in valid:
                    raise KeyError(f"unknown config key {section}.{k}")
            new = dataclasses.replace(new, **{section: dataclasses.replace(old, **kw)})
        if top:
            new = dataclasses.replace(new, **top)
        return new

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "Config":
        raw = json.loads(text)
        return Config(
            model=ModelConfig(**raw["model"]),
            mesh=MeshConfig(**{k: tuple(v) if k == "axis_names" else v for k, v in raw["mesh"].items()}),
            data=DataConfig(**raw["data"]),
            train=TrainConfig(**raw["train"]),
            # Absent in checkpoints written before the resilience subsystem.
            resilience=ResilienceConfig(**raw.get("resilience", {})),
            # Absent in checkpoints written before the observability subsystem.
            obs=ObservabilityConfig(**raw.get("obs", {})),
            # Absent in checkpoints written before the serving scheduler knobs.
            serving=ServingConfig(**raw.get("serving", {})),
            # Absent in checkpoints written before the serving gateway.
            frontend=FrontendConfig(**raw.get("frontend", {})),
            name=raw.get("name", "custom"),
        )


# ---------------------------------------------------------------------------
# Presets — the 5 BASELINE.json configs + reference parity shape
# ---------------------------------------------------------------------------


def _gpt2_model(**kw: Any) -> ModelConfig:
    base = dict(
        vocab_size=50304,
        activation="gelu",
        norm="layernorm",
        pos_embed="learned",
        use_output_proj=True,
        tie_embeddings=True,
        qkv_bias=True,
        mlp_bias=True,
    )
    base.update(kw)
    return ModelConfig(**base)


def _llama_model(**kw: Any) -> ModelConfig:
    base = dict(
        activation="swiglu",
        norm="rmsnorm",
        pos_embed="rope",
        use_output_proj=True,
        tie_embeddings=False,
        lm_head_bias=False,
        qkv_bias=False,
        mlp_bias=False,
    )
    base.update(kw)
    return ModelConfig(**base)


_PRESETS: Dict[str, Config] = {}


def _register(name: str, cfg: Config) -> None:
    _PRESETS[name] = dataclasses.replace(cfg, name=name)


# BASELINE config #1: GPT-2 124M single-process (tiny-shakespeare, CPU ref)
_register(
    "gpt2-124m",
    Config(
        model=_gpt2_model(
            context_length=1024, d_model=768, n_heads=12, n_layers=12,
            attention_impl="flash",
        ),
        mesh=MeshConfig(),
        train=TrainConfig(batch_size=12, train_steps=5000, lr=6e-4, eval_interval=250, eval_iters=20),
    ),
)

# BASELINE config #2: GPT-2 350M data-parallel on v4-8 (psum grads only)
_register(
    "gpt2-350m-dp",
    Config(
        model=_gpt2_model(
            context_length=1024, d_model=1024, n_heads=16, n_layers=24,
            attention_impl="flash",
        ),
        mesh=MeshConfig(data=-1),
        train=TrainConfig(batch_size=32, lr=3e-4),
    ),
)

# BASELINE config #3: GPT-2 1.3B FSDP-style param/optimizer sharding on v4-32
_register(
    "gpt2-1p3b-fsdp",
    Config(
        model=_gpt2_model(
            context_length=1024, d_model=2048, n_heads=16, n_layers=24,
            remat="dots_saveable", attention_impl="flash",
        ),
        mesh=MeshConfig(data=-1, fsdp=8),
        train=TrainConfig(batch_size=64, lr=2e-4, microbatches=2),
    ),
)

# BASELINE config #4: Llama-style 1B (RoPE + SwiGLU + RMSNorm)
_register(
    "llama-1b",
    Config(
        model=_llama_model(
            vocab_size=32000,
            context_length=2048,
            d_model=2048,
            n_heads=16,
            n_layers=22,
            mlp_ratio=2.6875,  # d_ff = 5504, Llama-style 8/3 rounding
            remat="dots_saveable",
            attention_impl="flash",
        ),
        mesh=MeshConfig(data=-1, fsdp=4),
        train=TrainConfig(batch_size=32, lr=3e-4, weight_decay=0.1),
    ),
)

# BASELINE config #5: 8k-context pretraining, Pallas flash-attn + sequence
# parallel. remat=save_attn: the 2026-08-01 same-day on-chip comparison
# measured save_attn 24.2% vs dots_saveable 23.9% MFU at this preset
# (save_attn also won every gpt2-124m point across rounds).
_register(
    "gpt2-8k-sp",
    Config(
        model=_gpt2_model(
            context_length=8192,
            d_model=768,
            n_heads=12,
            n_layers=12,
            pos_embed="rope",  # learned-absolute does not extrapolate; 8k uses RoPE
            attention_impl="ring",
            sequence_parallel=True,
            remat="save_attn",
        ),
        mesh=MeshConfig(data=-1, seq=4),
        train=TrainConfig(batch_size=8, lr=3e-4),
    ),
)

# Beyond-parity: the 8k preset with grouped-query attention (12 query
# heads over 3 KV heads -> 4x less KV bandwidth). At long context the
# flash kernel's K/V streaming is the wall (8k measured 24.2% vs 43.8%
# at 1k on v5e, r4); G=4 quarters those bytes without touching the MXU
# work — the r5 long-context lever (VERDICT r4 #7) inside the proven
# kernel class (GQA flash/ring are gradient-tested, no block overrides).
_register(
    "gpt2-8k-gqa",
    Config(
        model=_gpt2_model(
            context_length=8192,
            d_model=768,
            n_heads=12,
            n_kv_heads=3,
            n_layers=12,
            pos_embed="rope",
            attention_impl="ring",
            sequence_parallel=True,
            remat="save_attn",
        ),
        mesh=MeshConfig(data=-1, seq=4),
        train=TrainConfig(batch_size=8, lr=3e-4),
    ),
)

# The reference's own default shape (config/config.py:4-8 + src/models/*):
# 3.16B params — vocab 50304, ctx 512, d 2048, 16 heads, 64 blocks, ReLU MLP,
# no attention output projection, untied biased lm_head, learned positions.
_register(
    "reference-3b",
    Config(
        model=ModelConfig(
            vocab_size=50304,
            context_length=512,
            d_model=2048,
            n_heads=16,
            n_layers=64,
            activation="relu",
            norm="layernorm",
            pos_embed="learned",
            use_output_proj=False,
            tie_embeddings=False,
            lm_head_bias=True,
            qkv_bias=False,
            mlp_bias=True,
            remat="dots_saveable",
            # Perf intent: flash. Parity experiments pin their own config
            # (scripts/parity_experiment.py builds it explicitly), so the
            # preset is free to use the fast kernel.
            attention_impl="flash",
        ),
        mesh=MeshConfig(data=-1, fsdp=4),
        train=TrainConfig(batch_size=32, train_steps=200_000, lr=1e-4, eval_interval=1000, eval_iters=250),
    ),
)

# Beyond-parity: Llama-3-style 1B with grouped-query attention (4 KV heads
# for 16 query heads -> 4x smaller KV cache at decode).
_register(
    "llama3-1b-gqa",
    Config(
        model=_llama_model(
            vocab_size=32000,
            context_length=2048,
            d_model=2048,
            n_heads=16,
            n_kv_heads=4,
            n_layers=22,
            mlp_ratio=2.6875,
            attention_impl="flash",
            remat="dots_saveable",
        ),
        mesh=MeshConfig(data=-1, fsdp=4),
        train=TrainConfig(batch_size=32, lr=3e-4, weight_decay=0.1),
    ),
)

# Beyond-parity: MoE with expert parallelism (SURVEY §2.2 lists EP as the one
# strategy the reference leaves open). 8 experts, top-2 routing, experts
# sharded over the 'expert' mesh axis.
_register(
    "moe-8x350m",
    Config(
        model=_gpt2_model(
            context_length=1024,
            d_model=1024,
            n_heads=16,
            n_layers=24,
            n_experts=8,
            experts_per_token=2,
            remat="dots_saveable",
            attention_impl="flash",
        ),
        mesh=MeshConfig(data=-1, expert=4),
        train=TrainConfig(batch_size=32, lr=3e-4),
    ),
)

# Tiny config for tests and smoke runs. Byte tokenizer: vocab 256 can't hold
# GPT-2 BPE ids, and byte-level needs no downloaded vocab files.
_register(
    "tiny",
    Config(
        model=_gpt2_model(vocab_size=256, context_length=64, d_model=32, n_heads=4, n_layers=2),
        mesh=MeshConfig(),
        data=DataConfig(tokenizer_name="byte"),
        train=TrainConfig(batch_size=8, train_steps=50, eval_interval=20, eval_iters=2, lr=1e-3),
    ),
)


def get_preset(name: str) -> Config:
    if name not in _PRESETS:
        raise KeyError(f"unknown preset {name!r}; available: {sorted(_PRESETS)}")
    return _PRESETS[name]


def list_presets() -> Tuple[str, ...]:
    return tuple(sorted(_PRESETS))
