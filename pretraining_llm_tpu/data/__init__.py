from pretraining_llm_tpu.data.loader import get_batch_iterator, MemmapTokens  # noqa: F401
