"""Shared loader for the C++ runtime libraries under native/.

One code path for auto-building (`make <target>.so`) and ctypes-loading every
native extension, used by native_batcher.py and native_bpe.py. Build is
serialized across *processes* with an fcntl file lock — preprocess fans out
a multiprocessing Pool, and without the lock every fresh worker would race
`make` in the same directory and could dlopen a half-written library.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import threading
from typing import Callable, Dict, Optional

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_cache: Dict[str, Optional[ctypes.CDLL]] = {}
_cache_lock = threading.Lock()


def load_native_lib(
    so_name: str,
    configure: Callable[[ctypes.CDLL], None],
    *,
    auto_build: bool = True,
) -> Optional[ctypes.CDLL]:
    """Load native/<so_name>, building it first if absent.

    `configure(lib)` sets restype/argtypes; an AttributeError there (stale
    .so missing a symbol) makes the load fail soft. Returns None when no
    toolchain/library is available — callers fall back to their pure-Python
    paths. The result (including failure) is cached per process.
    """
    with _cache_lock:
        if so_name in _cache:
            return _cache[so_name]
        lib = _load(so_name, configure, auto_build)
        _cache[so_name] = lib
        return lib


def _load(
    so_name: str, configure: Callable[[ctypes.CDLL], None], auto_build: bool
) -> Optional[ctypes.CDLL]:
    path = os.path.join(NATIVE_DIR, so_name)
    if not os.path.exists(path):
        if not auto_build:
            return None
        lock_path = os.path.join(NATIVE_DIR, ".build.lock")
        try:
            with open(lock_path, "w") as lock_file:
                fcntl.flock(lock_file, fcntl.LOCK_EX)
                try:
                    if not os.path.exists(path):  # a peer may have built it
                        subprocess.run(
                            ["make", "-s", so_name],
                            cwd=NATIVE_DIR,
                            check=True,
                            capture_output=True,
                            timeout=120,
                        )
                finally:
                    fcntl.flock(lock_file, fcntl.LOCK_UN)
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return None
    try:
        lib = ctypes.CDLL(path)
        configure(lib)
    except (OSError, AttributeError):
        return None
    return lib
