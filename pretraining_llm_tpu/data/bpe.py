"""In-repo byte-level BPE: trainable, serializable, tiktoken-compatible API.

The reference outsources tokenization to tiktoken's pretrained Rust BPE
(`/root/reference/scripts/data_preprocess.py:29-34`). This framework supplies
its own equivalent so the data pipeline is self-contained:

  - `ByteTokenizer`: the degenerate no-merge case — raw UTF-8 bytes + an
    <|endoftext|> id. Always available, zero data files.
  - `BPETokenizer`: byte-level BPE trained on your own corpus (merges stored
    as JSON). Same `encode_ordinary` / `decode` / `eot_token` / `n_vocab`
    surface as tiktoken's `Encoding`, so the preprocess/generate paths take
    either interchangeably.

Tokenization is host-side and offline — never on the device path — so pure
Python is acceptable here; the hot encode loop is replaced by the C++ runtime
extension when built (native/, ctypes-loaded).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List, Sequence, Tuple


class ByteTokenizer:
    """UTF-8 bytes as tokens; id 256 is <|endoftext|>."""

    n_vocab = 257

    @property
    def eot_token(self) -> int:
        return 256

    def encode_ordinary(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def encode(self, text: str) -> List[int]:
        return self.encode_ordinary(text)

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class BPETokenizer:
    """Byte-level BPE with an explicit merge list.

    Encoding applies merges in priority order (lowest rank first) — the
    standard BPE greedy scheme. Training is iterative highest-frequency pair
    merging over a sample corpus.
    """

    def __init__(self, merges: List[Tuple[int, int]], special_tokens: Dict[str, int] | None = None):
        self.merges = [tuple(m) for m in merges]
        self.ranks: Dict[Tuple[int, int], int] = {m: i for i, m in enumerate(self.merges)}
        # token id space: 0..255 bytes, 256+i for merge i, then specials
        self.special_tokens = special_tokens or {"<|endoftext|>": 256 + len(self.merges)}
        self._decode_table: Dict[int, bytes] = {i: bytes([i]) for i in range(256)}
        for i, (a, b) in enumerate(self.merges):
            self._decode_table[256 + i] = self._decode_table[a] + self._decode_table[b]
        self._native = None  # lazily constructed C++ encoder (or False = tried)

    # -- tiktoken-compatible surface ------------------------------------
    @property
    def n_vocab(self) -> int:
        return 256 + len(self.merges) + len(self.special_tokens)

    @property
    def eot_token(self) -> int:
        return self.special_tokens["<|endoftext|>"]

    def encode_ordinary(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        if not self.ranks:
            return list(data)
        if self._native is None:
            try:
                from pretraining_llm_tpu.data.native_bpe import NativeBpeEncoder

                self._native = NativeBpeEncoder(self.merges)
            except (RuntimeError, OSError, ImportError):
                self._native = False  # toolchain absent: Python sweep below
        if self._native:
            return self._native.encode_bytes(data)
        return self._encode_python(list(data))

    def _encode_python(self, ids: List[int]) -> List[int]:
        """Reference greedy sweep — the correctness oracle for the C++ path."""
        while len(ids) >= 2:
            # find the lowest-rank adjacent pair
            best_rank = None
            best_pos = -1
            for pos in range(len(ids) - 1):
                rank = self.ranks.get((ids[pos], ids[pos + 1]))
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank, best_pos = rank, pos
            if best_rank is None:
                break
            merged_id = 256 + best_rank
            out = []
            i = 0
            while i < len(ids):
                if (
                    i < len(ids) - 1
                    and ids[i] == self.merges[best_rank][0]
                    and ids[i + 1] == self.merges[best_rank][1]
                ):
                    out.append(merged_id)
                    i += 2
                else:
                    out.append(ids[i])
                    i += 1
            ids = out
        return ids

    def encode(self, text: str) -> List[int]:
        return self.encode_ordinary(text)

    def decode(self, ids: Sequence[int]) -> str:
        specials = set(self.special_tokens.values())
        data = b"".join(self._decode_table[i] for i in ids if i not in specials)
        return data.decode("utf-8", errors="replace")

    # -- training / persistence ----------------------------------------
    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int) -> "BPETokenizer":
        """Train merges until vocab_size (>= 257) is reached."""
        n_merges = max(0, vocab_size - 257)
        # Work on word-like chunks to bound pair interactions (whitespace split
        # keeps training tractable without a regex pre-tokenizer).
        words = Counter()
        for text in texts:
            for word in text.split(" "):
                words[tuple((" " + word).encode("utf-8"))] += 1
        merges: List[Tuple[int, int]] = []
        for merge_index in range(n_merges):
            pairs: Counter = Counter()
            for word, freq in words.items():
                for a, b in zip(word, word[1:]):
                    pairs[(a, b)] += freq
            if not pairs:
                break
            best = max(pairs, key=lambda p: (pairs[p], -p[0], -p[1]))
            if pairs[best] < 2:
                break
            new_id = 256 + merge_index
            merges.append(best)
            new_words = Counter()
            for word, freq in words.items():
                out = []
                i = 0
                while i < len(word):
                    if i < len(word) - 1 and (word[i], word[i + 1]) == best:
                        out.append(new_id)
                        i += 2
                    else:
                        out.append(word[i])
                        i += 1
                new_words[tuple(out)] += freq
            words = new_words
        return cls(merges)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {"merges": self.merges, "special_tokens": self.special_tokens}, f
            )

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            raw = json.load(f)
        return cls([tuple(m) for m in raw["merges"]], raw.get("special_tokens"))
