"""Host-side batch pipeline: uint16 memmap -> (x, y) shifted token pairs.

Capability parity with `/root/reference/data_loader/data_loader.py:7-52`, with
the reference's defects fixed by design (SURVEY §A):

  - B1: the reference shards the token *stream* by stride
    (`data[rank::world_size]`), interleaving every-Nth tokens and destroying
    sequence structure. Here each host reads a **contiguous block** of the
    stream (with context_length overlap so no boundary sequences are lost).
  - Q1: the reference samples crops with unseeded `torch.randint` — runs are
    unreproducible. Here sampling is a seeded `np.random.Generator`, and the
    generator state round-trips through checkpoints (the iterator exposes
    `state`/`set_state`).

The on-disk format is the reference's own: a flat uint16 token memmap, so
datasets tokenized for the reference load unchanged. Device transfer is the
trainer's job (`device_prefetch` below double-buffers `jax.device_put`).
"""

from __future__ import annotations

import atexit
import os
import queue
import threading
import weakref
from typing import Any, Dict, Iterator, Tuple

import numpy as np


class MemmapTokens:
    """Read-only view of a uint16 token file, optionally host-sharded."""

    def __init__(
        self,
        path: str,
        context_length: int,
        shard_index: int = 0,
        shard_count: int = 1,
    ) -> None:
        data = np.memmap(path, dtype=np.uint16, mode="r")
        if shard_count > 1:
            # Contiguous block per host + overlap so every crossing sequence
            # is sampleable by exactly one host.
            n = len(data)
            lo = (n * shard_index) // shard_count
            hi = min((n * (shard_index + 1)) // shard_count + context_length, n)
            data = data[lo:hi]
        if len(data) < context_length + 1:
            raise ValueError(
                f"{path}: shard has {len(data)} tokens < context_length+1={context_length + 1}"
            )
        self.data = data
        self.context_length = context_length

    def sample_batch(
        self, rng: np.random.Generator, batch_size: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        t = self.context_length
        # Valid crop starts are 0 .. len-(t+1) inclusive: the window reads
        # t+1 tokens (inputs + shifted targets). `integers` is exclusive-high.
        starts = rng.integers(0, len(self.data) - t, size=batch_size)
        # Single gather into one contiguous int32 buffer (the reference does
        # batch_size separate tensor conversions + a Python-level stack).
        idx = starts[:, None] + np.arange(t + 1)[None, :]
        tokens = self.data[idx].astype(np.int32)
        return tokens[:, :-1], tokens[:, 1:]


class BatchIterator:
    """Infinite seeded batch iterator with checkpointable RNG state."""

    def __init__(
        self,
        source: MemmapTokens,
        batch_size: int,
        seed: int,
    ) -> None:
        self.source = source
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> "BatchIterator":
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        return self.source.sample_batch(self._rng, self.batch_size)

    # RNG state round-trip for exact resume (SURVEY §5 checkpoint/resume).
    def state(self) -> Dict[str, Any]:
        return {"bit_generator": self._rng.bit_generator.state}

    def set_state(self, state: Dict[str, Any]) -> None:
        if "bit_generator" not in state:
            return  # checkpoint written by a different iterator backend
        self._rng.bit_generator.state = state["bit_generator"]


def is_mixture(data_path: str) -> bool:
    """True when ``data_path`` is a mixture spec, not a single file.

    A comma marks a mixture — unless the whole string names an existing
    file (escape hatch for pathological comma-containing filenames). The
    single source of truth for every dispatch site (loader, trainer's
    native-batcher routing)."""
    return "," in data_path and not os.path.exists(data_path)


def parse_mixture(spec: str) -> "list[Tuple[str, float]]":
    """Parse a mixture spec: comma-separated ``path[:weight]`` entries.

    "a.bin:3,b.bin:1" -> [("a.bin", 3.0), ("b.bin", 1.0)] (weights need not
    normalize; omitted weight = 1). An entry whose ':' suffix is not a
    number keeps the colon as part of the path (drive letters etc.);
    malformed entries (empty path, dangling ':') raise with the offending
    entry named instead of surfacing later as a file-not-found.
    """
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        path, sep, w = entry.rpartition(":")
        if sep:
            if path and w and w.replace(".", "", 1).isdigit():
                out.append((path, float(w)))
                continue
            if not path or not w:
                raise ValueError(
                    f"malformed mixture entry {entry!r} in {spec!r}: "
                    "expected path[:weight]"
                )
            # Non-numeric suffix: the ':' belongs to the path itself.
        out.append((entry, 1.0))
    if not out:
        raise ValueError(f"empty mixture spec {spec!r}")
    return out


class MixtureIterator:
    """Weighted mixture over several token streams (beyond-reference: the
    reference trains on exactly one memmap, data_loader.py:32).

    Each batch row draws its SOURCE by weight, then a crop from that
    source — all from ONE seeded generator, so the whole mixture state
    checkpoints/resumes through a single RNG (``state``/``set_state``,
    same contract as BatchIterator; works under DevicePrefetcher's
    consumed-frontier tracking unchanged).
    """

    def __init__(
        self,
        sources: "list[MemmapTokens]",
        weights: "list[float]",
        batch_size: int,
        seed: int,
    ) -> None:
        if len(sources) != len(weights) or not sources:
            raise ValueError("sources and weights must be equal-length, non-empty")
        total = float(sum(weights))
        if total <= 0 or any(w < 0 for w in weights):
            raise ValueError(f"mixture weights must be >= 0 with a positive sum: {weights}")
        self.sources = sources
        self.weights = np.asarray([w / total for w in weights], np.float64)
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> "MixtureIterator":
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        choice = self._rng.choice(
            len(self.sources), size=self.batch_size, p=self.weights
        )
        t = self.sources[0].context_length
        xs = np.empty((self.batch_size, t), np.int32)
        ys = np.empty((self.batch_size, t), np.int32)
        for si in range(len(self.sources)):
            rows = np.nonzero(choice == si)[0]
            if rows.size:
                x, y = self.sources[si].sample_batch(self._rng, rows.size)
                xs[rows] = x
                ys[rows] = y
        return xs, ys

    state = BatchIterator.state
    set_state = BatchIterator.set_state


def get_batch_iterator(
    data_path: str,
    batch_size: int,
    context_length: int,
    *,
    seed: int = 1337,
    shard_index: int = 0,
    shard_count: int = 1,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Mirror of the reference's public API (data_loader.py:7-15), returning
    host numpy batches; sharding is contiguous-block, sampling is seeded.

    ``data_path`` may be a weighted mixture spec — comma-separated
    ``path[:weight]`` (see `parse_mixture`); each source is host-sharded
    contiguously as usual.
    """
    # Decorrelate shards: each host folds its index into the stream seed.
    host_seed = seed + 7919 * shard_index
    if is_mixture(data_path):
        entries = parse_mixture(data_path)
        sources = [
            MemmapTokens(p, context_length, shard_index, shard_count)
            for p, _ in entries
        ]
        return MixtureIterator(
            sources, [w for _, w in entries], batch_size, host_seed
        )
    source = MemmapTokens(data_path, context_length, shard_index, shard_count)
    return BatchIterator(source, batch_size, host_seed)


class SyntheticTokens:
    """Deterministic structured token stream for tests and data-free smoke runs.

    A degree-2 Markov chain over the vocab: learnable structure (loss drops
    well below ln(V)) with no files needed.
    """

    def __init__(self, vocab_size: int, context_length: int, seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        n = max(context_length * 64, 65536)
        table = rng.integers(0, vocab_size, size=(vocab_size, 4))
        stream = np.empty(n, dtype=np.uint16)
        stream[0] = rng.integers(vocab_size)
        choices = rng.integers(0, 4, size=n)
        for i in range(1, n):
            stream[i] = table[stream[i - 1], choices[i]]
        self.data = stream
        self.context_length = context_length

    sample_batch = MemmapTokens.sample_batch


def synthetic_iterator(
    vocab_size: int, context_length: int, batch_size: int, seed: int = 0
) -> BatchIterator:
    return BatchIterator(SyntheticTokens(vocab_size, context_length, seed), batch_size, seed)


class DevicePrefetcher:
    """Run host sampling + H2D transfer ahead of the training step WITHOUT
    giving up exact resume.

    `put_fn(host_batch) -> device_batch` (typically a sharded jax.device_put).
    A daemon thread keeps `depth` batches in flight — the TPU-native analog of
    the reference's pinned-memory `non_blocking=True` copy (data_loader.py:48),
    but overlapping the *sampling* too.

    Exact-resume contract (VERDICT r2 next #8): each produced batch carries
    the source iterator's RNG state snapshot taken immediately AFTER drawing
    it; `state()` reports the snapshot of the last batch the CONSUMER took —
    the consumed-batch frontier, exactly what the synchronous loop would
    checkpoint. Batches still sitting in the queue at checkpoint/preemption
    time are simply re-drawn (identically) on resume.
    """

    _DONE = object()

    def __init__(self, iterator: Iterator[Any], put_fn: Any, depth: int = 2) -> None:
        self._it = iterator
        self._put = put_fn
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._exhausted = False
        has_state = hasattr(iterator, "state")
        self._state = iterator.state() if has_state else None
        self._thread = threading.Thread(
            target=self._worker, args=(has_state,), daemon=True
        )
        self._thread.start()
        _LIVE_PREFETCHERS.add(self)

    def _offer(self, item: Any) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, has_state: bool) -> None:
        try:
            # Check stop BEFORE each draw (not only in _offer): after
            # close(), the source iterator must not be advanced again — the
            # owner may be about to rewind its RNG to the consumed frontier,
            # and a post-rewind draw would corrupt it.
            while not self._stop.is_set():
                try:
                    batch = next(self._it)
                except StopIteration:
                    break
                snap = self._it.state() if has_state else None
                if not self._offer((self._put(batch), snap)):
                    return
        except Exception as e:  # surface loader errors on the consumer side
            self._offer(e)
        finally:
            # ALWAYS terminate the stream — after a delivered exception too,
            # so a consumer that catches it and calls next() again gets
            # StopIteration instead of blocking forever on an empty queue.
            self._offer(self._DONE)

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._exhausted:
            # Standard iterator contract: exhaustion is permanent and
            # re-raisable — a second loop over the same object must get
            # StopIteration again, not block on the empty queue.
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        batch, snap = item
        if snap is not None:
            self._state = snap
        return batch

    def state(self) -> Any:
        """RNG frontier of the batches actually CONSUMED (not produced)."""
        return self._state

    def close(self) -> bool:
        """Stop the worker and JOIN it. Returns True iff the worker is dead.

        The join is load-bearing: callers rewind the source iterator's RNG
        to the consumed frontier right after close(), which is only safe
        once the worker can no longer draw from it (a mid-draw worker races
        the rewind and silently corrupts the stream). A False return means
        the worker is wedged (e.g. blocked in a slow device transfer) — the
        caller must NOT rewind; keeping the live feed preserves determinism
        through the queue instead.
        """
        self._stop.set()
        # Unblock a worker stuck on a full queue.
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)
        return not self._thread.is_alive()


# Interpreter-teardown guard: a daemon worker that outlives its owner
# (a consumer that never exhausted the stream and never called close())
# keeps calling put_fn — a device transfer — while CPython finalization
# tears the runtime down underneath it, which can segfault inside the
# extension (observed once in a full-suite run, 2026-08-02: prefetcher
# thread parked in queue.put at interpreter exit). atexit runs BEFORE
# extension teardown: stop every live worker and give each a moment to
# park. WeakSet: the guard must not keep abandoned prefetchers alive.
_LIVE_PREFETCHERS: "weakref.WeakSet[DevicePrefetcher]" = weakref.WeakSet()


def _stop_live_prefetchers() -> None:
    import time as _time

    for p in list(_LIVE_PREFETCHERS):
        p._stop.set()
    # Shared deadline: exit latency stays ~1s total however many workers
    # are live (a worker wedged inside a device transfer cannot be
    # interrupted anyway — the guard is best-effort by construction).
    deadline = _time.monotonic() + 1.0
    for p in list(_LIVE_PREFETCHERS):
        p._thread.join(timeout=max(0.0, deadline - _time.monotonic()))


atexit.register(_stop_live_prefetchers)


def device_prefetch(
    iterator: Iterator[Tuple[np.ndarray, np.ndarray]],
    put_fn: Any,
    depth: int = 2,
) -> Iterator[Any]:
    """Iterator-style view of `DevicePrefetcher` (kept for API stability)."""
    return DevicePrefetcher(iterator, put_fn, depth)
