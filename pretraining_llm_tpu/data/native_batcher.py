"""ctypes bindings for the native C++ batch gatherer (native/batcher.cpp).

Auto-builds `native/libbatcher.so` with `make` on first use when a toolchain
is present; callers fall back to the pure-numpy loader otherwise (the Trainer
does this automatically). The native iterator is counter-based: its full
sampling state is one integer, which makes checkpoint resume trivially exact.
"""

from __future__ import annotations

import ctypes
from typing import Any, Dict, Tuple

import numpy as np

from pretraining_llm_tpu.data._native import load_native_lib


def _configure(lib: ctypes.CDLL) -> None:
    lib.batcher_open.restype = ctypes.c_void_p
    lib.batcher_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.batcher_num_tokens.restype = ctypes.c_int64
    lib.batcher_num_tokens.argtypes = [ctypes.c_void_p]
    lib.batcher_sample.restype = None
    lib.batcher_sample.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.batcher_close.restype = None
    lib.batcher_close.argtypes = [ctypes.c_void_p]


def _load_library():
    return load_native_lib("libbatcher.so", _configure)


def native_available() -> bool:
    return _load_library() is not None


class NativeBatchIterator:
    """Drop-in for data.loader.BatchIterator, backed by the C++ gatherer."""

    def __init__(
        self,
        data_path: str,
        batch_size: int,
        context_length: int,
        *,
        seed: int = 1337,
        shard_index: int = 0,
        shard_count: int = 1,
        n_threads: int = 4,
    ) -> None:
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native batcher library unavailable (no toolchain?)")
        self._lib = lib
        self._handle = lib.batcher_open(
            data_path.encode(), context_length, shard_index, shard_count, n_threads
        )
        if not self._handle:
            raise ValueError(
                f"{data_path}: could not open (missing, or shard smaller than "
                f"context_length+1={context_length + 1})"
            )
        self.batch_size = batch_size
        self.context_length = context_length
        self.seed = seed
        self.counter = 0
        self._x = np.empty((batch_size, context_length), np.int32)
        self._y = np.empty((batch_size, context_length), np.int32)

    @property
    def n_tokens(self) -> int:
        return int(self._lib.batcher_num_tokens(self._handle))

    def __iter__(self) -> "NativeBatchIterator":
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        self._lib.batcher_sample(
            self._handle,
            ctypes.c_uint64(self.seed),
            ctypes.c_uint64(self.counter),
            self.batch_size,
            self._x.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        self.counter += 1
        # Copies: the internal buffers are reused next call.
        return self._x.copy(), self._y.copy()

    # Checkpointable sampling state: just the counter (counter-based PRNG).
    def state(self) -> Dict[str, Any]:
        return {"native_counter": self.counter, "seed": self.seed}

    def set_state(self, state: Dict[str, Any]) -> None:
        if "native_counter" not in state:
            return  # checkpoint written by a different iterator backend
        self.counter = int(state["native_counter"])
        self.seed = int(state.get("seed", self.seed))

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.batcher_close(self._handle)
            self._handle = None

    def __del__(self) -> None:  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
