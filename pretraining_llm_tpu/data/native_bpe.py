"""ctypes bindings for the native C++ BPE encoder (native/bpe.cpp).

Auto-builds `native/libbpe.so` on first use when a toolchain is present (via
data/_native.py, cross-process safe); `BPETokenizer.encode_ordinary` falls
back to the pure-Python sweep otherwise. The native encoder is bit-identical
to the Python path (tests/test_tokenizer.py::test_native_bpe_matches_python_sweep)
— it exists because offline corpus tokenization is the one data-prep stage
whose cost scales with raw corpus bytes, the same reason the reference leans
on tiktoken's native BPE (scripts/data_preprocess.py:29-34).
"""

from __future__ import annotations

import ctypes
from typing import List, Sequence, Tuple

import numpy as np

from pretraining_llm_tpu.data._native import load_native_lib


def _configure(lib: ctypes.CDLL) -> None:
    lib.bpe_create.restype = ctypes.c_void_p
    lib.bpe_create.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]
    lib.bpe_encode.restype = ctypes.c_int64
    lib.bpe_encode.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.bpe_destroy.restype = None
    lib.bpe_destroy.argtypes = [ctypes.c_void_p]


def _load_library():
    return load_native_lib("libbpe.so", _configure)


def native_available() -> bool:
    return _load_library() is not None


class NativeBpeEncoder:
    """Holds a native merge table; encodes UTF-8 byte buffers to token ids."""

    def __init__(self, merges: Sequence[Tuple[int, int]]) -> None:
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native BPE library unavailable (no toolchain?)")
        self._lib = lib
        a = np.asarray([m[0] for m in merges], np.int32)
        b = np.asarray([m[1] for m in merges], np.int32)
        self._handle = lib.bpe_create(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            b.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(merges),
        )
        if not self._handle:
            raise RuntimeError("bpe_create failed")

    def encode_bytes(self, data: bytes) -> List[int]:
        n = len(data)
        if n == 0:
            return []
        buf = np.frombuffer(data, np.uint8)
        out = np.empty(n, np.int32)
        m = self._lib.bpe_encode(
            self._handle,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out[:m].tolist()

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.bpe_destroy(self._handle)
            self._handle = None

    def __del__(self) -> None:  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
