"""Offline tokenize-and-pack: documents -> flat uint16 token memmap.

Capability parity with `/root/reference/scripts/data_preprocess.py:19-64`
(tiktoken BPE, per-doc <|endoftext|> append, parallel map, single uint16
memmap written in shards) with its defects fixed:

  - the reference crashes as shipped (`dataset_name` undefined, `val_path`
    vs `dev_path`, SURVEY §A B4/B5) — here all paths/names are typed config;
  - works fully offline: sources are local text/jsonl files or an HF dataset
    when the environment has one cached; tokenizer can be tiktoken, an
    in-repo BPE, or the byte fallback;
  - uint16 is validated against the tokenizer's vocab size (silent overflow
    is impossible), with automatic uint32 fallback for large vocabs.

Output format is the reference's own (flat token array on disk), so either
stack's files interoperate.
"""

from __future__ import annotations

import multiprocessing
import os
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from pretraining_llm_tpu.data.tokenizer import get_tokenizer

_WRITE_CHUNK_DOCS = 1024  # flush cadence, mirrors the reference's 1024 shards


def _encode_doc(args: Tuple[str, str]) -> List[int]:
    text, tokenizer_name = args
    tok = get_tokenizer(tokenizer_name)
    ids = tok.encode_ordinary(text)
    ids.append(tok.eot_token)
    return ids


def iter_text_files(paths: Sequence[str]) -> Iterator[str]:
    """Documents from .txt (one doc per file) or .jsonl ('text' field per line)."""
    import json

    for path in paths:
        if path.endswith(".jsonl"):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)["text"]
        else:
            with open(path) as f:
                yield f.read()


def split_documents(
    docs: Iterable[str], val_fraction: float, seed: int
) -> Tuple[List[str], List[str]]:
    """Deterministic train/val split (reference: 0.05% split, seed 42)."""
    docs = list(docs)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(docs))
    n_val = max(1, int(len(docs) * val_fraction)) if len(docs) > 1 else 0
    val_idx = set(order[:n_val].tolist())
    train = [d for i, d in enumerate(docs) if i not in val_idx]
    val = [d for i, d in enumerate(docs) if i in val_idx]
    return train, val


def token_dtype(n_vocab: int) -> np.dtype:
    return np.dtype(np.uint16) if n_vocab <= np.iinfo(np.uint16).max + 1 else np.dtype(np.uint32)


def write_token_file(
    docs: Sequence[str],
    out_path: str,
    tokenizer_name: str,
    num_proc: Optional[int] = None,
) -> int:
    """Tokenize docs (parallel) and write one flat token array. Returns count."""
    tok = get_tokenizer(tokenizer_name)
    dtype = token_dtype(tok.n_vocab)
    num_proc = num_proc or min(multiprocessing.cpu_count(), 8)
    args = [(d, tokenizer_name) for d in docs]
    if num_proc > 1 and len(docs) > 8:
        with multiprocessing.Pool(num_proc) as pool:
            encoded = pool.map(_encode_doc, args, chunksize=32)
    else:
        encoded = [_encode_doc(a) for a in args]

    total = sum(len(e) for e in encoded)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    mm = np.memmap(out_path, dtype=dtype, mode="w+", shape=(total,))
    pos = 0
    for start in range(0, len(encoded), _WRITE_CHUNK_DOCS):
        chunk = np.concatenate(
            [np.asarray(e, dtype) for e in encoded[start : start + _WRITE_CHUNK_DOCS]]
        )
        mm[pos : pos + len(chunk)] = chunk
        pos += len(chunk)
    mm.flush()
    del mm
    return total


def preprocess(
    *,
    input_files: Optional[Sequence[str]] = None,
    dataset_name: Optional[str] = None,
    out_dir: str = "data",
    tokenizer_name: str = "gpt2",
    val_fraction: float = 0.0005,
    seed: int = 42,
    num_proc: Optional[int] = None,
    max_docs: Optional[int] = None,
) -> Tuple[str, str]:
    """Full pipeline -> (train_path, val_path)."""
    if input_files:
        docs = list(iter_text_files(input_files))
    elif dataset_name:
        from datasets import load_dataset  # HF cache / network required

        ds = load_dataset(dataset_name, split="train", trust_remote_code=True)
        docs = [row["text"] for row in ds]
    else:
        raise ValueError("provide input_files or dataset_name")
    if max_docs:
        docs = docs[:max_docs]
    if not docs:
        raise ValueError("no documents found")

    train_docs, val_docs = split_documents(docs, val_fraction, seed)
    if not val_docs:  # single-doc corpora: carve val from the train tail
        text = train_docs[-1]
        cut = max(1, int(len(text) * (1 - max(val_fraction, 0.01))))
        train_docs[-1], val_docs = text[:cut], [text[cut:]]

    train_path = os.path.join(out_dir, "train.bin")
    val_path = os.path.join(out_dir, "val.bin")
    n_train = write_token_file(train_docs, train_path, tokenizer_name, num_proc)
    n_val = write_token_file(val_docs, val_path, tokenizer_name, num_proc)
    print(f"wrote {n_train} train tokens -> {train_path}, {n_val} val tokens -> {val_path}")
    return train_path, val_path
