"""Tokenizer access: GPT-2 BPE (tiktoken) / in-repo BPE / byte fallback.

The reference uses tiktoken 'gpt2' in preprocessing and 'r50k_base' in
generation — the same vocab under two names (SURVEY §A B9); one accessor here
keeps that consistent. Tokenization is host-side and offline; it never touches
the device path (SURVEY §2.4).

Names:
  'gpt2' / 'r50k_base'  tiktoken's pretrained GPT-2 BPE. Requires its data
                        file (network or TIKTOKEN_CACHE_DIR) — raises a clear
                        error in air-gapped environments.
  'byte'                raw UTF-8 bytes + <|endoftext|> (always available).
  '<path>.json'         an in-repo BPETokenizer trained with
                        `pretraining_llm_tpu.data.bpe.BPETokenizer.train`.
"""

from __future__ import annotations

import functools
from typing import Any

from pretraining_llm_tpu.data.bpe import BPETokenizer, ByteTokenizer


@functools.lru_cache(maxsize=4)
def get_tokenizer(name: str = "gpt2") -> Any:
    if name == "byte":
        return ByteTokenizer()
    if name.endswith(".json"):
        return BPETokenizer.load(name)
    if name in ("gpt2", "r50k_base"):
        import tiktoken

        try:
            return tiktoken.get_encoding("gpt2")
        except Exception as e:  # offline and uncached
            raise RuntimeError(
                "tiktoken could not load the GPT-2 BPE data (offline without a "
                "TIKTOKEN_CACHE_DIR cache). Use tokenizer_name='byte', or train "
                "an in-repo BPE (pretraining_llm_tpu.data.bpe.BPETokenizer.train) "
                "and pass its .json path as tokenizer_name."
            ) from e
    raise ValueError(f"unknown tokenizer {name!r}")
