"""Online serving frontend: the request-lifecycle layer over ServingEngine.

The engine (generation/serving.py) is a scheduler: it knows rows, pages
and windows, but nothing about arrival, waiting clients, deadlines or
load. This package adds the online half:

  engine_loop  — a long-lived thread driving ``ServingEngine.pipeline_tick``
                 that drains a submission inbox, admits requests mid-flight
                 between scheduler turns, streams committed tokens to
                 per-request queues, and applies cancellation/deadlines
                 (releasing rows and pool blocks immediately);
  admission    — backpressure policy: bounded in-system request depth and
                 an outstanding-token budget (-> 429 Retry-After), plus
                 deadline-aware shedding of requests that cannot finish in
                 time (-> 504);
  gateway      — a stdlib ThreadingHTTPServer exposing POST /v1/generate
                 (JSON in; full response or SSE token streaming out),
                 GET /healthz, GET /readyz and GET /metrics (Prometheus
                 text via the observability exporter);
  loadgen      — open-loop (Poisson) and closed-loop load generators
                 reporting TTFT/TPOT/e2e percentiles and goodput-under-SLO;
  replica      — one restartable engine replica (engine factory +
                 EngineLoop + per-replica registry/admission/fault clock);
  router       — the fleet tier over N replicas: prefix-affinity routing
                 with spill, health-based ejection with backoff, brownout
                 shedding, and drain/redrive of in-flight requests.

Everything is CPU-testable with the tiny preset; the reference has no
serving stack at all (batch-1 fixed-count generate).
"""

from pretraining_llm_tpu.frontend.admission import (  # noqa: F401
    AdmissionController,
    RejectedBusy,
    RejectedInfeasible,
)
from pretraining_llm_tpu.frontend.engine_loop import (  # noqa: F401
    EngineLoop,
    FrontendRequest,
)
from pretraining_llm_tpu.frontend.gateway import ServingGateway  # noqa: F401
from pretraining_llm_tpu.frontend.loadgen import (  # noqa: F401
    FleetAction,
    LoadReport,
    LoadSpec,
    build_schedule,
    rolling_restart_plan,
    run_engine_loop,
    run_fleet_plan,
    run_http,
)
from pretraining_llm_tpu.frontend.replica import (  # noqa: F401
    Replica,
    ReplicaUnavailable,
)
from pretraining_llm_tpu.frontend.router import (  # noqa: F401
    Router,
    RouterRequest,
)
