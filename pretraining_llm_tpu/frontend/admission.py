"""Backpressure and deadline-aware admission for the serving frontend.

The engine already has an internal admission watermark (rows + pool
blocks), but that only protects the DEVICE: an unbounded submission queue
still grows without limit under overload, and every queued request pays
its whole queue wait before learning it cannot finish by its deadline.
This controller is the gate the gateway consults BEFORE a request enters
the system:

  - bounded in-system depth: at most ``max_queue_depth`` requests admitted
    and not yet terminal -> excess is rejected with ``RejectedBusy``
    (HTTP 429 + Retry-After), the load-shedding answer that keeps queue
    waits bounded instead of letting tail latency run away;
  - outstanding-token budget: the sum of ``prompt + max_new_tokens`` over
    live requests is capped — ten 8-token requests and one 8000-token
    request are not the same load, and a depth bound alone cannot see
    that;
  - deadline-aware shedding: once a TPOT estimate exists (EWMA over
    completed requests), a request whose minimum service time already
    exceeds its deadline is rejected up front with ``RejectedInfeasible``
    (HTTP 504) instead of wasting pool pages to miss it.

All host-side, lock-protected, called from gateway threads; ``release``
is called by the engine loop at each request's terminal event.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional


class RejectedBusy(Exception):
    """The system is at capacity; retry after ``retry_after_s``."""

    def __init__(self, reason: str, retry_after_s: float) -> None:
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


class RejectedInfeasible(Exception):
    """The request's deadline cannot be met even if it ran alone."""

    def __init__(self, reason: str, estimate_s: float) -> None:
        super().__init__(reason)
        self.reason = reason
        self.estimate_s = estimate_s


@dataclasses.dataclass
class Ticket:
    """One admitted request's claim on the budgets; hand back to
    ``release`` exactly once at the request's terminal event."""

    cost_tokens: int
    released: bool = False


class AdmissionController:
    def __init__(
        self,
        *,
        max_queue_depth: int = 64,
        max_outstanding_tokens: int = 0,
        retry_after_s: float = 1.0,
        shed_infeasible: bool = True,
        tpot_ewma_alpha: float = 0.2,
        registry: Optional[object] = None,
        scope: str = "",
    ) -> None:
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}"
            )
        if max_outstanding_tokens < 0:
            raise ValueError(
                f"max_outstanding_tokens must be >= 0 (0 = unlimited), got "
                f"{max_outstanding_tokens}"
            )
        if not 0.0 < tpot_ewma_alpha <= 1.0:
            raise ValueError(
                f"tpot_ewma_alpha must be in (0, 1], got {tpot_ewma_alpha}"
            )
        self.max_queue_depth = int(max_queue_depth)
        self.max_outstanding_tokens = int(max_outstanding_tokens)
        self.retry_after_s = float(retry_after_s)
        self.shed_infeasible = bool(shed_infeasible)
        self._alpha = float(tpot_ewma_alpha)
        self._lock = threading.Lock()
        self._live = 0
        self._outstanding_tokens = 0
        self._tpot_ewma: Optional[float] = None
        self.stats: Dict[str, int] = {
            "admitted": 0, "rejected_busy": 0, "rejected_infeasible": 0,
        }
        # Typed live counters (observability.metrics.MetricsRegistry):
        # the same tallies as `stats`, but as real Prometheus counters
        # with the rejection reason as a label. None = untyped only.
        self._c_admitted = self._c_rejected = None
        self._g_depth = self._g_tokens = None
        # ``scope`` distinguishes multiple controllers on ONE registry
        # (the fleet router's budget vs. each replica's own): it becomes a
        # label on every typed series here, so the names stay shared while
        # the samples stay apart. "" = no label (the single-engine case,
        # and replicas whose registries already carry a const replica
        # label).
        sl = {"scope": scope} if scope else {}
        self.scope = scope
        if registry is not None:
            self._c_admitted = registry.counter(
                "admission_admitted_total", "requests admitted", **sl)
            self._c_rejected = {
                reason: registry.counter(
                    "admission_rejected_total",
                    "requests rejected at admission", reason=reason, **sl)
                for reason in ("busy", "infeasible")
            }
            # Live-budget gauges: the numbers snapshot() reports, but as
            # typed series a scraper can alert on (depth vs. its limit is
            # the saturation signal capacity attribution keys off).
            self._g_depth = registry.gauge(
                "admission_queue_depth",
                "requests admitted and not terminal", **sl)
            self._g_tokens = registry.gauge(
                "admission_outstanding_tokens",
                "sum of prompt+max_new over live requests", **sl)
            registry.gauge(
                "admission_queue_depth_limit", "max_queue_depth", **sl
            ).set(self.max_queue_depth)
            registry.gauge(
                "admission_outstanding_tokens_limit",
                "max_outstanding_tokens (0 = unlimited)", **sl
            ).set(self.max_outstanding_tokens)

    # -- queries ------------------------------------------------------------

    @property
    def live(self) -> int:
        with self._lock:
            return self._live

    @property
    def outstanding_tokens(self) -> int:
        with self._lock:
            return self._outstanding_tokens

    def estimate_service_s(self, max_new_tokens: int) -> Optional[float]:
        """Minimum-service-time estimate for a request: decode only, zero
        queueing — deliberately OPTIMISTIC, so shedding on it never
        rejects a request that had any chance (None until a completed
        request has taught the controller a TPOT)."""
        with self._lock:
            tpot = self._tpot_ewma
        if tpot is None:
            return None
        return max_new_tokens * tpot

    # -- admit / release ----------------------------------------------------

    def try_admit(
        self,
        n_prompt_tokens: int,
        max_new_tokens: int,
        deadline_s: Optional[float] = None,
        cached_tokens: int = 0,
    ) -> Ticket:
        """Admit or raise. ``deadline_s`` is the request's REMAINING time
        budget in seconds (None = no deadline). ``cached_tokens`` is the
        engine's prefix-cache hint: prompt tokens already resident in
        shared KV blocks cost no prefill and no new pool pages, so they
        don't count against the outstanding-token budget — cache hits buy
        admission headroom. Capped at n_prompt - 1 (the final prompt
        token always prefills privately)."""
        discount = min(
            max(0, int(cached_tokens)), max(0, int(n_prompt_tokens) - 1)
        )
        cost = int(n_prompt_tokens) - discount + int(max_new_tokens)
        if self.shed_infeasible and deadline_s is not None:
            if deadline_s <= 0:
                with self._lock:
                    self.stats["rejected_infeasible"] += 1
                if self._c_rejected is not None:
                    self._c_rejected["infeasible"].inc()
                raise RejectedInfeasible("deadline already expired", 0.0)
            est = self.estimate_service_s(max_new_tokens)
            if est is not None and est > deadline_s:
                with self._lock:
                    self.stats["rejected_infeasible"] += 1
                if self._c_rejected is not None:
                    self._c_rejected["infeasible"].inc()
                raise RejectedInfeasible(
                    f"needs ~{est:.3f}s of decode but only {deadline_s:.3f}s "
                    f"remain before the deadline",
                    est,
                )
        try:
            with self._lock:
                if self._live >= self.max_queue_depth:
                    self.stats["rejected_busy"] += 1
                    raise RejectedBusy(
                        f"{self._live} requests in flight (limit "
                        f"{self.max_queue_depth})",
                        self.retry_after_s,
                    )
                if (
                    self.max_outstanding_tokens
                    and self._outstanding_tokens + cost > self.max_outstanding_tokens
                ):
                    self.stats["rejected_busy"] += 1
                    raise RejectedBusy(
                        f"outstanding-token budget exhausted "
                        f"({self._outstanding_tokens} + {cost} > "
                        f"{self.max_outstanding_tokens})",
                        self.retry_after_s,
                    )
                self._live += 1
                self._outstanding_tokens += cost
                self.stats["admitted"] += 1
        except RejectedBusy:
            if self._c_rejected is not None:
                self._c_rejected["busy"].inc()
            raise
        if self._c_admitted is not None:
            self._c_admitted.inc()
        if self._g_depth is not None:
            self._g_depth.inc()
            self._g_tokens.inc(cost)
        return Ticket(cost_tokens=cost)

    def release(self, ticket: Ticket, *, tpot_s: Optional[float] = None) -> None:
        """Return a ticket's budget; ``tpot_s`` (seconds per OUTPUT token
        of the completed request) feeds the shedding estimate."""
        with self._lock:
            if ticket.released:
                return
            ticket.released = True
            self._live -= 1
            self._outstanding_tokens -= ticket.cost_tokens
            if self._g_depth is not None:
                self._g_depth.dec()
                self._g_tokens.dec(ticket.cost_tokens)
            if tpot_s is not None and tpot_s > 0:
                if self._tpot_ewma is None:
                    self._tpot_ewma = tpot_s
                else:
                    self._tpot_ewma += self._alpha * (tpot_s - self._tpot_ewma)

    def snapshot(self) -> Dict[str, float]:
        """Counters + live budgets for /metrics and capacity sampling."""
        with self._lock:
            out: Dict[str, float] = dict(self.stats)
            out["live_requests"] = self._live
            out["outstanding_tokens"] = self._outstanding_tokens
            out["max_queue_depth"] = self.max_queue_depth
            out["max_outstanding_tokens"] = self.max_outstanding_tokens
            if self._tpot_ewma is not None:
                out["tpot_ewma_s"] = self._tpot_ewma
        return out
