"""Long-lived engine thread: the online request lifecycle over ServingEngine.

``ServingEngine.run()`` is offline — every request is submitted up front
and the call drains to completion. This loop makes the engine ONLINE:

  - one dedicated thread owns the engine (and therefore all device
    dispatch; JAX state never crosses threads) and repeatedly calls
    ``pipeline_tick()``, the single deep-pipelined scheduler turn;
  - gateway threads ``submit()`` into a thread-safe inbox; the loop
    drains it BETWEEN scheduler turns, so requests arriving mid-decode
    join the engine's waiting queue and are admitted at the next window
    boundary without disturbing in-flight windows;
  - committed tokens stream to per-request queues via the engine's
    ``on_token`` hook (commit time = reap time under deep pipelining, so
    a streamed token is never retracted);
  - cancellation and per-request deadlines are applied between turns:
    the engine's ``cancel()`` flushes the in-flight window queue before
    releasing the victim's row and pool blocks (see ServingEngine.cancel
    for why the flush must come first), so surviving requests' outputs
    are bit-identical to a run that never saw the victim.

Terminal statuses mirror the HTTP story: ``done`` (200), ``cancelled``
(499 client closed), ``expired`` (504 deadline), ``error`` (500).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import warnings
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from pretraining_llm_tpu.frontend.admission import (
    AdmissionController,
    RejectedBusy,
    RejectedInfeasible,
    Ticket,
)
from pretraining_llm_tpu.observability.capacity import (
    CapacitySampler,
    DecisionLog,
)

TERMINAL_STATUSES = ("done", "cancelled", "expired", "error")

# Distinguishes "caller made no tracing decision" (loop samples from its
# own tracer) from an explicit trace=None (gateway decided: unsampled).
_TRACE_UNSET = object()


def _finish_trace(trace: Any, status: str, **meta: Any) -> None:
    """Finish a request trace UNLESS its owner deferred the root: the
    fleet router marks lineage-tree roots ``finish_deferred`` because an
    attempt-level terminal here (e.g. "error" on a replica crash) is not
    the request's fate — the router redrives and finishes the root once
    the lineage settles."""
    if trace is None or getattr(trace, "finish_deferred", False):
        return
    trace.finish(status, **meta)


@dataclasses.dataclass
class FrontendRequest:
    """One in-flight request as the frontend sees it. ``out_q`` carries
    ``("token", int)`` items followed by exactly one
    ``("end", status, info)`` tuple; ``tokens``/``status``/``info`` are
    the loop thread's authoritative copies, safe to read after the end
    event has been consumed."""

    prompt: List[int]
    max_new: int
    deadline: Optional[float]  # monotonic deadline, None = none
    submitted_s: float
    ticket: Optional[Ticket] = None
    trace: Any = None  # observability.tracing.RequestTrace | None
    out_q: "queue.Queue[Tuple]" = dataclasses.field(default_factory=queue.Queue)
    rid: Optional[int] = None
    status: str = "queued"
    tokens: List[int] = dataclasses.field(default_factory=list)
    info: Dict[str, Any] = dataclasses.field(default_factory=dict)
    cancel_requested: bool = False
    # Scheduling priority (higher = more important). The loop itself is
    # FIFO regardless; the fleet router's brownout mode sheds by it.
    priority: int = 0

    def events(self, timeout: Optional[float] = None) -> Iterator[Tuple]:
        """Yield stream events until (and including) the terminal
        ``("end", status, info)``. ``timeout`` bounds the wait for EACH
        event; expiry raises ``TimeoutError``."""
        while True:
            try:
                ev = self.out_q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no stream event within {timeout}s (status={self.status})"
                )
            yield ev
            if ev[0] == "end":
                return

    def result(
        self, timeout: Optional[float] = None
    ) -> Tuple[str, List[int], Dict[str, Any]]:
        """Drain the stream; returns (status, tokens, info)."""
        for _ in self.events(timeout=timeout):
            pass
        return self.status, self.tokens, self.info


class EngineLoop:
    """Owns a ServingEngine on a dedicated thread; see module docstring.

    ``bus`` (optional, observability.events.EventBus) receives per-request
    lifecycle events: req_submit, req_done, req_cancelled, req_expired —
    each terminal event carries queue_wait_s/ttft_s/e2e_s and the token
    count, so the event stream is the serving audit log.
    """

    def __init__(
        self,
        engine: Any,
        *,
        admission: Optional[AdmissionController] = None,
        bus: Any = None,
        idle_wait_s: float = 0.005,
        clock: Any = time.monotonic,
        tracer: Any = None,
        registry: Any = None,
        capacity_ring: int = 512,
        weight_fingerprint_interval_s: float = 0.0,
    ) -> None:
        self.engine = engine
        self.admission = admission
        self.bus = bus
        self.idle_wait_s = float(idle_wait_s)
        # Deadlines compare against this clock; injectable so tests can
        # expire a request mid-flight deterministically.
        self._clock = clock
        # Per-request tracing (observability.tracing.Tracer). None = off:
        # submit() mints no trace and every recording site is a single
        # attribute/None check.
        self.tracer = tracer
        # Typed live metrics (observability.metrics.MetricsRegistry).
        # Histograms are observed once per terminal / reaped window, the
        # token counter once per committed token — each is one lock +
        # bisect, no device work anywhere.
        self.registry = registry
        self._h_ttft = self._h_tpot = self._h_queue = self._h_e2e = None
        self._c_terminal: Dict[str, Any] = {}
        self._c_tokens = self._c_submitted = None
        if registry is not None:
            self._h_ttft = registry.histogram(
                "ttft_seconds", "submit -> first committed token")
            self._h_tpot = registry.histogram(
                "tpot_seconds", "per-output-token seconds after the first")
            self._h_queue = registry.histogram(
                "queue_wait_seconds", "submit -> engine row claim")
            self._h_e2e = registry.histogram(
                "e2e_seconds", "submit -> terminal")
            self._c_terminal = {
                s: registry.counter(
                    "requests_terminal_total",
                    "requests reaching a terminal status", status=s)
                for s in TERMINAL_STATUSES
            }
            self._c_tokens = registry.counter(
                "tokens_streamed_total", "committed tokens streamed to clients")
            self._c_submitted = registry.counter(
                "requests_submitted_total", "requests accepted past admission")
            engine.window_hist = registry.histogram(
                "window_seconds", "decode-window dispatch -> reap wall time")
            engine.host_blocked_hist = registry.histogram(
                "host_blocked_seconds", "host blocked on window readback")
            cache = getattr(engine, "prefix_cache", None)
            if cache is not None:
                cache.bind(registry)
            engine.preempt_counter = registry.counter(
                "preemptions_total", "running requests preempted (pool dry)")
            engine.preempt_tokens_counter = registry.counter(
                "preempted_tokens_recomputed_total",
                "prompt tokens re-prefilled on preemption resume")
            engine.chunk_counter = registry.counter(
                "prefill_chunks_total", "prefill chunks dispatched")
            engine.chunk_tokens_counter = registry.counter(
                "prefill_chunk_tokens_total",
                "prompt tokens prefilled via the chunk lane")
            engine.chunk_interleaved_counter = registry.counter(
                "chunk_windows_interleaved_total",
                "scheduler ticks that dispatched chunks alongside a decode window")
            engine.chunk_dedicated_counter = registry.counter(
                "chunk_windows_dedicated_total",
                "scheduler ticks that dispatched chunks with no decode rows live")
            self._c_shed = {
                kind: registry.counter(
                    "deadline_shed_total",
                    "requests shed on deadline grounds", kind=kind)
                for kind in ("admission", "inflight")
            }
            engine.invalid_token_counter = registry.counter(
                "invalid_token_total",
                "out-of-vocab token ids caught by the reap sanity guard")
            engine.kv_mismatch_counter = registry.counter(
                "kv_checksum_mismatch_total",
                "cached KV pages that failed verify-on-acquire")
            # KV-pool residency is static per engine (pools are allocated
            # once at construction), so the gauge is set here rather than
            # on the per-window path. Includes scale pools on quantized
            # engines — it is the number capacity planning compares across
            # quantize modes at a fixed HBM budget.
            pool_info = getattr(engine, "pool_info", None)
            if pool_info is not None:
                info = pool_info()
                registry.gauge(
                    "kv_pool_bytes",
                    "resident KV pool bytes across layers, including "
                    "quantization scale pools",
                ).set(info["pool_bytes"])
                registry.gauge(
                    "kv_pool_bytes_per_block",
                    "KV pool bytes per block across layers (quantized "
                    "pools pack more tokens per byte)",
                ).set(info["bytes_per_block"])
        else:
            self._c_shed = {}
        # Capacity observability (observability/capacity.py): occupancy
        # sampler + scheduler decision log, installed on the engine like
        # the histograms above. ``capacity_ring`` bounds both buffers;
        # 0 disables the layer entirely (engine hooks stay None).
        if capacity_ring < 0:
            raise ValueError(
                f"capacity_ring must be >= 0, got {capacity_ring}"
            )
        self.capacity: Optional[CapacitySampler] = None
        self.decisions: Optional[DecisionLog] = None
        if capacity_ring > 0:
            self.capacity = CapacitySampler(
                engine.max_batch,
                engine.alloc.n_blocks - 1,  # block 0 is reserved scratch
                maxlen=capacity_ring,
                bus=bus,
                admission_snapshot_fn=(
                    admission.snapshot if admission is not None else None
                ),
                pool_layout=(
                    engine.pool_info()
                    if hasattr(engine, "pool_info") else None
                ),
            )
            self.decisions = DecisionLog(maxlen=capacity_ring, bus=bus)
            if registry is not None:
                self.capacity.bind(registry)
            engine.capacity = self.capacity
            engine.decisions = self.decisions
        engine.on_token = self._on_token
        engine.on_finish = self._on_finish
        # Engine-loop liveness: monotonic time of the last completed
        # scheduler turn; /healthz subtracts it from now to distinguish a
        # wedged loop (stuck in one turn) from a healthy idle one (which
        # keeps turning).
        self._last_turn = self._clock()
        self._inbox: "queue.Queue[FrontendRequest]" = queue.Queue()
        # Control mailbox: callables executed ON the loop thread between
        # scheduler turns. This is the only sanctioned way for another
        # thread to mutate engine device state (e.g. KV-page adoption
        # writes ``engine.pools`` — racing the loop thread's own pools
        # swap would lose one side's update). Reads of committed state
        # don't need it; writes do.
        self._control: "queue.Queue[Tuple[Callable[[], Any], queue.Queue]]" = (
            queue.Queue()
        )
        # Guards the submit-side put against the shutdown drain: once the
        # loop thread has drained the inbox (_drained), a late put would
        # enqueue a request nothing will ever terminate.
        self._inbox_lock = threading.Lock()
        self._drained = False
        self._by_rid: Dict[int, FrontendRequest] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # counters only
        # Guards the terminal status check-and-set: a wedged-stop caller
        # (_fail_outstanding) and a later-unwedging loop thread may race
        # to deliver the same request's terminal; exactly one must win.
        self._term_lock = threading.Lock()
        # Set by _run on the way down when the engine (or a hook) raised —
        # the fleet router reads it to distinguish "crashed" from
        # "stopped" without parsing terminal reasons.
        self.failure: Optional[BaseException] = None
        # Live weight fingerprint (resilience/integrity.py). Both values are
        # computed ON the loop thread — the only thread allowed to dispatch
        # device work for this engine — and merely READ by the router's
        # sentinel: ``weight_fingerprint0`` is pinned once at loop start (the
        # known-good reference), ``weight_fingerprint`` is refreshed every
        # ``weight_fingerprint_interval_s`` between scheduler turns. 0
        # disables the layer (both stay None; no device work added).
        if weight_fingerprint_interval_s < 0:
            raise ValueError(
                f"weight_fingerprint_interval_s must be >= 0, got "
                f"{weight_fingerprint_interval_s}"
            )
        self.weight_fingerprint_interval_s = float(weight_fingerprint_interval_s)
        self.weight_fingerprint0: Optional[float] = None
        self.weight_fingerprint: Optional[float] = None
        self._draining = False
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "cancelled": 0, "expired": 0,
            "errors": 0, "tokens_streamed": 0,
        }

    # -- public API (any thread) -------------------------------------------

    def start(self) -> "EngineLoop":
        assert self._thread is None, "start() called twice"
        self._thread = threading.Thread(
            target=self._run, name="engine-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> bool:
        """Stop the loop thread. Outstanding requests get an ``error``
        terminal event ("shutdown") — a serving process going down does
        not pretend in-flight work completed.

        Returns True when the loop thread exited within ``timeout``. On
        expiry the (daemon) thread is abandoned mid-turn, but its
        outstanding requests are NOT stranded: this caller delivers
        their error terminals itself — idempotent against the wedged
        thread waking up later and running its own shutdown path — and
        the timeout is surfaced as a warning plus the False return, so a
        fleet drain can eject the replica instead of trusting it."""
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout=timeout)
        if t.is_alive():
            n_out = len(self._by_rid) + self._inbox.qsize()
            warnings.warn(
                f"EngineLoop.stop: loop thread still alive after "
                f"{timeout}s; delivering error terminals for {n_out} "
                f"outstanding request(s) from the stopping thread",
                RuntimeWarning,
                stacklevel=2,
            )
            self._fail_outstanding(f"shutdown timeout after {timeout}s")
            return False
        self._thread = None
        return True

    def _fail_outstanding(self, reason: str) -> int:
        """Deliver ``error`` terminals for every request the loop thread
        will never get to (the wedged-stop path). Runs on the STOPPING
        thread and touches only host-side dicts and queues — the wedged
        loop thread still owns the engine, so no device work, no
        ``eng.cancel``. Returns how many terminals were delivered."""
        n = 0
        for req in list(self._by_rid.values()):
            if req.status not in TERMINAL_STATUSES:
                self._terminal(req, "error", reason=reason)
                n += 1
        with self._inbox_lock:
            self._drained = True
        while True:
            try:
                req = self._inbox.get_nowait()
            except queue.Empty:
                break
            self._terminal(req, "error", reason=reason)
            n += 1
        return n

    def __enter__(self) -> "EngineLoop":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        """True while the loop thread is alive and not stopping."""
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop accepting new work (``submit`` raises, ``/readyz`` reports
        not-ready) while in-flight requests keep decoding — the first half
        of the rolling-restart handshake: drain, redrive/finish, stop()."""
        self._draining = True

    def readiness(self) -> Dict[str, Any]:
        """The ``/readyz`` signal, distinct from ``/healthz`` liveness: a
        draining or stopped loop is alive (liveness ok) but must not
        receive new traffic (readiness not ok)."""
        return {
            "ready": self.running and not self._draining,
            "running": self.running,
            "draining": self._draining,
        }

    def submit(
        self,
        prompt: List[int],
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
        trace: Any = _TRACE_UNSET,
        priority: int = 0,
    ) -> FrontendRequest:
        """Validate, pass admission, enqueue. Raises ``ValueError`` on a
        malformed request (gateway: 400), ``RejectedBusy`` (429) or
        ``RejectedInfeasible`` (504) from the admission controller.
        Returns immediately with the request handle; tokens stream on its
        ``out_q``.

        ``trace`` is a gateway-minted RequestTrace (the gateway owns the
        inbound ``traceparent`` header and the sampling decision — an
        explicit ``None`` means "decided: unsampled" and the loop must
        NOT re-sample); with no gateway in the path (in-process loadgen)
        the argument is left unset and the loop mints one from its own
        tracer. A rejected request still gets a complete one-span trace:
        admission outcome + a ``rejected`` terminal."""
        if self._stop.is_set() or self._thread is None:
            raise RuntimeError("EngineLoop is not running")
        if self._draining:
            raise RuntimeError("EngineLoop is draining")
        if trace is _TRACE_UNSET:
            trace = (
                self.tracer.begin_request() if self.tracer is not None else None
            )
        trace_fields = (
            {"trace_id": trace.trace_id} if trace is not None else {}
        )
        try:
            # validate_request reads only construction-time constants —
            # safe from gateway threads while the loop thread runs.
            max_new = self.engine.validate_request(prompt, max_new_tokens)
        except ValueError as e:
            self._rejected(trace, "invalid", str(e), trace_fields)
            raise
        ticket = None
        t_adm = time.perf_counter()
        if self.admission is not None:
            # Prefix-cache hint: tokens already resident in shared blocks
            # won't charge the outstanding budget. peek() is lock-guarded
            # and side-effect-free, so gateway threads may call it while
            # the loop thread mutates the cache; the hint can go stale
            # either way before the engine's own lookup, which only makes
            # the discount conservative, never the budget unsound (the
            # ticket stores whatever was charged).
            cached = 0
            cache = getattr(self.engine, "prefix_cache", None)
            if cache is not None:
                cached = cache.peek(prompt)
            try:
                ticket = self.admission.try_admit(
                    len(prompt), max_new, deadline_s=deadline_s,
                    cached_tokens=cached,
                )
            except RejectedBusy as e:
                self._rejected(trace, "busy", e.reason, trace_fields)
                raise
            except RejectedInfeasible as e:
                self._rejected(trace, "infeasible", e.reason, trace_fields)
                raise
        try:
            now = self._clock()
            if trace is not None:
                trace.span("req.admission", t_adm, outcome="admitted")
                # The engine's queue span starts here: admission passed,
                # the request is now waiting (inbox + engine queue).
                trace.marks["submit"] = time.perf_counter()
            req = FrontendRequest(
                prompt=[int(t) for t in prompt],
                max_new=max_new,
                deadline=(now + deadline_s) if deadline_s is not None else None,
                submitted_s=now,
                ticket=ticket,
                trace=trace,
                priority=int(priority),
            )
            with self._lock:
                self.counters["submitted"] += 1
            if self._c_submitted is not None:
                self._c_submitted.inc()
            if self.bus is not None:
                self.bus.emit(
                    "req_submit", n_prompt=len(req.prompt), max_new=max_new,
                    deadline_s=deadline_s, **trace_fields,
                )
            with self._inbox_lock:
                if self._drained:
                    raise RuntimeError("EngineLoop is not running")
                self._inbox.put(req)
        except BaseException:
            # The request never reached the inbox, so _terminal will never
            # run for it — its admission budget must be returned here or
            # the queue-depth slot leaks until restart.
            if ticket is not None:
                self.admission.release(ticket)
            _finish_trace(trace, "error", reason="submit failed")
            raise
        self._wake.set()
        return req

    def _rejected(
        self,
        trace: Any,
        reason: str,
        detail: str,
        trace_fields: Dict[str, Any],
    ) -> None:
        """Bookkeeping for a request refused before the inbox: one
        ``req_rejected`` event, a decision record, and a finished
        (rejected) trace."""
        if self.bus is not None:
            self.bus.emit(
                "req_rejected", reason=reason, detail=detail, **trace_fields
            )
        if self.decisions is not None and reason in ("busy", "infeasible"):
            self.decisions.record(
                f"reject_{reason}", detail=detail,
                trace_id=trace_fields.get("trace_id"),
            )
        if reason == "infeasible" and self._c_shed:
            self._c_shed["admission"].inc()
        if trace is not None:
            trace.span(
                "req.admission", time.perf_counter(),
                outcome="rejected", reason=reason,
            )
            _finish_trace(trace, "rejected", reason=reason)

    def run_on_loop(
        self, fn: Callable[[], Any], *, timeout: Optional[float] = 30.0
    ) -> Any:
        """Run ``fn()`` on the loop thread between scheduler turns and
        return its result (re-raising its exception). The engine owns all
        device dispatch on that one thread, so any caller that must WRITE
        engine state (KV-page adoption swaps ``engine.pools``) funnels
        through here instead of racing the turn loop. Draining loops
        still execute control work — adoption into a draining replica is
        legal; only a stopped/dead loop refuses."""
        if self._stop.is_set() or self._thread is None or not self._thread.is_alive():
            raise RuntimeError("EngineLoop is not running")
        done: "queue.Queue[Tuple[str, Any]]" = queue.Queue(maxsize=1)
        self._control.put((fn, done))
        self._wake.set()
        try:
            kind, value = done.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"loop-thread control call did not complete in {timeout}s"
            )
        if kind == "err":
            raise value
        return value

    def cancel(self, req: FrontendRequest) -> None:
        """Request cancellation (client disconnect / explicit abort). The
        loop applies it between scheduler turns; tokens already committed
        stay delivered, then the handle gets a ``cancelled`` terminal."""
        req.cancel_requested = True
        self._wake.set()

    def last_turn_age_s(self) -> float:
        """Seconds since the loop thread last COMPLETED a scheduler turn
        — the /healthz liveness signal. A healthy loop (busy or idle)
        keeps this near zero; a loop wedged inside one turn (a hung
        device dispatch) lets it grow without bound."""
        return max(0.0, self._clock() - self._last_turn)

    @property
    def active_requests(self) -> int:
        """Requests in the system (inbox + engine), the router's load and
        spill signal. A point-in-time read off host containers only."""
        return len(self._by_rid) + self._inbox.qsize()

    def metrics(self) -> Dict[str, float]:
        """Counter snapshot for /metrics: loop counters + live gauges +
        the engine's numeric stats (prefixed ``engine_``) + admission."""
        with self._lock:
            out: Dict[str, float] = dict(self.counters)
        out["active_requests"] = self.active_requests
        for k, v in list(self.engine.stats.items()):
            if isinstance(v, (int, float)):
                out[f"engine_{k}"] = v
        if self.admission is not None:
            for k, v in self.admission.snapshot().items():
                out[f"admission_{k}"] = v
        return out

    # -- live introspection (gateway threads) --------------------------------
    #
    # Both debug views read engine host state WITHOUT the loop thread's
    # cooperation: every container touched (rows list, waiting deque,
    # _by_rid dict, req_timing) is only ever mutated between scheduler
    # turns, and each read is a single snapshot (list()/dict()) of a
    # structure CPython mutates atomically — so a concurrent turn can make
    # the view stale by one boundary, never torn mid-request. Purely
    # host-side: no device access, nothing on the hot path.

    def debug_requests(self) -> List[Dict[str, Any]]:
        """Per-request live state for /debug/requests: frontend status,
        engine phase (row vs. queue), blocks held, cached tokens, and the
        preemption count — the "where is my request right now" view."""
        eng = self.engine
        on_row = {}
        for row, ereq in enumerate(list(eng.rows)):
            if ereq is not None:
                on_row[ereq.rid] = (row, ereq)
        queued = {ereq.rid: ereq for ereq in list(eng.waiting)}
        now = self._clock()
        out: List[Dict[str, Any]] = []
        for rid, req in list(self._by_rid.items()):
            rec: Dict[str, Any] = {
                "rid": rid,
                "status": req.status,
                "n_prompt": len(req.prompt),
                "max_new": req.max_new,
                "n_tokens": len(req.tokens),
            }
            if req.trace is not None:
                rec["trace_id"] = req.trace.trace_id
            if req.deadline is not None:
                rec["deadline_remaining_s"] = round(req.deadline - now, 6)
            ereq = None
            if rid in on_row:
                row, ereq = on_row[rid]
                rec["phase"] = "decode"
                rec["row"] = row
            elif rid in queued:
                ereq = queued[rid]
                rec["phase"] = "queued"
            else:
                rec["phase"] = "inbox"
            if ereq is not None:
                rec["blocks_held"] = len(ereq.blocks)
                rec["blocks_shared"] = ereq.n_shared
                rec["preemptions"] = ereq.preemptions
            timing = eng.req_timing.get(rid)
            if timing and "cached_tokens" in timing:
                rec["cached_tokens"] = timing["cached_tokens"]
            out.append(rec)
        return out

    def debug_engine(self) -> Dict[str, Any]:
        """Engine-wide capacity state for /debug/engine: pool-block
        accounting (must tie out against the allocator — the CI gate
        asserts it), row occupancy, queue depths, the occupancy ring
        tail, and decision-log totals + tail."""
        eng = self.engine
        pool_total = eng.alloc.n_blocks - 1  # block 0 is reserved scratch
        free = eng.alloc.available
        cache = getattr(eng, "prefix_cache", None)
        cold = cache.evictable if cache is not None else 0
        out: Dict[str, Any] = {
            "rows": {
                "active": sum(r is not None for r in list(eng.rows)),
                "capacity": eng.max_batch,
            },
            "waiting": len(eng.waiting),
            "inbox": self._inbox.qsize(),
            "pool": {
                "total": pool_total,
                "free": free,
                "cold": cold,
                "live": pool_total - free - cold,
            },
            # Pool byte/dtype identity (quantize mode, KV dtype, scale
            # dtype, bytes-per-block): how an operator confirms which
            # graph a replica is actually serving from /debug/engine.
            **(
                {"pool_layout": eng.pool_info()}
                if hasattr(eng, "pool_info") else {}
            ),
            "stats": {
                k: v for k, v in list(eng.stats.items())
                if isinstance(v, (int, float))
            },
        }
        if cache is not None:
            out["prefix_cache"] = cache.debug_snapshot()
        if self.admission is not None:
            out["admission"] = self.admission.snapshot()
        if self.capacity is not None:
            out["occupancy"] = self.capacity.tail(32)
            out["windows_sampled"] = self.capacity.windows_sampled
        if self.decisions is not None:
            out["decisions"] = {
                "counts": self.decisions.counts_snapshot(),
                "tail": self.decisions.tail(32),
            }
        return out

    # -- loop thread --------------------------------------------------------

    def _run(self) -> None:
        eng = self.engine
        failure: Optional[BaseException] = None
        fp_interval = self.weight_fingerprint_interval_s
        last_fp = self._clock()
        if fp_interval > 0:
            # Pin the known-good reference before serving the first request.
            # Both the pin and every periodic refresh run HERE so the device
            # reduction stays on the one thread that owns engine dispatch.
            from pretraining_llm_tpu.resilience.integrity import weight_fingerprint
            self.weight_fingerprint0 = weight_fingerprint(eng.params)
            self.weight_fingerprint = self.weight_fingerprint0
        try:
            while True:
                self._wake.clear()
                self._drain_control()
                self._drain_inbox()
                self._apply_cancels_and_deadlines()
                if self._stop.is_set():
                    break
                busy = False
                if eng.has_work() or eng._inflight:
                    busy = eng.pipeline_tick()
                    # A long window may have carried requests past their
                    # deadlines; apply before the next dispatch extends them.
                    self._apply_cancels_and_deadlines()
                self._last_turn = self._clock()
                if fp_interval > 0 and self._clock() - last_fp >= fp_interval:
                    self.weight_fingerprint = weight_fingerprint(eng.params)
                    last_fp = self._clock()
                if not busy and self._inbox.empty() and not self._stop.is_set():
                    self._wake.wait(self.idle_wait_s)
        except BaseException as e:
            failure = e
            self.failure = e
            from pretraining_llm_tpu.resilience.integrity import IntegrityError
            if self.bus is not None and isinstance(e, IntegrityError):
                self.bus.emit(
                    "integrity_invalid_token",
                    rid=getattr(e, "rid", None),
                    token=getattr(e, "token", None),
                )
            raise
        finally:
            # Runs on clean stop() AND when the engine (or a hook) raised:
            # every outstanding request must get a terminal event, or the
            # gateway threads blocked in result()/events() hang forever.
            # _stop also makes submit() raise instead of enqueueing into a
            # dead loop.
            self._stop.set()
            reason = (
                "shutdown" if failure is None
                else f"engine failure: {failure!r}"
            )
            try:
                # Drain device state so nothing is mid-write, then fail
                # the survivors loudly. A FAILED engine's flush must not
                # stream or complete anything (after an integrity trip the
                # commit stream is exactly what can't be trusted — e.g. the
                # reap that raised already advanced past the bad token, so
                # later windows would skip a position): mute the callbacks
                # and let every request take the error terminal below,
                # which redrives it from its last CLEAN committed frontier.
                if failure is not None:
                    eng.on_token = None
                    eng.on_finish = None
                eng._flush_inflight()
            except Exception:
                pass  # the engine is already broken; still fail survivors
            for req in list(self._by_rid.values()):
                try:
                    if req.rid is not None:
                        eng.cancel(req.rid)
                except Exception:
                    pass
                self._terminal(req, "error", reason=reason)
            with self._inbox_lock:
                self._drained = True
            while True:
                try:
                    req = self._inbox.get_nowait()
                except queue.Empty:
                    break
                self._terminal(req, "error", reason=reason)
            # Control callers blocked in run_on_loop must not hang until
            # their timeout: the loop is down, tell them now.
            while True:
                try:
                    _, done = self._control.get_nowait()
                except queue.Empty:
                    break
                try:
                    done.put_nowait(
                        ("err", RuntimeError(f"EngineLoop stopped: {reason}"))
                    )
                except queue.Full:
                    pass

    def _drain_control(self) -> None:
        """Execute queued control callables (loop thread). A callable's
        exception is delivered to its caller, never allowed to kill the
        loop — control work is auxiliary to serving."""
        while True:
            try:
                fn, done = self._control.get_nowait()
            except queue.Empty:
                return
            try:
                result = ("ok", fn())
            except BaseException as e:  # delivered, not raised here
                result = ("err", e)
            try:
                done.put_nowait(result)
            except queue.Full:
                pass  # caller timed out and went away

    def _drain_inbox(self) -> None:
        eng = self.engine
        while True:
            try:
                req = self._inbox.get_nowait()
            except queue.Empty:
                return
            if req.cancel_requested:
                self._terminal(req, "cancelled")
                continue
            now = self._clock()
            if req.deadline is not None and now >= req.deadline:
                self._terminal(req, "expired")
                continue
            try:
                req.rid = eng.submit(req.prompt, req.max_new)
            except ValueError as e:  # pre-validated; belt and suspenders
                self._terminal(req, "error", reason=str(e))
                continue
            if req.trace is not None:
                eng.set_trace(req.rid, req.trace)
            req.status = "active"
            self._by_rid[req.rid] = req

    def _apply_cancels_and_deadlines(self) -> None:
        eng = self.engine
        now = self._clock()
        for rid, req in list(self._by_rid.items()):
            if req.status in TERMINAL_STATUSES:
                continue
            status = None
            if req.cancel_requested:
                status = "cancelled"
            elif req.deadline is not None and now >= req.deadline:
                status = "expired"
            if status is None:
                continue
            # cancel() may flush the queue; the flush can FINISH this
            # request (tokens stream, _on_finish sends the done terminal)
            # — then cancellation lost the race and there is nothing to do.
            if eng.cancel(rid):
                self._terminal(req, status)

    # -- engine hooks (loop thread) ----------------------------------------

    def _on_token(self, rid: int, tok: int) -> None:
        req = self._by_rid.get(rid)
        if req is None:
            return
        req.tokens.append(tok)
        with self._lock:
            self.counters["tokens_streamed"] += 1
        if self._c_tokens is not None:
            self._c_tokens.inc()
        req.out_q.put(("token", tok))

    def _on_finish(self, rid: int, out: List[int]) -> None:
        req = self._by_rid.get(rid)
        if req is None:
            return
        req.tokens = list(out)  # authoritative (== concatenated stream)
        self._terminal(req, "done")

    # -- terminal bookkeeping (loop thread) --------------------------------

    _COUNTER_FOR = {
        "done": "completed", "cancelled": "cancelled",
        "expired": "expired", "error": "errors",
    }

    def _terminal(self, req: FrontendRequest, status: str, **info: Any) -> None:
        with self._term_lock:
            if req.status in TERMINAL_STATUSES:
                return
            req.status = status
        eng = self.engine
        timing: Dict[str, float] = {}
        if req.rid is not None:
            timing = eng.timing_summary(req.rid)
            self._by_rid.pop(req.rid, None)
            # Bound long-lived growth: the loop owns delivery, the engine
            # need not keep per-request state past the terminal event.
            eng.req_timing.pop(req.rid, None)
            eng.finished.pop(req.rid, None)
            eng.cancelled.discard(req.rid)
            eng.pop_trace(req.rid)
        info.update(timing)
        info["n_tokens"] = len(req.tokens)
        if req.trace is not None:
            info["trace_id"] = req.trace.trace_id
        req.info = info
        if status == "expired":
            # Deadline shed mid-flight: the decision-log twin of the
            # admission-time infeasible reject.
            if self._c_shed:
                self._c_shed["inflight"].inc()
            if self.decisions is not None:
                self.decisions.record(
                    "expire_inflight", rid=req.rid,
                    trace_id=info.get("trace_id"),
                    n_tokens=len(req.tokens),
                )
        tpot = None
        if (
            status == "done"
            and len(req.tokens) > 1
            and "ttft_s" in timing
            and "e2e_s" in timing
        ):
            tpot = (timing["e2e_s"] - timing["ttft_s"]) / (len(req.tokens) - 1)
            info["tpot_s"] = tpot
        if self.admission is not None and req.ticket is not None:
            self.admission.release(req.ticket, tpot_s=tpot)
        with self._lock:
            self.counters[self._COUNTER_FOR[status]] += 1
        if self.registry is not None:
            # e2e is observed for EVERY terminal (engine timing when the
            # request ran, loop clock otherwise) so the histogram _count
            # equals the terminal-event count by construction; the other
            # latencies only exist for phases the request reached.
            self._h_e2e.observe(
                timing.get("e2e_s", self._clock() - req.submitted_s))
            if "queue_wait_s" in timing:
                self._h_queue.observe(timing["queue_wait_s"])
            if "ttft_s" in timing:
                self._h_ttft.observe(timing["ttft_s"])
            if tpot is not None:
                self._h_tpot.observe(tpot)
            self._c_terminal[status].inc()
        if req.trace is not None and not req.trace.finished:
            if "admit" not in req.trace.marks:
                # Never admitted (cancelled/expired in the inbox or the
                # engine's waiting queue): close the queue span here so
                # the tree is still complete — queue time IS where this
                # request's whole life went.
                req.trace.span(
                    "req.queue",
                    req.trace.marks.get("submit", req.trace.t0),
                    outcome=status,
                )
            _finish_trace(req.trace, status, n_tokens=len(req.tokens))
        if self.bus is not None:
            self.bus.emit(f"req_{status}", **info)
        req.out_q.put(("end", status, info))
