"""HTTP/SSE serving gateway over the EngineLoop — stdlib only.

A deliberately small, dependency-free frontend (http.server's
ThreadingHTTPServer): one handler thread per connection blocks on its
request's stream queue while the single engine-loop thread does all
device work. Endpoints:

  POST /v1/generate   JSON in -> full JSON response, or SSE token
                      streaming when ``"stream": true`` (one
                      ``data: {...}`` event per committed token, then a
                      terminal ``data: {"done": ...}`` and
                      ``data: [DONE]``); an inbound W3C ``traceparent``
                      header joins the caller's trace (when the loop has
                      a tracer), and terminal bodies carry ``trace_id``
                      plus — behind a fleet router — ``replica`` (which
                      one served the final attempt) and ``redrives``, so
                      a client can correlate its response with the
                      request's lineage tree without parsing the trace;
  GET  /healthz       liveness + queue gauges + engine-loop staleness
                      (seconds since the last scheduler turn; 503 past
                      ``healthz_stale_after_s`` — a wedged loop must not
                      look like a healthy idle process);
  GET  /readyz        readiness, distinct from liveness: 503 while the
                      backend is draining or has no replica accepting
                      traffic (rolling restarts pull a replica from the
                      balancer via /readyz while /healthz stays green —
                      alive-but-not-ready must not get new work);
  GET  /metrics       Prometheus text exposition: the loop's typed
                      registry (counters/histograms) when wired, plus
                      loop/engine/admission gauges and typed HTTP
                      counters (``..._total``);
  GET  /slo           the live SLO snapshot (observability/slo.py):
                      rolling-window latency distributions (sketch
                      percentiles per replica + fleet-wide), per-class
                      error-budget status and burn rates, active/
                      recently-resolved alerts — and, behind a fleet
                      router, the aggregated worker health gauges
                      (Router.fleet_health). 404 when no SLO engine is
                      wired (``slo=`` here or a router with one);
  GET  /metricsz      the same numbers /metrics exposes, as one JSON
                      object (machine-readable: ``gauges`` is the
                      loop's counter snapshot, ``series`` the typed
                      registry snapshot when wired) — for pollers that
                      want values without parsing Prometheus text.

``loop`` is anything with the EngineLoop surface — a single EngineLoop or
a fleet Router (frontend/router.py); the gateway never inspects which.

Request schema (unknown keys are a 400 — a typo'd knob must not be
silently ignored):

  {"prompt": [1, 2, 3] | "text...",   # token ids, or text with a tokenizer
   "max_new_tokens": 32,              # required positive int
   "stream": false,                   # SSE streaming
   "deadline_s": 2.5,                 # optional per-request deadline
   "priority": 0}                     # brownout shedding order (fleet)

Status mapping: validation error 400, backpressure 429 (+ Retry-After),
infeasible/missed deadline 504, client-cancelled 499, engine failure 500.
The body always carries the lifecycle latencies the engine measured
(queue_wait_s / ttft_s / e2e_s).

Retry-After semantics: the header on a 429 is the admission controller's
base hint plus a small DETERMINISTIC jitter — a seeded PRNG sequence
(``retry_jitter_seed``), not wall-clock randomness — so a burst of
rejected clients that all honor the header fan out over
``[base, base * (1 + retry_jitter_frac)]`` instead of thundering back in
lockstep at a recovering fleet, while any run remains exactly
reproducible under a fixed seed. Values are whole seconds (RFC 7231
delta-seconds), never below 1.
"""

from __future__ import annotations

import json
import random
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from pretraining_llm_tpu.frontend.admission import (
    RejectedBusy,
    RejectedInfeasible,
)
from pretraining_llm_tpu.observability.export import prometheus_lines

_MAX_BODY_BYTES = 16 * 1024 * 1024
_REQUEST_KEYS = {"prompt", "max_new_tokens", "stream", "deadline_s", "priority"}


class _BadRequest(Exception):
    pass


class ServingGateway:
    """Owns the HTTP server; ``loop`` must already be started.

    ``encode``/``decode`` (optional) let clients send/receive text instead
    of token ids. ``port=0`` binds an ephemeral port (tests); read it back
    from ``.port``.
    """

    def __init__(
        self,
        loop: Any,
        *,
        host: str = "127.0.0.1",
        port: int = 8000,
        encode: Optional[Callable[[str], Any]] = None,
        decode: Optional[Callable[[Any], str]] = None,
        default_deadline_s: float = 0.0,
        healthz_stale_after_s: float = 0.0,
        retry_jitter_frac: float = 0.25,
        retry_jitter_seed: int = 0,
        slo: Optional[Any] = None,
    ) -> None:
        if healthz_stale_after_s < 0:
            raise ValueError(
                f"healthz_stale_after_s must be >= 0 (0 = disabled), got "
                f"{healthz_stale_after_s}"
            )
        if not 0.0 <= retry_jitter_frac <= 1.0:
            raise ValueError(
                f"retry_jitter_frac must be in [0, 1] (0 = no jitter), got "
                f"{retry_jitter_frac}"
            )
        self.loop = loop
        self.encode = encode
        self.decode = decode
        # Live SLO engine for GET /slo on the single-loop path; behind a
        # fleet router the loop's own slo_snapshot() wins (it folds the
        # aggregated worker health in).
        self.slo = slo
        self.default_deadline_s = float(default_deadline_s)
        # 0 disables the staleness 503: a cold-start jit compile can
        # legitimately hold the loop thread for minutes, so the threshold
        # is opt-in and deployment-tuned.
        self.healthz_stale_after_s = float(healthz_stale_after_s)
        # Deterministic-seeded Retry-After jitter (see module docstring):
        # one PRNG sequence per gateway, lock-guarded because handler
        # threads draw from it concurrently.
        self.retry_jitter_frac = float(retry_jitter_frac)
        self._retry_rng = random.Random(int(retry_jitter_seed))
        self._retry_rng_lock = threading.Lock()
        self._counters_lock = threading.Lock()
        self.http_counters: Dict[str, int] = {}
        gateway = self

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        class _Handler(_GatewayHandler):
            pass

        _Handler.gateway = gateway
        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "ServingGateway":
        """Serve on a background thread (scripts serve_forever inline)."""
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gateway", daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def count_response(self, code: int) -> None:
        with self._counters_lock:
            key = f"http_responses_{code}"
            self.http_counters[key] = self.http_counters.get(key, 0) + 1
            self.http_counters["http_requests_total"] = (
                self.http_counters.get("http_requests_total", 0) + 1
            )

    def _http_counter_lines(self) -> str:
        """The HTTP tallies as VALID Prometheus counters: one
        ``http_requests_total`` plus ``http_responses_total{code=...}``
        children (the per-code dict keys become a label, which is what
        they always were)."""
        with self._counters_lock:
            http = dict(self.http_counters)
        lines = [
            "# TYPE pllm_serving_http_requests_total counter",
            "pllm_serving_http_requests_total "
            f"{float(http.get('http_requests_total', 0))}",
            "# TYPE pllm_serving_http_responses_total counter",
        ]
        for key in sorted(http):
            if key.startswith("http_responses_"):
                code = key.rsplit("_", 1)[1]
                lines.append(
                    f'pllm_serving_http_responses_total{{code="{code}"}} '
                    f"{float(http[key])}"
                )
        return "\n".join(lines) + "\n"

    def retry_after_header(self, base_s: float) -> str:
        """Whole-second Retry-After value with deterministic-seeded jitter
        over ``[base, base * (1 + retry_jitter_frac)]``; never below 1."""
        with self._retry_rng_lock:
            u = self._retry_rng.random()
        jittered = float(base_s) * (1.0 + u * self.retry_jitter_frac)
        return f"{max(1, round(jittered))}"

    def metrics_text(self) -> str:
        merged: Dict[str, float] = dict(self.loop.metrics())
        render = getattr(self.loop, "render_metrics", None)
        if render is not None:
            # Fleet router: merged exposition over the fleet registry and
            # every replica's labeled registry, then the HTTP counters.
            return render(merged) + self._http_counter_lines()
        registry = getattr(self.loop, "registry", None)
        if registry is not None:
            # Typed series (counters + latency histograms) first, then the
            # legacy loop/engine/admission snapshot as gauges, then the
            # HTTP counters — one exposition, lint-clean.
            body = registry.render(extra_gauges=merged)
        else:
            body = prometheus_lines(merged, prefix="pllm_serving_")
        return body + self._http_counter_lines()


class _GatewayHandler(BaseHTTPRequestHandler):
    gateway: ServingGateway  # installed per-subclass by ServingGateway
    protocol_version = "HTTP/1.1"

    # Route server chatter away from stderr; the gateway is not a log.
    def log_message(self, fmt: str, *args: Any) -> None:
        pass

    # -- plumbing -----------------------------------------------------------

    def _send_json(self, code: int, payload: Dict[str, Any], **headers: str) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            self.send_header("Connection", "close")
        for k, v in headers.items():
            self.send_header(k.replace("_", "-"), v)
        self.end_headers()
        self.wfile.write(body)
        self.gateway.count_response(code)

    def _read_json_body(self) -> Dict[str, Any]:
        length = self.headers.get("Content-Length")
        if length is None:
            raise _BadRequest("missing Content-Length")
        try:
            n = int(length)
        except ValueError:
            raise _BadRequest(f"bad Content-Length {length!r}")
        if n > _MAX_BODY_BYTES:
            raise _BadRequest(f"body too large ({n} bytes)")
        try:
            payload = json.loads(self.rfile.read(n).decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise _BadRequest(f"invalid JSON body: {e}")
        if not isinstance(payload, dict):
            raise _BadRequest("body must be a JSON object")
        return payload

    # -- GET ----------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        if self.path == "/healthz":
            gw = self.gateway
            m = gw.loop.metrics()
            age = gw.loop.last_turn_age_s()
            stale = (
                gw.healthz_stale_after_s > 0
                and age > gw.healthz_stale_after_s
            )
            self._send_json(503 if stale else 200, {
                "status": "stale" if stale else "ok",
                "active_requests": m.get("active_requests", 0),
                "completed": m.get("completed", 0),
                "engine_loop_last_turn_age_s": round(age, 3),
            })
        elif self.path == "/readyz":
            gw = self.gateway
            ready_fn = getattr(gw.loop, "readiness", None)
            if ready_fn is None:
                # Backend without drain support: ready iff alive enough to
                # take a submit (best-effort parity with old behavior).
                body = {"ready": True}
            else:
                body = dict(ready_fn())
            ok = bool(body.get("ready", False))
            body["status"] = "ready" if ok else "not-ready"
            self._send_json(200 if ok else 503, body)
        elif self.path == "/metrics":
            body = self.gateway.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            self.gateway.count_response(200)
        elif self.path.split("?", 1)[0] == "/slo":
            gw = self.gateway
            snap_fn = getattr(gw.loop, "slo_snapshot", None)
            if snap_fn is not None:
                self._send_json(200, snap_fn())
            elif gw.slo is not None:
                self._send_json(200, gw.slo.snapshot())
            else:
                self._send_json(
                    404, {"error": "no SLO engine configured"}
                )
        elif self.path.split("?", 1)[0] == "/metricsz":
            gw = self.gateway
            body: Dict[str, Any] = {"gauges": gw.loop.metrics()}
            registry = getattr(gw.loop, "registry", None)
            if registry is not None and hasattr(registry, "snapshot"):
                body["series"] = registry.snapshot()
            with gw._counters_lock:
                body["http"] = dict(gw.http_counters)
            self._send_json(200, body)
        elif self.path.split("?", 1)[0] == "/debug/requests":
            # Live per-request introspection — best-effort reads off the
            # hot path (see EngineLoop.debug_requests); stale by at most
            # one scheduler turn, never torn.
            self._send_json(200, {"requests": self.gateway.loop.debug_requests()})
        elif self.path.split("?", 1)[0] == "/debug/engine":
            self._send_json(200, self.gateway.loop.debug_engine())
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    # -- POST /v1/generate --------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        if self.path != "/v1/generate":
            # The body was never read: on a keep-alive connection the next
            # pipelined request would be parsed from these body bytes, so
            # close instead of corrupting the framing.
            self.close_connection = True
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        gw = self.gateway
        try:
            payload = self._read_json_body()
            prompt, max_new, stream, deadline_s, priority = (
                self._parse_request(payload)
            )
        except _BadRequest as e:
            # Some rejections (missing/huge Content-Length) fire before the
            # body is read — same unread-body framing hazard as above.
            self.close_connection = True
            self._send_json(400, {"error": str(e)})
            return
        trace = None
        tracer = getattr(gw.loop, "tracer", None)
        if tracer is not None:
            # Gateway accept is where the trace is minted: an inbound W3C
            # traceparent joins the caller's trace (its sampling decision
            # honored), otherwise head-sampling applies.
            trace = tracer.begin_request(self.headers.get("traceparent"))
        err_fields = (
            {"trace_id": trace.trace_id} if trace is not None else {}
        )
        try:
            req = gw.loop.submit(
                prompt, max_new, deadline_s=deadline_s, trace=trace,
                priority=priority,
            )
        except ValueError as e:
            # The engine's submit-time validation: the 4xx that replaces a
            # downstream shape error.
            self._send_json(400, {"error": str(e), **err_fields})
            return
        except RejectedBusy as e:
            self._send_json(
                429, {"error": f"overloaded: {e.reason}", **err_fields},
                Retry_After=gw.retry_after_header(e.retry_after_s),
            )
            return
        except RejectedInfeasible as e:
            self._send_json(
                504,
                {"error": f"deadline cannot be met: {e.reason}", **err_fields},
            )
            return
        except RuntimeError as e:
            # EngineLoop stopped (or died) between the health check and the
            # enqueue: the process is going away, tell the client to go
            # elsewhere rather than killing the handler thread.
            self.close_connection = True
            self._send_json(503, {"error": str(e)})
            return
        if stream:
            self._respond_sse(req)
        else:
            self._respond_full(req)

    def _parse_request(self, payload: Dict[str, Any]):
        unknown = set(payload) - _REQUEST_KEYS
        if unknown:
            raise _BadRequest(
                f"unknown request keys {sorted(unknown)}; expected subset "
                f"of {sorted(_REQUEST_KEYS)}"
            )
        if "prompt" not in payload:
            raise _BadRequest("missing 'prompt'")
        if "max_new_tokens" not in payload:
            raise _BadRequest("missing 'max_new_tokens'")
        prompt = payload["prompt"]
        if isinstance(prompt, str):
            if self.gateway.encode is None:
                raise _BadRequest(
                    "text prompts need a tokenizer; this gateway accepts "
                    "token-id lists only"
                )
            prompt = list(self.gateway.encode(prompt))
        elif not isinstance(prompt, list):
            raise _BadRequest("'prompt' must be a string or a list of ints")
        max_new = payload["max_new_tokens"]
        if isinstance(max_new, bool) or not isinstance(max_new, int):
            raise _BadRequest("'max_new_tokens' must be an integer")
        stream = payload.get("stream", False)
        if not isinstance(stream, bool):
            raise _BadRequest("'stream' must be a boolean")
        deadline_s = payload.get("deadline_s")
        if deadline_s is not None:
            if isinstance(deadline_s, bool) or not isinstance(
                deadline_s, (int, float)
            ):
                raise _BadRequest("'deadline_s' must be a number")
            deadline_s = float(deadline_s)
            if deadline_s <= 0:
                raise _BadRequest("'deadline_s' must be > 0")
        elif self.gateway.default_deadline_s > 0:
            deadline_s = self.gateway.default_deadline_s
        priority = payload.get("priority", 0)
        if isinstance(priority, bool) or not isinstance(priority, int):
            raise _BadRequest("'priority' must be an integer")
        return prompt, max_new, stream, deadline_s, priority

    _STATUS_CODE = {"done": 200, "expired": 504, "cancelled": 499, "error": 500}

    def _respond_full(self, req: Any) -> None:
        status, tokens, info = req.result()
        body: Dict[str, Any] = {"status": status, "tokens": tokens, **info}
        if status != "done":
            body["error"] = {
                "expired": "deadline exceeded during generation",
                "cancelled": "request cancelled",
                "error": f"engine failure: {info.get('reason', 'unknown')}",
            }[status]
        if self.gateway.decode is not None:
            body["text"] = self.gateway.decode(tokens)
        try:
            self._send_json(self._STATUS_CODE[status], body)
        except (BrokenPipeError, ConnectionResetError, OSError):
            # Client went away while we were blocked on the result. The
            # request is already terminal by now, so cancel() is a no-op
            # belt-and-suspenders; what matters is not letting the handler
            # thread die with a traceback and counting the response as the
            # 499 it effectively was (the 200 in _send_json was never
            # counted — count_response comes after the failed write).
            self.gateway.loop.cancel(req)
            self.gateway.count_response(499)
            self.close_connection = True

    def _respond_sse(self, req: Any) -> None:
        gw = self.gateway
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        code = 200
        try:
            i = 0
            for ev in req.events():
                if ev[0] == "token":
                    self._sse_data({"token": ev[1], "index": i})
                    i += 1
                else:  # ("end", status, info)
                    _, status, info = ev
                    final: Dict[str, Any] = {
                        "done": True, "status": status, **info
                    }
                    if gw.decode is not None:
                        final["text"] = gw.decode(req.tokens)
                    self._sse_data(final)
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                    code = self._STATUS_CODE[status]
        except (BrokenPipeError, ConnectionResetError, OSError):
            # Client went away mid-stream: release the row and pool blocks
            # now rather than decoding tokens nobody will read.
            gw.loop.cancel(req)
            code = 499
        gw.count_response(code)

    def _sse_data(self, obj: Dict[str, Any]) -> None:
        self.wfile.write(f"data: {json.dumps(obj)}\n\n".encode())
        self.wfile.flush()
