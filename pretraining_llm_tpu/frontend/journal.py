"""Write-ahead fleet journal: the router's crash-recoverable control
plane.

Append-only JSONL, one record per line, written at every
redrive-relevant transition so a restarted router can rebuild exactly
the state it needs to finish what the dead one started:

==========  ===========================================================
rec         written when / carries
==========  ===========================================================
member      router start — replica index, mode (spawn/attach/inproc),
            attach address if any
fence       router start and every eject — the replica's fence
            generation; recovery bumps past the MAX seen, so every
            frame the old router's workers still have in flight is
            stale by construction ("fence the old generation
            everywhere")
submit      request admitted — frid, prompt, max_new, priority,
            deadline_s, trace_id (write-ahead: BEFORE placement; the
            trace_id lets a recovered router CONTINUE the original
            distributed trace instead of minting an orphan root)
frontier    redrive — the committed token frontier carried to the
            survivor (token VALUES, not a count: recovery re-submits
            ``prompt + tokens`` and greedy decode makes the
            continuation bit-identical)
terminal    request finished (any status) — recovery skips it
next_frid   compaction bookkeeping — preserves the frid high-water
            mark across a rotation that dropped every terminal'd
            submit (frids must never be reused across a restart)
==========  ===========================================================

Compaction: the journal grows without bound under sustained load
(terminal'd submits are never dropped), so ``rotate_bytes > 0`` arms
size-threshold rotation — once the file exceeds the threshold after an
append, the journal is rewritten as exactly its ``recovery_plan`` fold
(max fences + live submits at their frontiers + the frid high-water
mark) via write-to-temp then atomic ``os.replace``. A crash at ANY
point mid-rotate leaves either the old complete file or the new
complete file, never a torn hybrid; a stray ``.rotate`` temp from a
crash is ignored by ``load`` and overwritten by the next rotation.

Recovery folds the records front to back (`recovery_plan`): live
requests are submits without terminals, each at its last journaled
frontier. Tokens streamed between the last frontier record and the
crash are simply re-decoded — greedy determinism makes the full output
identical, and exactly-once holds per router lifetime (terminal
records are what dedups across the restart).

Durability is flush-per-record (the OS page cache): the failure model
is a crashed ROUTER PROCESS on a healthy host — the same machine
restarts it. Torn final lines (crash mid-write) are tolerated on load.

No engine, socket, or JAX dependency: unit-testable in tier 1.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional


class FleetJournal:
    """Append-only JSONL writer with crash-tolerant load/replay."""

    def __init__(self, path: str, rotate_bytes: int = 0) -> None:
        if rotate_bytes < 0:
            raise ValueError(
                f"rotate_bytes must be >= 0 (0 = no rotation), got "
                f"{rotate_bytes}"
            )
        self.path = str(path)
        self.rotate_bytes = int(rotate_bytes)
        self.rotations = 0
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f: Optional[Any] = open(self.path, "a", encoding="utf-8")

    def append(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            f = self._f
            if f is None:
                return  # closed under a racing pump terminal; drop
            f.write(line)
            f.flush()
            if self.rotate_bytes > 0 and f.tell() >= self.rotate_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Rewrite the journal as its recovery fold (caller holds the
        lock). The fold is written to a sibling temp file and swapped in
        with ``os.replace`` — atomic on POSIX — so a crash mid-rotate
        leaves a loadable journal at every instant. If the rewrite
        fails, the original (oversize but complete) file keeps serving;
        rotation is an optimization, never a durability trade."""
        plan = self.recovery_plan(self.load(self.path))
        tmp = self.path + ".rotate"
        try:
            with open(tmp, "w", encoding="utf-8") as out:
                for idx in sorted(plan["fences"]):
                    out.write(json.dumps(
                        {"rec": "fence", "replica": idx,
                         "fence": plan["fences"][idx]},
                        separators=(",", ":")) + "\n")
                # next_frid first among request records: even if every
                # live submit terminates before the next rotation, the
                # frid high-water mark survives.
                out.write(json.dumps(
                    {"rec": "next_frid", "frid": plan["next_frid"]},
                    separators=(",", ":")) + "\n")
                for frid in sorted(plan["live"]):
                    ent = plan["live"][frid]
                    out.write(json.dumps(
                        {"rec": "submit", "frid": frid,
                         "prompt": ent["prompt"],
                         "max_new": ent["max_new"],
                         "priority": ent["priority"],
                         "deadline_s": ent["deadline_s"],
                         "trace_id": ent.get("trace_id")},
                        separators=(",", ":")) + "\n")
                    if ent["tokens"] or ent["redrives"]:
                        out.write(json.dumps(
                            {"rec": "frontier", "frid": frid,
                             "tokens": ent["tokens"],
                             "redrives": ent["redrives"]},
                            separators=(",", ":")) + "\n")
                out.flush()
                os.fsync(out.fileno())
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        old = self._f
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")
        self.rotations += 1
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        """Read every parseable record; a torn final line (crash
        mid-append) is skipped, mirroring how a real WAL discards its
        incomplete tail."""
        records: List[Dict[str, Any]] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except FileNotFoundError:
            pass
        return records

    @staticmethod
    def recovery_plan(records: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Fold the journal into what a restarting router needs:

        - ``fences``: per-replica MAX fence generation seen (the new
          router bumps past these before any worker re-attaches).
        - ``live``: frid -> {prompt, max_new, priority, deadline_s,
          trace_id, tokens, redrives} for every submit without a
          terminal, at its last journaled frontier.
        - ``next_frid``: one past the highest frid ever journaled (or
          the journaled ``next_frid`` high-water mark after a rotation
          dropped the terminal'd submits), so recovered and fresh
          requests never collide.
        """
        fences: Dict[int, int] = {}
        live: Dict[int, Dict[str, Any]] = {}
        next_frid = 0
        for rec in records:
            kind = rec.get("rec")
            if kind == "fence":
                idx = int(rec.get("replica", -1))
                fences[idx] = max(
                    fences.get(idx, 0), int(rec.get("fence", 0))
                )
            elif kind == "next_frid":
                next_frid = max(next_frid, int(rec.get("frid", 0)))
            elif kind == "submit":
                frid = int(rec["frid"])
                next_frid = max(next_frid, frid + 1)
                live[frid] = {
                    "prompt": [int(t) for t in rec.get("prompt", [])],
                    "max_new": int(rec.get("max_new", 1)),
                    "priority": int(rec.get("priority", 0)),
                    "deadline_s": rec.get("deadline_s"),
                    "trace_id": rec.get("trace_id"),
                    "tokens": [],
                    "redrives": 0,
                }
            elif kind == "frontier":
                ent = live.get(int(rec.get("frid", -1)))
                if ent is not None:
                    ent["tokens"] = [int(t) for t in rec.get("tokens", [])]
                    ent["redrives"] = int(rec.get("redrives", 0))
            elif kind == "terminal":
                live.pop(int(rec.get("frid", -1)), None)
        return {"fences": fences, "live": live, "next_frid": next_frid}
