"""Write-ahead fleet journal: the router's crash-recoverable control
plane.

Append-only JSONL, one record per line, written at every
redrive-relevant transition so a restarted router can rebuild exactly
the state it needs to finish what the dead one started:

==========  ===========================================================
rec         written when / carries
==========  ===========================================================
member      router start — replica index, mode (spawn/attach/inproc),
            attach address if any
fence       router start and every eject — the replica's fence
            generation; recovery bumps past the MAX seen, so every
            frame the old router's workers still have in flight is
            stale by construction ("fence the old generation
            everywhere")
submit      request admitted — frid, prompt, max_new, priority,
            deadline_s (write-ahead: BEFORE placement)
frontier    redrive — the committed token frontier carried to the
            survivor (token VALUES, not a count: recovery re-submits
            ``prompt + tokens`` and greedy decode makes the
            continuation bit-identical)
terminal    request finished (any status) — recovery skips it
==========  ===========================================================

Recovery folds the records front to back (`recovery_plan`): live
requests are submits without terminals, each at its last journaled
frontier. Tokens streamed between the last frontier record and the
crash are simply re-decoded — greedy determinism makes the full output
identical, and exactly-once holds per router lifetime (terminal
records are what dedups across the restart).

Durability is flush-per-record (the OS page cache): the failure model
is a crashed ROUTER PROCESS on a healthy host — the same machine
restarts it. Torn final lines (crash mid-write) are tolerated on load.

No engine, socket, or JAX dependency: unit-testable in tier 1.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional


class FleetJournal:
    """Append-only JSONL writer with crash-tolerant load/replay."""

    def __init__(self, path: str) -> None:
        self.path = str(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f: Optional[Any] = open(self.path, "a", encoding="utf-8")

    def append(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            f = self._f
            if f is None:
                return  # closed under a racing pump terminal; drop
            f.write(line)
            f.flush()

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        """Read every parseable record; a torn final line (crash
        mid-append) is skipped, mirroring how a real WAL discards its
        incomplete tail."""
        records: List[Dict[str, Any]] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except FileNotFoundError:
            pass
        return records

    @staticmethod
    def recovery_plan(records: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Fold the journal into what a restarting router needs:

        - ``fences``: per-replica MAX fence generation seen (the new
          router bumps past these before any worker re-attaches).
        - ``live``: frid -> {prompt, max_new, priority, deadline_s,
          tokens, redrives} for every submit without a terminal, at its
          last journaled frontier.
        - ``next_frid``: one past the highest frid ever journaled, so
          recovered and fresh requests never collide.
        """
        fences: Dict[int, int] = {}
        live: Dict[int, Dict[str, Any]] = {}
        next_frid = 0
        for rec in records:
            kind = rec.get("rec")
            if kind == "fence":
                idx = int(rec.get("replica", -1))
                fences[idx] = max(
                    fences.get(idx, 0), int(rec.get("fence", 0))
                )
            elif kind == "submit":
                frid = int(rec["frid"])
                next_frid = max(next_frid, frid + 1)
                live[frid] = {
                    "prompt": [int(t) for t in rec.get("prompt", [])],
                    "max_new": int(rec.get("max_new", 1)),
                    "priority": int(rec.get("priority", 0)),
                    "deadline_s": rec.get("deadline_s"),
                    "tokens": [],
                    "redrives": 0,
                }
            elif kind == "frontier":
                ent = live.get(int(rec.get("frid", -1)))
                if ent is not None:
                    ent["tokens"] = [int(t) for t in rec.get("tokens", [])]
                    ent["redrives"] = int(rec.get("redrives", 0))
            elif kind == "terminal":
                live.pop(int(rec.get("frid", -1)), None)
        return {"fences": fences, "live": live, "next_frid": next_frid}
