"""KV-page migration: cached prefix chains serialized into wire frames.

The fleet machinery so far only moves REQUESTS between hosts — a decode
worker re-prefills every prefix some other worker already computed. This
module makes KV state itself migratable, page by page:

  snapshot    ``snapshot_chain`` pulls the longest cached block chain for
              a prompt out of a sender engine's pool: per pool leaf (K,
              V, and quantization-scale leaves alike, layer-stacked) one
              contiguous byte string per page, plus a content digest
              computed with exactly the ``kv_block_digest`` algorithm —
              the same digest ``kv_checksum`` verifies at acquire, so a
              migrated page carries its integrity identity with it;
  framing     ``split_frames``/``join_frames`` batch pages into bounded
              ``kv_page`` wire frames (base64 inside the JSON framing of
              frontend/wire.py). Frames carry ``seq``/``n_frames`` so a
              torn transfer (missing or duplicated frame) is rejected as
              a unit, and ride the same ``g`` fence stamp as every other
              worker frame so stale-generation pages are dropped by the
              existing fence filters;
  adoption    ``adopt_chain`` inserts received pages into a receiver
              engine's pool BEHIND the prefix-cache publish path: verify
              each page's digest against its transported bytes, stop the
              chain at the first corrupt page (drop + count, never a
              wrong token — the request re-prefills what was dropped),
              scatter the accepted prefix into freshly reserved blocks,
              publish via ``PrefixCache.release_row`` (first writer
              wins: duplicate chains are freed back), and record the
              digest via ``set_checksum`` so verify-on-acquire guards
              migrated pages exactly like locally published ones.

Threading contract: ``snapshot_chain`` may run on any thread — it reads
only COMMITTED shared pages, pinned against eviction by an acquire-side
refcount, and pool arrays are immutable (a concurrent decode turn swaps
``engine.pools`` to a new array whose bytes at published blocks are
unchanged). ``adopt_chain`` WRITES ``engine.pools`` and must run on the
engine's loop thread (``EngineLoop.run_on_loop``) or a lost-update race
with the scheduler's own pools swap would corrupt live state.

Bit-identity story: every admission commits pool bytes through the
suffix-prefill lane as a pure function of the token's prompt prefix
(see ServingEngine._admit — int8-KV engines route even full misses
through it for exactly this reason), so a page computed on the prefill
tier is byte-identical to the page the decode tier would have computed
itself, and greedy outputs are unchanged by migration.
"""

from __future__ import annotations

import base64
import hashlib
from typing import Any, Dict, List, Optional

import numpy as np

# Per-frame payload budget for page data (pre-base64 bytes). Well under
# wire.MAX_FRAME_BYTES even after base64's 4/3 expansion plus JSON
# overhead; a single page larger than the budget still travels (one page
# per frame) — the hard frame cap in wire.encode_frame is the backstop.
KV_FRAME_BUDGET_BYTES = 8 * 1024 * 1024

# Transfer payload schema revision (inside the frames; the frame kinds
# themselves are negotiated via wire.PROTO_VERSION >= 3).
XFER_VERSION = 1


def _block_axis(leaf: Any) -> int:
    # Mirrors resilience.integrity._block_axis: stacked pools are
    # (L, n_blocks, block_size, ...), per-layer leaves (n_blocks, ...).
    return 1 if getattr(leaf, "ndim", 0) >= 5 else 0


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes extensions
    (bfloat16 scale pools) plain numpy does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _page_digest(arrays: List[np.ndarray]) -> str:
    """Content digest over one page's per-leaf arrays — byte-for-byte
    the ``resilience.integrity.kv_block_digest`` algorithm (dtype string
    then raw bytes, per leaf in tree order), computed host-side so one
    device pull serves both serialization and integrity."""
    h = hashlib.blake2b(digest_size=16)
    for arr in arrays:
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def snapshot_chain(
    engine: Any,
    prompt: List[int],
    *,
    max_pages: Optional[int] = None,
) -> Optional[Dict[str, Any]]:
    """Serialize the longest cached block chain covering ``prompt`` from
    ``engine``'s pool. Returns a transfer dict (see module docstring) or
    None when the engine has no prefix cache or no cached coverage.

    Safe from any thread: the chain's blocks are refcount-pinned via
    ``PrefixCache.acquire`` for the duration of the pull and released
    before returning, and only committed (published/shared) pages are
    ever read."""
    import jax

    cache = getattr(engine, "prefix_cache", None)
    if cache is None:
        return None
    cached_tokens, acquired = cache.acquire(prompt)
    if not acquired:
        return None
    try:
        blocks = acquired if max_pages is None else acquired[:max_pages]
        pools = engine.pools  # one read; see threading contract above
        leaves = jax.tree_util.tree_leaves(pools)
        bs = int(engine.block_size)
        layout: List[Dict[str, Any]] = []
        pages: List[Dict[str, Any]] = []
        for j, b in enumerate(blocks):
            arrays: List[np.ndarray] = []
            for leaf in leaves:
                page = leaf[:, b] if _block_axis(leaf) == 1 else leaf[b]
                arrays.append(np.ascontiguousarray(jax.device_get(page)))
            digest = _page_digest(arrays)
            expected = cache.checksum_of(b)
            if expected is not None and digest != expected:
                # The source page itself is corrupt: ship only the clean
                # prefix; the engine's own verify-on-acquire will deal
                # with the bad block on its next local hit.
                break
            if not layout:
                layout = [
                    {"dtype": str(a.dtype), "shape": list(a.shape)}
                    for a in arrays
                ]
            pages.append({
                "digest": digest,
                "leaves": [
                    base64.b64encode(a.tobytes()).decode("ascii")
                    for a in arrays
                ],
            })
    finally:
        cache.release_shared(acquired)
    if not pages:
        return None
    return {
        "v": XFER_VERSION,
        "block_size": bs,
        "tokens": [int(t) for t in prompt[: len(pages) * bs]],
        "layout": layout,
        "pages": pages,
    }


def transfer_bytes(xfer: Dict[str, Any]) -> int:
    """Decoded page-payload bytes of a transfer (the migrated-bytes
    accounting the fleet counters report)."""
    total = 0
    for page in xfer.get("pages", ()):
        for data in page["leaves"]:
            total += (len(data) * 3) // 4  # base64 -> raw, ignoring pad
    return total


def split_frames(
    xfer: Dict[str, Any], *, budget: int = KV_FRAME_BUDGET_BYTES
) -> List[Dict[str, Any]]:
    """Batch a transfer's pages into bounded frames. Frame 0 carries the
    header (tokens, layout, block size); every frame carries
    ``seq``/``n_frames`` so the receiver can detect a torn transfer.
    The caller adds routing fields (op, transfer id, fence stamp)."""
    if budget < 1:
        raise ValueError(f"frame budget must be >= 1, got {budget}")
    groups: List[List[Dict[str, Any]]] = []
    cur: List[Dict[str, Any]] = []
    cur_bytes = 0
    for page in xfer["pages"]:
        pb = sum((len(d) * 3) // 4 for d in page["leaves"])
        if cur and cur_bytes + pb > budget:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(page)
        cur_bytes += pb
    groups.append(cur)  # header frame exists even for an empty transfer
    frames: List[Dict[str, Any]] = []
    for i, pgs in enumerate(groups):
        frame: Dict[str, Any] = {
            "seq": i, "n_frames": len(groups), "pages": pgs,
        }
        if i == 0:
            frame["v"] = xfer["v"]
            frame["block_size"] = xfer["block_size"]
            frame["tokens"] = xfer["tokens"]
            frame["layout"] = xfer["layout"]
        frames.append(frame)
    return frames


def join_frames(frames: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Reassemble a transfer from its frames (any arrival order).
    Raises ``ValueError`` on a torn transfer: missing/duplicate seq,
    inconsistent ``n_frames``, or a missing header."""
    if not frames:
        raise ValueError("torn kv transfer: no frames")
    n = frames[0].get("n_frames")
    by_seq: Dict[int, Dict[str, Any]] = {}
    for f in frames:
        if f.get("n_frames") != n:
            raise ValueError(
                f"torn kv transfer: inconsistent n_frames "
                f"({f.get('n_frames')} vs {n})"
            )
        seq = f.get("seq")
        if not isinstance(seq, int) or seq < 0 or seq >= n:
            raise ValueError(f"torn kv transfer: bad seq {seq!r} of {n}")
        if seq in by_seq:
            raise ValueError(f"torn kv transfer: duplicate seq {seq}")
        by_seq[seq] = f
    if len(by_seq) != n:
        missing = sorted(set(range(n)) - set(by_seq))
        raise ValueError(f"torn kv transfer: missing frames {missing}")
    head = by_seq[0]
    for key in ("v", "block_size", "tokens", "layout"):
        if key not in head:
            raise ValueError(f"torn kv transfer: header missing {key!r}")
    pages: List[Dict[str, Any]] = []
    for i in range(n):
        pages.extend(by_seq[i]["pages"])
    return {
        "v": head["v"],
        "block_size": head["block_size"],
        "tokens": head["tokens"],
        "layout": head["layout"],
        "pages": pages,
    }


def corrupt_first_page(xfer: Dict[str, Any]) -> bool:
    """Fault-injection hook (``corrupt_kv_migration``): flip one byte in
    the first page's first leaf, leaving the transported digest claiming
    the ORIGINAL bytes — the receiver must detect the mismatch and drop
    the page. Returns False when the transfer has no pages to corrupt."""
    pages = xfer.get("pages") or []
    if not pages:
        return False
    raw = bytearray(base64.b64decode(pages[0]["leaves"][0]))
    if not raw:
        return False
    raw[0] ^= 0xFF
    pages[0]["leaves"][0] = base64.b64encode(bytes(raw)).decode("ascii")
    return True


def adopt_chain(engine: Any, xfer: Dict[str, Any]) -> Dict[str, Any]:
    """Insert a received transfer's pages into ``engine``'s pool behind
    the prefix-cache publish path. MUST run on the engine's loop thread
    (``EngineLoop.run_on_loop``) — this swaps ``engine.pools``.

    Every page's digest is verified against its TRANSPORTED bytes before
    anything touches the pool; the chain is adopted up to the first
    corrupt page and the remainder dropped (the re-prefill fallback:
    requests simply miss the cache for what was dropped, so corruption
    can cost latency but never a wrong token). Returns
    ``{"inserted", "rejected", "published", "reason"}``."""
    import jax

    n_pages = len(xfer.get("pages") or [])

    def _bump(adopted: int, dropped: int) -> None:
        stats = getattr(engine, "stats", None)
        if isinstance(stats, dict):
            stats["kv_pages_adopted"] = (
                stats.get("kv_pages_adopted", 0) + adopted
            )
            stats["kv_pages_rejected"] = (
                stats.get("kv_pages_rejected", 0) + dropped
            )

    def _reject_all(reason: str) -> Dict[str, Any]:
        _bump(0, n_pages)
        return {
            "inserted": 0, "rejected": n_pages,
            "published": 0, "reason": reason,
        }

    cache = getattr(engine, "prefix_cache", None)
    if cache is None:
        return _reject_all("no_prefix_cache")
    if n_pages == 0:
        return _reject_all("empty")
    if int(xfer.get("v", -1)) != XFER_VERSION:
        return _reject_all("version_mismatch")
    bs = int(engine.block_size)
    if int(xfer["block_size"]) != bs:
        return _reject_all("block_size_mismatch")
    tokens = [int(t) for t in xfer["tokens"]]
    if len(tokens) < n_pages * bs:
        return _reject_all("short_tokens")
    leaves = jax.tree_util.tree_leaves(engine.pools)
    layout = xfer["layout"]
    if len(layout) != len(leaves):
        return _reject_all("layout_mismatch")
    for spec, leaf in zip(layout, leaves):
        axis = _block_axis(leaf)
        shape = (
            (leaf.shape[0],) + tuple(leaf.shape[2:]) if axis == 1
            else tuple(leaf.shape[1:])
        )
        if (
            tuple(spec["shape"]) != shape
            or str(spec["dtype"]) != str(leaf.dtype)
        ):
            return _reject_all("layout_mismatch")

    # Decode + verify host-side BEFORE touching the pool: a corrupt page
    # truncates the adoptable chain (pages after it would be unreachable
    # index entries — their digests chain through the dropped block).
    decoded: List[List[np.ndarray]] = []
    rejected_reason = ""
    for page in xfer["pages"]:
        if len(page["leaves"]) != len(layout):
            rejected_reason = "layout_mismatch"
            break
        arrays: List[np.ndarray] = []
        ok = True
        for spec, data in zip(layout, page["leaves"]):
            dtype = _np_dtype(spec["dtype"])
            raw = base64.b64decode(data)
            count = int(np.prod(spec["shape"], dtype=np.int64))
            if len(raw) != count * dtype.itemsize:
                ok = False
                break
            arrays.append(
                np.frombuffer(raw, dtype=dtype).reshape(spec["shape"])
            )
        if not ok or _page_digest(arrays) != page["digest"]:
            rejected_reason = rejected_reason or "checksum_mismatch"
            break
        decoded.append(arrays)
    k = len(decoded)
    if k == 0:
        return _reject_all(rejected_reason or "checksum_mismatch")

    blocks = engine.reserve_migration_blocks(k)
    if blocks is None:
        _bump(0, n_pages)
        return {
            "inserted": 0, "rejected": n_pages,
            "published": 0, "reason": "capacity",
        }
    # Scatter accepted pages into the reserved blocks, one functional
    # update per leaf (pool arrays are immutable; this is the write that
    # pins adopt_chain to the loop thread).
    pool_leaves, treedef = jax.tree_util.tree_flatten(engine.pools)
    for j, leaf in enumerate(pool_leaves):
        axis = _block_axis(leaf)
        for i, b in enumerate(blocks):
            idx = (slice(None), b) if axis == 1 else (b,)
            pool_leaves[j] = pool_leaves[j].at[idx].set(
                decoded[i][j].astype(leaf.dtype)
            )
    engine.pools = jax.tree_util.tree_unflatten(treedef, pool_leaves)

    # Publish behind the normal path: n_shared=0, publish_len = the full
    # adopted span, so release_row indexes every block (duplicates of
    # chains this engine already holds go straight back to the
    # allocator — first writer wins) and returns the newly published
    # ids, which get the transported digest as their acquire-side
    # checksum exactly like a locally computed publish would.
    published = cache.release_row(tokens[: k * bs], blocks, 0, k * bs)
    digest_by_block = {
        b: page["digest"] for b, page in zip(blocks, xfer["pages"])
    }
    for b in published:
        cache.set_checksum(b, digest_by_block[b])
    _bump(k, n_pages - k)
    return {
        "inserted": k,
        "rejected": n_pages - k,
        "published": len(published),
        "reason": rejected_reason,
    }
