"""SLO load generator for the serving frontend.

Two classic shapes:

  open-loop    arrivals follow a seeded Poisson process at ``rate_rps``,
               independent of the system's progress — the honest way to
               measure latency under load, because a slow server cannot
               slow the arrival process down (no coordinated omission);
  closed-loop  ``concurrency`` workers each keep exactly one request in
               flight, submitting the next the moment the previous one
               terminates — measures best-case pipeline throughput.

The whole workload is materialised up front by ``build_schedule`` from
``LoadSpec.seed`` (arrival offsets, prompt ids, lengths, token budgets),
so a given spec is ONE reproducible workload: same seed -> byte-identical
schedule, regardless of wall-clock, host, or which client runs it.

Clients: ``run_engine_loop`` drives an in-process EngineLoop (bench.py's
serving-SLO mode); ``run_http`` drives a live gateway over HTTP with
stdlib urllib (no deps). Both produce a ``LoadReport`` with
TTFT/TPOT/e2e percentiles and goodput-under-SLO — completed requests
that met BOTH SLO bounds, per second of wall time; a server that answers
fast but late earns nothing.
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from pretraining_llm_tpu.frontend.admission import (
    RejectedBusy,
    RejectedInfeasible,
)


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One reproducible workload. ``vocab_size`` bounds the sampled token
    ids; prompt lengths and token budgets are uniform over the inclusive
    ranges. ``rate_rps`` is used in open-loop mode, ``concurrency`` in
    closed-loop. SLO bounds of 0 disable that bound."""

    n_requests: int = 32
    mode: str = "open"  # "open" | "closed"
    rate_rps: float = 8.0
    concurrency: int = 4
    vocab_size: int = 256
    prompt_len_min: int = 4
    prompt_len_max: int = 12
    max_new_min: int = 4
    max_new_max: int = 16
    deadline_s: Optional[float] = None
    slo_ttft_s: float = 0.0
    slo_e2e_s: float = 0.0
    seed: int = 0
    # Hot-prefix scenario (prefix-cache workloads): when
    # ``prefix_pool_size`` > 0, a pool of that many shared prefixes (each
    # ``prefix_len`` tokens, seeded like everything else) is materialised
    # and every request PREPENDS one, drawn zipf(s=``prefix_zipf``) over
    # pool rank — rank-1 is the hottest "system prompt", the tail is
    # cold. 0 (the default) leaves schedules byte-identical to specs
    # that predate these fields.
    prefix_pool_size: int = 0
    prefix_len: int = 0
    prefix_zipf: float = 1.0
    # HTTP client only: send a seeded W3C ``traceparent`` header per
    # request (sampled flag set), so the gateway joins trace ids the
    # workload chose — outcomes then correlate with the server's trace
    # export byte-for-byte. The in-process client instead reads back the
    # ids the loop's tracer minted.
    send_traceparent: bool = False
    # Fleet/brownout scenario: fraction of requests marked high priority
    # (``priority_hi``; the rest stay 0). Brownout shedding drops
    # low-priority work first, so a mixed-priority workload shows the
    # policy's selectivity. 0 (the default) consumes no rng — schedules
    # stay byte-identical to specs that predate this field.
    priority_hi_frac: float = 0.0
    priority_hi: int = 1

    def __post_init__(self) -> None:
        if self.mode not in ("open", "closed"):
            raise ValueError(f"mode must be 'open' or 'closed', got {self.mode!r}")
        if self.n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got {self.n_requests}")
        if self.mode == "open" and self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.mode == "closed" and self.concurrency < 1:
            raise ValueError(f"concurrency must be >= 1, got {self.concurrency}")
        if not 1 <= self.prompt_len_min <= self.prompt_len_max:
            raise ValueError(
                f"bad prompt length range "
                f"[{self.prompt_len_min}, {self.prompt_len_max}]"
            )
        if not 1 <= self.max_new_min <= self.max_new_max:
            raise ValueError(
                f"bad max_new range [{self.max_new_min}, {self.max_new_max}]"
            )
        if self.prefix_pool_size < 0:
            raise ValueError(
                f"prefix_pool_size must be >= 0, got {self.prefix_pool_size}"
            )
        if self.prefix_pool_size > 0 and self.prefix_len < 1:
            raise ValueError(
                f"prefix_len must be >= 1 with a prefix pool, got "
                f"{self.prefix_len}"
            )
        if self.prefix_zipf < 0:
            raise ValueError(
                f"prefix_zipf must be >= 0, got {self.prefix_zipf}"
            )
        if not 0.0 <= self.priority_hi_frac <= 1.0:
            raise ValueError(
                f"priority_hi_frac must be in [0, 1], got "
                f"{self.priority_hi_frac}"
            )


@dataclasses.dataclass(frozen=True)
class ScheduledRequest:
    index: int
    arrival_s: float  # offset from workload start; 0.0 in closed-loop
    prompt: List[int]
    max_new: int
    priority: int = 0


def build_schedule(spec: LoadSpec) -> List[ScheduledRequest]:
    """Materialise the workload. Pure function of ``spec`` (seeded PRNG,
    no wall clock): call it twice, get the same schedule."""
    rng = random.Random(spec.seed)
    # Shared-prefix pool + zipf-over-rank weights, materialised before
    # the request loop so the rng is consumed ONLY when the scenario is
    # on: pool-off schedules stay byte-identical to pre-pool specs.
    pool: List[List[int]] = []
    weights: List[float] = []
    if spec.prefix_pool_size > 0:
        pool = [
            [rng.randrange(spec.vocab_size) for _ in range(spec.prefix_len)]
            for _ in range(spec.prefix_pool_size)
        ]
        weights = [
            1.0 / (rank ** spec.prefix_zipf)
            for rank in range(1, spec.prefix_pool_size + 1)
        ]
    out: List[ScheduledRequest] = []
    t = 0.0
    for i in range(spec.n_requests):
        if spec.mode == "open":
            t += rng.expovariate(spec.rate_rps)
        n_prompt = rng.randint(spec.prompt_len_min, spec.prompt_len_max)
        prompt = [rng.randrange(spec.vocab_size) for _ in range(n_prompt)]
        if pool:
            prompt = pool[rng.choices(range(len(pool)), weights)[0]] + prompt
        max_new = rng.randint(spec.max_new_min, spec.max_new_max)
        priority = 0
        if spec.priority_hi_frac > 0:  # rng consumed only when the scenario is on
            if rng.random() < spec.priority_hi_frac:
                priority = spec.priority_hi
        out.append(
            ScheduledRequest(
                index=i,
                arrival_s=t if spec.mode == "open" else 0.0,
                prompt=prompt,
                max_new=max_new,
                priority=priority,
            )
        )
    return out


@dataclasses.dataclass
class RequestOutcome:
    index: int
    status: str  # done | cancelled | expired | error | rejected_busy | rejected_infeasible
    n_tokens: int = 0
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    e2e_s: Optional[float] = None
    trace_id: Optional[str] = None
    # Prompt tokens the engine served from the prefix cache (0 with the
    # cache off; accumulates across preemption re-admissions).
    cached_tokens: int = 0
    # Fleet client: how many times the router failed this request over to
    # another replica before it finished (0 on a single loop).
    redrives: int = 0


def traceparent_for(spec: LoadSpec, index: int) -> str:
    """Deterministic per-request W3C traceparent (sampled): same spec ->
    same trace ids, so a rerun's trace export is join-comparable."""
    rng = random.Random((spec.seed << 20) ^ index)
    trace_id = f"{rng.getrandbits(128) or 1:032x}"
    span_id = f"{rng.getrandbits(64) or 1:016x}"
    return f"00-{trace_id}-{span_id}-01"


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank on a pre-sorted list; q in [0, 1]."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


@dataclasses.dataclass
class LoadReport:
    spec: LoadSpec
    wall_s: float
    outcomes: List[RequestOutcome]

    def counts(self) -> Dict[str, int]:
        c: Dict[str, int] = {}
        for o in self.outcomes:
            c[o.status] = c.get(o.status, 0) + 1
        return c

    def percentiles(self, field: str) -> Dict[str, float]:
        vals = sorted(
            v for o in self.outcomes
            if (v := getattr(o, field)) is not None
        )
        return {
            "p50": _percentile(vals, 0.50),
            "p90": _percentile(vals, 0.90),
            "p99": _percentile(vals, 0.99),
        }

    def met_slo(self, o: RequestOutcome) -> bool:
        if o.status != "done":
            return False
        if self.spec.slo_ttft_s > 0 and (
            o.ttft_s is None or o.ttft_s > self.spec.slo_ttft_s
        ):
            return False
        if self.spec.slo_e2e_s > 0 and (
            o.e2e_s is None or o.e2e_s > self.spec.slo_e2e_s
        ):
            return False
        return True

    def summary(self) -> Dict[str, Any]:
        n_ok = sum(1 for o in self.outcomes if self.met_slo(o))
        n_done = sum(1 for o in self.outcomes if o.status == "done")
        tokens = sum(o.n_tokens for o in self.outcomes)
        wall = max(self.wall_s, 1e-9)
        return {
            "n_requests": len(self.outcomes),
            "counts": self.counts(),
            "wall_s": self.wall_s,
            "throughput_rps": n_done / wall,
            "throughput_tok_s": tokens / wall,
            "goodput_rps": n_ok / wall,
            "slo_attainment": (n_ok / len(self.outcomes)) if self.outcomes else 0.0,
            "cached_tokens_total": sum(o.cached_tokens for o in self.outcomes),
            "redrives_total": sum(o.redrives for o in self.outcomes),
            "ttft": self.percentiles("ttft_s"),
            "tpot": self.percentiles("tpot_s"),
            "e2e": self.percentiles("e2e_s"),
        }


# -- clients ---------------------------------------------------------------

# A client callable takes one ScheduledRequest and returns its outcome;
# _execute handles arrival pacing and the two loop shapes around it.
_Client = Callable[[ScheduledRequest], RequestOutcome]


def _execute(spec: LoadSpec, client: _Client) -> LoadReport:
    schedule = build_schedule(spec)
    outcomes: List[Optional[RequestOutcome]] = [None] * len(schedule)
    start = time.monotonic()

    if spec.mode == "open":
        def run_one(sr: ScheduledRequest) -> None:
            delay = start + sr.arrival_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            outcomes[sr.index] = client(sr)

        threads = [
            threading.Thread(target=run_one, args=(sr,), daemon=True)
            for sr in schedule
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    else:
        it = iter(schedule)
        it_lock = threading.Lock()

        def worker() -> None:
            while True:
                with it_lock:
                    sr = next(it, None)
                if sr is None:
                    return
                outcomes[sr.index] = client(sr)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(min(spec.concurrency, len(schedule)))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

    wall = time.monotonic() - start
    done = [o for o in outcomes if o is not None]
    return LoadReport(spec=spec, wall_s=wall, outcomes=done)


def run_engine_loop(loop: Any, spec: LoadSpec) -> LoadReport:
    """Drive an in-process EngineLoop (already started)."""

    def client(sr: ScheduledRequest) -> RequestOutcome:
        t0 = time.monotonic()
        try:
            req = loop.submit(
                sr.prompt, sr.max_new, deadline_s=spec.deadline_s,
                priority=sr.priority,
            )
        except RejectedBusy:
            return RequestOutcome(sr.index, "rejected_busy")
        except RejectedInfeasible:
            return RequestOutcome(sr.index, "rejected_infeasible")
        except (ValueError, RuntimeError):
            return RequestOutcome(sr.index, "error")
        status, tokens, info = req.result()
        # Client-side clock for TTFT/e2e (what a caller experiences);
        # engine-side marks live in info if finer attribution is needed.
        return RequestOutcome(
            sr.index,
            status,
            n_tokens=len(tokens),
            ttft_s=info.get("ttft_s"),
            tpot_s=info.get("tpot_s"),
            e2e_s=info.get("e2e_s", time.monotonic() - t0),
            trace_id=info.get("trace_id"),
            cached_tokens=int(info.get("cached_tokens", 0)),
            redrives=int(info.get("redrives", 0)),
        )

    return _execute(spec, client)


def run_http(base_url: str, spec: LoadSpec, timeout_s: float = 120.0) -> LoadReport:
    """Drive a live gateway over HTTP (non-streaming POSTs, stdlib only)."""
    url = base_url.rstrip("/") + "/v1/generate"

    def client(sr: ScheduledRequest) -> RequestOutcome:
        payload: Dict[str, Any] = {
            "prompt": sr.prompt,
            "max_new_tokens": sr.max_new,
        }
        if spec.deadline_s is not None:
            payload["deadline_s"] = spec.deadline_s
        if sr.priority:
            payload["priority"] = sr.priority
        data = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        trace_id = None
        if spec.send_traceparent:
            tp = traceparent_for(spec, sr.index)
            headers["traceparent"] = tp
            trace_id = tp.split("-")[1]
        t0 = time.monotonic()
        try:
            http_req = urllib.request.Request(url, data=data, headers=headers)
            with urllib.request.urlopen(http_req, timeout=timeout_s) as resp:
                body = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 429:
                return RequestOutcome(sr.index, "rejected_busy")
            try:
                body = json.loads(e.read().decode())
            except (ValueError, OSError):
                body = {}
            status = body.get(
                "status", {504: "expired", 499: "cancelled"}.get(e.code, "error")
            )
            if e.code == 504 and "tokens" not in body:
                status = "rejected_infeasible"
            return RequestOutcome(
                sr.index,
                status,
                n_tokens=body.get("n_tokens", 0),
                ttft_s=body.get("ttft_s"),
                tpot_s=body.get("tpot_s"),
                e2e_s=body.get("e2e_s"),
                trace_id=body.get("trace_id", trace_id),
            )
        except (urllib.error.URLError, OSError, ValueError):
            return RequestOutcome(sr.index, "error", trace_id=trace_id)
        return RequestOutcome(
            sr.index,
            body.get("status", "done"),
            n_tokens=body.get("n_tokens", len(body.get("tokens", []))),
            ttft_s=body.get("ttft_s"),
            tpot_s=body.get("tpot_s"),
            e2e_s=body.get("e2e_s", time.monotonic() - t0),
            trace_id=body.get("trace_id", trace_id),
            cached_tokens=int(body.get("cached_tokens", 0)),
            redrives=int(body.get("redrives", 0)),
        )

    return _execute(spec, client)


# -- fleet choreography ----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetAction:
    """One timed operation against a fleet Router while load is running:

      kill     shadow the replica's live engine tick to raise (the loop
               thread dies mid-decode; the router's health loop ejects the
               replica and redrives its in-flight requests) — the
               wall-clock analogue of the injector's ``replica_crash@req_n``;
      drain    administrative drain: redrive in-flight work to survivors,
               stop the loop, hold the replica not-ready;
      restore  relaunch a drained/ejected replica with a fresh engine;
      upgrade  probe-vetted weight upgrade: drain, apply ``update`` to the
               replica's spec/factory, relaunch HELD, run golden probes,
               and only then take traffic (Router.upgrade_replica). The
               mid-upgrade-kill drill rides this action: an ``update``
               carrying ``kill_after_submits: 1`` makes the new worker die
               on its first vetting probe, which must roll the old weights
               back without clients ever seeing the unvetted checkpoint;
      partition  blackhole the replica's worker connection (process mode):
               reads hang and writes buffer — no RST, no EOF. Detection
               is the lease/fence machinery, never the socket;
      heal     flush the partitioned connection's buffered writes and
               release its read backlog — the stale-generation frame
               flood the router's fence filter must count and drop.
    """

    at_s: float
    kind: str  # "kill" | "drain" | "restore" | "upgrade" | "partition" | "heal"
    replica: int
    # Spec/factory delta applied before the upgrade relaunch (upgrade
    # only). None means "relaunch with the current spec" — still vetted.
    update: Optional[Dict[str, Any]] = None

    def __post_init__(self) -> None:
        if self.kind not in (
            "kill", "drain", "restore", "upgrade", "partition", "heal"
        ):
            raise ValueError(f"unknown fleet action kind {self.kind!r}")
        if self.at_s < 0:
            raise ValueError(f"at_s must be >= 0, got {self.at_s}")
        if self.update is not None and self.kind != "upgrade":
            raise ValueError(
                f"update only applies to upgrade actions, got {self.kind!r}"
            )


def rolling_restart_plan(
    n_replicas: int, *, start_s: float, step_s: float
) -> List[FleetAction]:
    """Drain replica i at ``start_s + i*step_s``, restore it one step
    later — at most one replica down at a time once ``step_s`` exceeds a
    drain's duration (the standard rolling-restart invariant)."""
    out: List[FleetAction] = []
    for i in range(n_replicas):
        t = start_s + i * step_s
        out.append(FleetAction(at_s=t, kind="drain", replica=i))
        out.append(FleetAction(at_s=t + step_s, kind="restore", replica=i))
    return out


def run_fleet_plan(router: Any, actions: List[FleetAction]) -> threading.Thread:
    """Execute a fleet plan against ``router`` on a daemon thread (offsets
    are from the call, so start it when the load run starts). Returns the
    thread; join it after the load run to be sure every action fired."""
    from pretraining_llm_tpu.resilience.faults import InjectedFault

    plan = sorted(actions, key=lambda a: a.at_s)
    start = time.monotonic()

    def _kill(replica: int) -> None:
        rep = router.replicas[replica]
        # Out-of-process replica: the honest kill is SIGKILL to the worker
        # itself — the parent sees the socket die, exactly like a real
        # process death.
        proc = getattr(rep, "proc", None)
        if proc is not None:
            proc.kill()
            return
        eng = rep.engine
        if eng is None:
            return

        def _boom(*a: Any, **k: Any) -> None:
            raise InjectedFault(f"fleet plan killed replica {replica}")

        # Same instance-attribute shadowing as ServingFaultInjector.wrap_tick;
        # the loop thread dies on its next scheduler turn.
        eng.pipeline_tick = _boom

    def _run() -> None:
        for act in plan:
            delay = start + act.at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                if act.kind == "kill":
                    _kill(act.replica)
                elif act.kind == "drain":
                    router.drain(act.replica)
                elif act.kind == "upgrade":
                    router.upgrade_replica(act.replica, act.update)
                elif act.kind in ("partition", "heal"):
                    # Process-mode replicas only (RemoteReplica.partition/
                    # heal); in-process replicas have no wire to cut.
                    fn = getattr(router.replicas[act.replica], act.kind, None)
                    if fn is not None:
                        fn()
                else:
                    router.restore(act.replica)
            except Exception:
                # The plan is chaos against live infrastructure; a replica
                # already down when its action fires is not a plan failure.
                pass

    th = threading.Thread(target=_run, name="fleet-plan", daemon=True)
    th.start()
    return th
