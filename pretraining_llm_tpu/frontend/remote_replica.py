"""RemoteReplica: the parent-side client for an out-of-process worker.

Duck-types the :class:`frontend.replica.Replica` surface the router
consumes — ``state``/``generation``/``submits``/``accepting``/``alive``/
``load()``/``submit()``/``drain()``/``eject()``/``relaunch()``/
``stop()``/``on_state``/``registry``/``engine``/``loop`` — so
``Router``, the integrity sentinel, and the gateway run UNCHANGED
whether a replica is an object in this process or a worker process on
the other end of a socket (``--replica_mode process``).

The key trick is that submitted attempts are real
:class:`frontend.engine_loop.FrontendRequest` objects: the reader
thread feeds ``tokens``/``out_q`` exactly the way EngineLoop does, so
the router's ``_pump``/abandonment/result machinery needs no remote
special case.

Fault domain (the robustness core of this tier):

- every RPC has a per-call timeout; idempotent ops (health, metrics,
  debug, drain, cancel) retry with seeded exponential backoff +
  jitter; ``submit`` is never retried (an accepted-but-unacked submit
  must surface as a failure, not a silent duplicate).
- a send failure, reader EOF, or final RPC timeout declares the
  connection lost: the replica stops reporting ``running``, every
  live attempt gets an ``"engine failure: worker connection lost"``
  error terminal (the redrivable prefix — the router immediately
  redrives them bit-identically onto survivors), and the router's
  health loop ejects + backs off + relaunches exactly as for an
  in-process engine crash.
- ``relaunch`` always tears the previous process down (graceful
  ``shutdown`` RPC, then SIGKILL) before spawning — a crash-looping
  worker can never accumulate orphans; the worker's own stdin-EOF
  watcher covers the reverse direction (dead parent).

Multi-host extensions (``spec["attach"] = "host:port"``):

- **attach mode** connects to a pre-spawned ``worker.py --listen``
  instead of spawning; the hello carries ``spec["token"]`` plus the
  router's fence generation and lease term, and teardown only closes
  our end — the worker survives to serve the next attach (including a
  restarted router recovering from its journal).
- **leases**: with ``lease_s > 0`` the health poll becomes the
  heartbeat. A poll window with no successful RPC for a full lease
  term declares the lease expired: live attempts fail with the
  redrivable ``engine failure`` prefix WITHOUT closing the socket —
  the connection must survive so that when a partition heals, the
  backlog the worker streamed into the void is still readable (and
  countable) rather than destroyed with the fd.
- **fencing**: ``fence`` is this replica's generation; the router
  bumps it on eject. Every inbound frame stamped with an older
  generation is dropped and counted (``fenced_frames_total``) — a
  healed partition can never stream duplicate tokens into a request
  a survivor already answered.
- **partition injection**: every connection is wrapped in a
  ``_PartitionGate`` so drills can blackhole it (reads hang, writes
  buffer — no RST, unlike ``conn_drop``) and add wire delay/jitter;
  ``heal()`` flushes buffered writes and releases the read backlog.

The worker spec (see ``frontend/worker.py``) is stored on the replica;
``update_snapshot()``/``apply_update({...})`` snapshot and mutate it,
which is how ``Router.upgrade_replica`` swaps a checkpoint path and —
on a failed probe vetting — restores the old one.
"""

from __future__ import annotations

import json
import os
import queue
import random
import select
import socket
import subprocess
import sys
import threading
import time
from types import SimpleNamespace
from typing import Any, Dict, List, Optional

import numpy as np

from ..observability import spans as _spans
from ..observability.clocksync import ClockSync
from ..observability.metrics import MetricsRegistry
from . import kv_transfer
from .admission import RejectedBusy
from .engine_loop import _TRACE_UNSET, FrontendRequest
from .replica import REPLICA_STATES, ReplicaUnavailable
from .wire import PROTO_VERSION, ConnectionLost, recv_frame, send_frame

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Transport latency buckets: LAN-ish RPCs, 1ms..5s.
_RPC_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 5.0)

# One-way delay applied by the "wire_delay" injected fault.
_WIRE_DELAY_S = 0.05


class _PartitionGate:
    """Socket wrapper that can simulate a network PARTITION, distinctly
    from ``conn_drop``: a blackholed route produces no RST and no EOF —
    reads simply hang and writes vanish into a buffer that never
    drains. The gate reproduces exactly that: while partitioned,
    ``recv`` ignores readable bytes (they stay queued in the kernel)
    and ``send``/``sendall`` divert into ``_wbuf``. ``heal()`` flushes
    the buffered writes and lets the read backlog through — the
    stale-frame flood that fencing exists to absorb. ``set_delay``
    models a slow WAN link (per-recv sleep with jitter). Transparent
    passthrough when no fault is active.

    ``recv`` polls via select rather than blocking in the kernel so a
    partition injected while the reader is mid-``recv`` takes effect
    within one poll tick, and ``close()`` always wakes it.
    """

    def __init__(self, sock: socket.socket, rng: Any = None) -> None:
        self._sock = sock
        self._partitioned = False
        self._closed = False
        self._wbuf = bytearray()
        self._wlock = threading.Lock()
        self._delay_s = 0.0
        self._jitter_frac = 0.0
        self._rng = rng if rng is not None else random.Random(0)

    # -- fault controls ----------------------------------------------

    def partition(self) -> None:
        with self._wlock:
            self._partitioned = True

    def heal(self) -> None:
        # Flush INSIDE the lock: a concurrent send observing
        # partitioned=False must not interleave its bytes with the
        # buffered backlog (a torn frame would kill the connection).
        with self._wlock:
            buf, self._wbuf = bytes(self._wbuf), bytearray()
            self._partitioned = False
            if buf and not self._closed:
                try:
                    self._sock.sendall(buf)
                except OSError:
                    pass  # peer gave up during the partition; reads will EOF

    def set_delay(self, delay_s: float, jitter_frac: float = 0.0) -> None:
        self._delay_s = max(0.0, float(delay_s))
        self._jitter_frac = max(0.0, float(jitter_frac))

    # -- socket surface ----------------------------------------------

    def recv(self, n: int) -> bytes:
        while True:
            if self._closed:
                raise OSError("socket closed")
            if self._partitioned:
                time.sleep(0.02)
                continue
            try:
                r, _, _ = select.select([self._sock], [], [], 0.05)
            except (OSError, ValueError):
                raise OSError("socket closed")
            if not r or self._partitioned:
                continue
            if self._delay_s > 0.0:
                time.sleep(
                    self._delay_s
                    * (1.0 + self._jitter_frac * self._rng.random())
                )
            return self._sock.recv(n)

    def send(self, data: bytes, flags: int = 0) -> int:
        with self._wlock:
            if self._partitioned:
                self._wbuf.extend(data)
                return len(data)
            return self._sock.send(data, flags)

    def sendall(self, data: bytes) -> None:
        with self._wlock:
            if self._partitioned:
                self._wbuf.extend(data)
                return
            self._sock.sendall(data)

    def fileno(self) -> int:
        return self._sock.fileno()

    def setsockopt(self, *args: Any) -> None:
        self._sock.setsockopt(*args)

    def settimeout(self, t: Optional[float]) -> None:
        self._sock.settimeout(t)

    def shutdown(self, how: int) -> None:
        self._sock.shutdown(how)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class _RemoteEngine:
    """Engine facade built from the worker's hello constants. Exposes
    exactly what the router needs from ``rep.engine``: submit-time
    validation (mirroring ``ServingEngine.validate_request`` so process
    mode returns the same HTTP 400s), the probe-geometry constants, and
    ``build_probe_set`` delegating to the worker (which holds the
    params this process never sees)."""

    def __init__(self, rep: "RemoteReplica", hello: Dict[str, Any]) -> None:
        self._rep = rep
        self.temperature = float(hello["temperature"])
        self.block_size = int(hello["block_size"])
        self.max_seq = int(hello["max_seq"])
        self.max_batch = int(hello["max_batch"])
        self.n_blocks = int(hello["n_blocks"])
        self.cfg = SimpleNamespace(
            vocab_size=int(hello["vocab_size"]),
            context_length=int(hello["context_length"]),
        )
        self.params = None        # weights live in the worker
        self.prefix_cache = None  # router's cached-token peek: no local view

    def validate_request(self, prompt_ids: Any, max_new_tokens: Any) -> int:
        from ..generation import paged

        try:
            max_new = int(max_new_tokens)
        except (TypeError, ValueError):
            raise ValueError(
                f"max_new_tokens must be an integer, got "
                f"{type(max_new_tokens).__name__}"
            )
        if max_new != max_new_tokens:
            raise ValueError(
                f"max_new_tokens must be an integer, got {max_new_tokens!r}"
            )
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt")
        ids = np.asarray(prompt_ids)
        if ids.ndim != 1:
            raise ValueError(
                f"prompt must be a flat list of token ids, got an array of "
                f"shape {ids.shape}"
            )
        if ids.dtype.kind not in "iu":
            raise ValueError(
                f"prompt must be integer token ids, got dtype {ids.dtype}"
            )
        lo, hi = int(ids.min()), int(ids.max())
        if lo < 0 or hi >= self.cfg.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, {self.cfg.vocab_size}); "
                f"got range [{lo}, {hi}]"
            )
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        total = p + max_new
        if total > self.max_seq:
            raise ValueError(
                f"prompt({p}) + max_new({max_new}) = {total} exceeds "
                f"max_seq={self.max_seq}"
            )
        if paged.required_blocks(total, self.block_size) > self.n_blocks - 1:
            raise ValueError(
                f"request needs "
                f"{paged.required_blocks(total, self.block_size)} "
                f"blocks; the pool only has {self.n_blocks - 1}"
            )
        return max_new

    def build_probe_set(
        self, *, n_probes: int = 2, probe_len: int = 9, max_new: int = 4
    ) -> List[Any]:
        from ..resilience.integrity import GoldenProbe

        raw = self._rep._rpc(
            "probe_set",
            {"n_probes": n_probes, "probe_len": probe_len, "max_new": max_new},
            timeout=self._rep.spawn_timeout_s,
        )
        return [
            GoldenProbe(
                prompt=tuple(int(t) for t in d["prompt"]),
                expected=tuple(int(t) for t in d["expected"]),
            )
            for d in raw
        ]


class _RemoteLoop:
    """EngineLoop facade over the health snapshot + RPCs. Identity is
    stable across worker relaunches (mirroring how the router treats
    ``rep.loop`` as replaced-on-relaunch is unnecessary: the router
    only reads liveness properties and calls submit/cancel, all of
    which route to whatever connection is current)."""

    def __init__(self, rep: "RemoteReplica") -> None:
        self._rep = rep

    # -- liveness mirror ---------------------------------------------

    @property
    def running(self) -> bool:
        return self._rep._connected() and bool(
            self._rep._snapshot.get("running", False)
        )

    @property
    def draining(self) -> bool:
        return bool(self._rep._snapshot.get("draining", False))

    @property
    def active_requests(self) -> int:
        return max(
            len(self._rep._attempts),
            int(self._rep._snapshot.get("active_requests", 0)),
        )

    @property
    def failure(self) -> Optional[str]:
        return self._rep._snapshot.get("failure")

    @property
    def weight_fingerprint0(self) -> Optional[str]:
        return self._rep._snapshot.get("weight_fingerprint0")

    @property
    def weight_fingerprint(self) -> Optional[str]:
        return self._rep._snapshot.get("weight_fingerprint")

    def last_turn_age_s(self) -> float:
        snap = self._rep._snapshot
        age = float(snap.get("last_turn_age_s", 0.0))
        taken = snap.get("t")
        if taken is not None:
            age += max(0.0, self._rep._clock() - taken)
        return age

    # -- request path ------------------------------------------------

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
        trace: Any = _TRACE_UNSET,
        traceparent: Optional[str] = None,
        priority: int = 0,
    ) -> FrontendRequest:
        if not self.running:
            raise RuntimeError("EngineLoop is not running")
        return self._rep._wire_submit(
            prompt,
            max_new_tokens,
            deadline_s=deadline_s,
            priority=priority,
            lane="loop",
            trace=trace,
            traceparent=traceparent,
        )

    def cancel(self, req: FrontendRequest) -> None:
        try:
            self._rep._rpc("cancel", {"rid": req.rid}, retries=0)
        except Exception:
            pass  # a dead worker has already cancelled everything

    def begin_drain(self) -> None:
        self._rep._snapshot["draining"] = True
        try:
            self._rep._rpc("drain")
        except Exception:
            pass

    # -- observability passthrough -----------------------------------

    def metrics(self) -> Dict[str, Any]:
        try:
            return dict(self._rep._rpc("metrics"))
        except Exception:
            return {}

    def debug_requests(self) -> List[Dict[str, Any]]:
        try:
            return list(self._rep._rpc("debug_requests"))
        except Exception:
            return []

    def debug_engine(self) -> Dict[str, Any]:
        try:
            return dict(self._rep._rpc("debug_engine"))
        except Exception:
            return {}

    def readiness(self) -> Dict[str, Any]:
        return {
            "ready": self.running and not self.draining,
            "running": self.running,
            "draining": self.draining,
        }


class RemoteReplica:
    """One worker process + socket, presented as a Replica."""

    def __init__(
        self,
        index: int,
        spec: Dict[str, Any],
        *,
        bus: Any = None,
        registry_prefix: str = "pllm_serving_",
        registry_labels: Optional[Dict[str, Any]] = None,
        fault_injector: Any = None,
        clock: Any = time.monotonic,
        rpc_timeout_s: float = 30.0,
        rpc_retries: int = 2,
        backoff_base_s: float = 0.05,
        backoff_jitter_frac: float = 0.25,
        backoff_seed: int = 0,
        spawn_timeout_s: float = 600.0,
        health_interval_s: float = 0.05,
        lease_s: float = 0.0,
        recorder: Any = None,
        python: str = sys.executable,
    ) -> None:
        self.index = int(index)
        self.spec = dict(spec)
        self._bus = bus
        self.faults = fault_injector
        self._clock = clock
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.rpc_retries = int(rpc_retries)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_jitter_frac = float(backoff_jitter_frac)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.health_interval_s = float(health_interval_s)
        self.lease_s = float(lease_s)
        self._python = python
        self.attach = str(self.spec.get("attach") or "")
        self.mode = "attach" if self.attach else "process"
        # Disaggregation role. The spec is the request; the hello reply
        # is the truth (an attach-mode worker was launched with its own
        # --role and may disagree with a stale router config).
        self.role = str(self.spec.get("role") or "both")

        self.registry = MetricsRegistry(
            registry_prefix,
            const_labels={**(registry_labels or {}), "replica": self.index},
        )
        self._c_spawns = self.registry.counter(
            "worker_spawns_total", "worker processes launched"
        )
        self._c_retries = self.registry.counter(
            "worker_rpc_retries_total", "worker RPCs retried after timeout"
        )
        self._c_timeouts = self.registry.counter(
            "worker_rpc_timeouts_total", "worker RPC attempts that timed out"
        )
        self._h_rpc = self.registry.histogram(
            "worker_rpc_latency_seconds",
            "round-trip latency of worker RPC replies",
            buckets=_RPC_BUCKETS,
        )
        self._c_lease = self.registry.counter(
            "lease_expiries_total",
            "worker leases the router declared expired (no contact)",
        )
        self._c_fenced = self.registry.counter(
            "fenced_frames_total",
            "stale-generation frames dropped after a fence bump",
        )
        self._c_spans = self.registry.counter(
            "worker_spans_total",
            "spans imported from the worker's span-export frames",
        )
        self._c_span_drops = self.registry.counter(
            "worker_span_drops_total",
            "spans the worker dropped before export (buffer saturated)",
        )
        self._g_clock_offset = self.registry.gauge(
            "clock_offset_seconds",
            "estimated worker->router perf_counter offset (min-RTT)",
        )
        self._g_clock_err = self.registry.gauge(
            "clock_error_bound_seconds",
            "half-RTT error bound on the current clock offset estimate",
        )

        self.state = "ejected"
        self.generation = 0
        self.submits = 0
        self.on_state: Any = None
        self._lock = threading.Lock()

        # Connection plumbing. _conn_gen increments per successful
        # connect; _on_conn_lost is idempotent per generation.
        self._conn_lock = threading.Lock()
        self._wlock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._proc: Optional[subprocess.Popen] = None
        self._conn_gen = 0
        self._rpc_seq = 0
        self._pending: Dict[int, "queue.Queue"] = {}
        self._pending_lock = threading.Lock()
        self._attempts: Dict[int, FrontendRequest] = {}
        self._attempts_lock = threading.Lock()
        # KV-fetch collectors: fetch rid -> list of kv_page frames. The
        # reader thread is single-threaded and the worker streams every
        # page frame BEFORE the summary reply, so when the fetch RPC
        # returns the collector is complete by construction.
        self._kv_rx: Dict[int, List[Dict[str, Any]]] = {}
        self._kv_rx_lock = threading.Lock()
        self._snapshot: Dict[str, Any] = {"running": False}
        self._rng = random.Random(backoff_seed * 1000003 + self.index)
        self._rng_lock = threading.Lock()
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

        # Fencing + lease state. ``fence`` is this replica's generation
        # — bumped by the router on eject, stamped by the worker onto
        # every outbound frame, enforced in _handle_frame. ``_last_ok``
        # is the lease heartbeat (any successful RPC refreshes it);
        # ``_lease_fired_gen`` makes expiry fire once per connection.
        self.fence = 0
        self._last_ok: Optional[float] = None
        self._lease_fired_gen = 0
        self._fence_note_gen = 0
        self._parted_gate: Optional[_PartitionGate] = None

        # Cross-process tracing: spans the worker exports land in this
        # recorder (shared with the router's tracer by default, so one
        # Chrome trace holds both timelines) after the clock estimator
        # maps their worker-epoch perf_counter timestamps into ours.
        # Each process has its own perf_counter zero, so the mapping is
        # re-estimated from hello + every health heartbeat (Cristian
        # min-RTT) and reset whenever the connection generation changes
        # (a re-attached worker may be a different process entirely).
        self.recorder = (
            recorder if recorder is not None else _spans.get_recorder()
        )
        self.clock_sync = ClockSync()
        self._clock_gen = 0
        self._peer_proto = 1  # until a hello reply advertises more

        self.engine: Optional[_RemoteEngine] = None
        # None until first launch so Router.start()'s `rep.loop is None`
        # launch guard works unchanged; stable _RemoteLoop afterwards.
        self.loop: Optional[_RemoteLoop] = None

    # -- spec management (rolling upgrades) ---------------------------

    def update_snapshot(self) -> Dict[str, Any]:
        """Copy of the current worker spec — hold this to roll back."""
        with self._lock:
            return json.loads(json.dumps(self.spec))

    def apply_update(
        self, update: Optional[Dict[str, Any]], *, replace: bool = False
    ) -> None:
        """Patch (merge) worker-spec fields, e.g. ``{"model_path":
        "..."}`` for a checkpoint upgrade; takes effect at the next
        (re)launch. ``replace=True`` swaps the whole spec — the rollback
        path, so keys the refused upgrade ADDED don't survive the
        restore. ``None`` means relaunch-as-is."""
        if update is None:
            return
        with self._lock:
            if replace:
                self.spec = dict(update)
            else:
                self.spec.update(update)

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "RemoteReplica":
        with self._lock:
            self._launch_locked("start")
        return self

    def relaunch(
        self, *, stop_timeout: float = 1.0, hold: bool = False
    ) -> "RemoteReplica":
        with self._lock:
            self._teardown_locked(stop_timeout)
            self._launch_locked("relaunch", hold=hold)
        return self

    def activate(self, reason: str = "activate") -> None:
        """Promote a held (vetting) replica to traffic-eligible."""
        with self._lock:
            self._set_state("active", reason)

    def drain(self) -> None:
        with self._lock:
            if self.loop is not None:
                self.loop.begin_drain()
            self._set_state("draining", "drain")

    def eject(self, reason: str) -> None:
        with self._lock:
            self._set_state("ejected", reason)

    def stop(self, timeout: float = 5.0) -> bool:
        with self._lock:
            return self._teardown_locked(timeout)

    def _launch_locked(self, reason: str, hold: bool = False) -> None:
        proc: Optional[subprocess.Popen] = None
        if self.attach:
            # Attach mode: the worker is pre-spawned (possibly on
            # another host) behind --listen/--token. Connect by address
            # instead of spawning.
            host, _, port_s = self.attach.rpartition(":")
            try:
                port = int(port_s)
                sock = socket.create_connection(
                    (host or "127.0.0.1", port), timeout=10.0
                )
            except (OSError, ValueError) as e:
                raise ReplicaUnavailable(
                    f"replica {self.index} attach to {self.attach!r} "
                    f"failed: {e}"
                ) from e
        else:
            spec = {**self.spec, "index": self.index}
            cmd = [
                self._python,
                "-m",
                "pretraining_llm_tpu.frontend.worker",
                "--spec-json",
                json.dumps(spec),
            ]
            env = dict(os.environ)
            env["PYTHONPATH"] = _REPO_ROOT + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            )
            proc = subprocess.Popen(
                cmd,
                stdin=subprocess.PIPE,   # orphan-detection pipe; never written
                stdout=subprocess.PIPE,  # handshake line
                stderr=None,
                env=env,
            )
            try:
                hs = self._read_handshake(proc)
                port = int(hs["port"])
                sock = socket.create_connection(
                    ("127.0.0.1", port), timeout=10.0
                )
            except Exception:
                try:
                    proc.kill()
                except OSError:
                    pass
                raise
        sock.settimeout(None)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        # Every connection is wrapped so partition/wire_delay faults are
        # injectable on whatever connection is current.
        gate = _PartitionGate(
            sock, rng=random.Random(self.index * 7919 + self._conn_gen)
        )
        with self._conn_lock:
            self._proc = proc
            self._sock = gate
            self._conn_gen += 1
            gen = self._conn_gen
        threading.Thread(
            target=self._reader,
            args=(gate, gen),
            name=f"remote-replica-{self.index}-reader",
            daemon=True,
        ).start()
        # hello blocks until the worker's engine is built (the connect
        # itself only landed in the listen backlog) — so its timeout is
        # the engine-build budget, not the RPC budget. It also grants
        # the worker its lease term and current fence generation, and
        # (attach mode) presents the shared token.
        hello_payload: Dict[str, Any] = {
            "fence": self.fence,
            "lease_s": self.lease_s,
            "proto": PROTO_VERSION,
        }
        token = str(self.spec.get("token") or "")
        if token:
            hello_payload["token"] = token
        hello = self._rpc(
            "hello", hello_payload, timeout=self.spawn_timeout_s, retries=0
        )
        expect = str(self.spec.get("expect_fingerprint") or "")
        got = str(hello.get("weight_fingerprint") or "")
        if expect and got != expect:
            # Wrong weights behind the address: refuse the attach. The
            # reader's _on_conn_lost goes stale via the gen bump, so
            # this raises without emitting a spurious conn-lost event.
            with self._conn_lock:
                bad, self._sock = self._sock, None
                self._conn_gen += 1
            if bad is not None:
                try:
                    bad.close()
                except OSError:
                    pass
            raise ReplicaUnavailable(
                f"replica {self.index} attach refused: worker serves "
                f"fingerprint {got!r}, expected {expect!r}"
            )
        self._peer_proto = int(hello.get("proto", 1))
        self.role = str(hello.get("role") or self.role)
        self.engine = _RemoteEngine(self, hello)
        if self.loop is None:
            self.loop = _RemoteLoop(self)
        self._snapshot = {
            "running": True,
            "draining": False,  # a HELD launch still accepts loop submits
            "active_requests": 0,
            "last_turn_age_s": 0.0,
            "t": self._clock(),
        }
        self.generation += 1
        self._c_spawns.inc()
        self._emit(
            "worker_spawn",
            replica=self.index,
            pid=int(hello.get("pid", 0)),
            port=port,
            reason=reason,
            generation=self.generation,
            held=bool(hold),
            mode=self.mode,
        )
        self._ensure_health_thread()
        # A held launch parks in "draining": the loop accepts submits
        # (begin_drain was NOT sent), but the router will not route
        # traffic to it and the health loop ignores it — the vetting
        # window for rolling upgrades.
        self._set_state("draining" if hold else "active", reason)

    def _read_handshake(self, proc: subprocess.Popen) -> Dict[str, Any]:
        result: Dict[str, Any] = {}

        def _read() -> None:
            while True:
                line = proc.stdout.readline()
                if not line:
                    return
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "worker" in obj:
                    result.update(obj["worker"])
                    return

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(self.spawn_timeout_s)
        if "port" not in result:
            raise RuntimeError(
                f"worker {self.index} did not announce a port within "
                f"{self.spawn_timeout_s}s (exit code "
                f"{proc.poll()})"
            )
        return result

    def _teardown_locked(self, timeout: float) -> bool:
        if self.attach:
            # Detach, never shut down: the pre-spawned worker is not
            # ours to kill. Closing our end makes its serve loop cancel
            # in-flight attempts (freeing decode slots + KV) and park
            # for the next attach — including from a restarted router.
            with self._conn_lock:
                sock, self._sock = self._sock, None
            self._parted_gate = None
            had_conn = sock is not None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            self._snapshot = {"running": False}
            self._fail_pending("worker detached")
            self._fail_attempts("shutdown: router detached from worker")
            if had_conn:
                self._emit(
                    "worker_detach", replica=self.index, address=self.attach
                )
            return True
        clean = True
        proc = self._proc
        if self._connected():
            try:
                self._rpc("shutdown", timeout=min(2.0, timeout), retries=0)
            except Exception:
                clean = False
        if proc is not None:
            try:
                proc.wait(timeout=max(0.1, timeout))
            except subprocess.TimeoutExpired:
                clean = False
                try:
                    proc.kill()
                    proc.wait(timeout=5.0)
                except OSError:
                    pass
            # A worker that died on its own (SIGKILL, crash) before we
            # tore it down waits instantly — the exit code is the truth.
            if proc.returncode != 0:
                clean = False
            self._emit(
                "worker_exit",
                replica=self.index,
                pid=proc.pid,
                clean=clean,
                returncode=proc.returncode,
            )
        with self._conn_lock:
            sock, self._sock = self._sock, None
            self._proc = None
        self._parted_gate = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._snapshot = {"running": False}
        self._fail_pending("worker stopped")
        self._fail_attempts("shutdown: worker stopped")
        return clean

    # -- connection fault domain --------------------------------------

    def _connected(self) -> bool:
        return self._sock is not None

    def _reader(self, sock: socket.socket, gen: int) -> None:
        try:
            while True:
                self._handle_frame(recv_frame(sock))
        except (ConnectionLost, Exception) as e:
            self._on_conn_lost(gen, str(e) or type(e).__name__)

    def _handle_frame(self, frame: Dict[str, Any]) -> None:
        g = frame.get("g")
        if g is not None and int(g) < self.fence:
            # Stale generation: produced before the router last fenced
            # (ejected) this replica — e.g. tokens decoded behind a
            # partition that has since healed. The requests they belong
            # to were redriven onto survivors; delivering them would
            # duplicate tokens. Drop and count.
            self._c_fenced.inc()
            with self._conn_lock:
                gen = self._conn_gen
            if self._fence_note_gen != gen:
                self._fence_note_gen = gen
                self._emit(
                    "fenced_frames_dropped",
                    replica=self.index,
                    fence=self.fence,
                    stale_generation=int(g),
                )
            return
        if "id" in frame:
            with self._pending_lock:
                q = self._pending.get(frame["id"])
            if q is not None:
                q.put(frame)
            return
        if "token" in frame:
            with self._attempts_lock:
                attempt = self._attempts.get(frame["token"])
            if attempt is not None:
                tok = int(frame["t"])
                attempt.tokens.append(tok)
                attempt.out_q.put(("token", tok))
            return
        if "end" in frame:
            with self._attempts_lock:
                attempt = self._attempts.pop(frame["end"], None)
            if attempt is not None:
                attempt.status = str(frame.get("status", "error"))
                attempt.info.update(frame.get("info") or {})
                self._finish_trace(attempt)
                attempt.out_q.put(
                    ("end", attempt.status, dict(attempt.info))
                )
            return
        if frame.get("op") == "kv_page":
            # One frame of a KV fetch stream, keyed by the fetch RPC's
            # id. Unknown keys mean the fetch already gave up (timeout)
            # or this is a stale-connection straggler: drop silently —
            # pages are a cache warm-up, never correctness.
            with self._kv_rx_lock:
                lst = self._kv_rx.get(frame.get("fetch"))
            if lst is not None:
                lst.append(
                    {
                        k: v
                        for k, v in frame.items()
                        if k not in ("op", "fetch", "g")
                    }
                )
            return
        if frame.get("op") == "spans":
            self._ingest_spans(frame)
            return
        if frame.get("op") == "event" and self._bus is not None:
            try:
                self._bus.emit(
                    str(frame.get("kind", "")),
                    step=frame.get("step"),
                    **dict(frame.get("fields") or {}),
                )
            except Exception:
                pass

    def _observe_clock(
        self, gen: int, t_send: float, t_recv: float, t_remote: float
    ) -> None:
        """Feed one RPC round trip into the offset estimator. Samples
        are scoped to a connection generation: a re-attach may put a
        DIFFERENT process (different perf_counter epoch) behind the same
        address, so stale-generation samples are discarded and a new
        generation resets the estimator before its first sample."""
        with self._conn_lock:
            cur = self._conn_gen
        if gen != cur:
            return
        if self._clock_gen != gen:
            self.clock_sync.reset()
            self._clock_gen = gen
        self.clock_sync.observe(t_send, t_recv, t_remote)
        offset = self.clock_sync.offset_s
        if offset is not None:
            self._g_clock_offset.set(offset)
            self._g_clock_err.set(self.clock_sync.error_bound_s or 0.0)

    def _ingest_spans(self, frame: Dict[str, Any]) -> None:
        """Import one batched span-export frame: map each worker-epoch
        timestamp into the router timeline via the current offset
        estimate (recording the error bound alongside), tag the span as
        remote, and re-record it into the shared recorder so the merged
        Chrome trace shows worker decode windows nested inside the
        router's request spans. Spans arriving with no usable offset
        estimate are kept but flagged ``unaligned`` — obs_report
        --fleet-trace --strict fails on them rather than silently
        plotting them in the wrong decade."""
        dropped = int(frame.get("dropped", 0) or 0)
        if dropped > 0:
            self._c_span_drops.inc(dropped)
        offset = self.clock_sync.offset_s
        err = self.clock_sync.error_bound_s
        n = 0
        for ent in frame.get("spans") or []:
            try:
                name = str(ent["name"])
                t0 = float(ent["t0"])
                dur = max(0.0, float(ent.get("dur", 0.0)))
            except (KeyError, TypeError, ValueError):
                continue
            meta = dict(ent.get("meta") or {})
            track = meta.pop("_track", None)
            meta["remote"] = True
            meta["worker"] = self.index
            if offset is not None:
                t0 = t0 + offset
                meta["clock_err_s"] = err
            else:
                meta["unaligned"] = True
            self.recorder.record(name, t0, dur, meta=meta, track=track)
            n += 1
        if n:
            self._c_spans.inc(n)

    @staticmethod
    def _finish_trace(attempt: FrontendRequest) -> None:
        trace = attempt.trace
        if trace is None:
            return
        try:
            # Deferred roots (fleet lineage trees) are finished by the
            # router after redrives settle — an attempt-level end here
            # must not close them.
            if getattr(trace, "finish_deferred", False):
                return
            if not getattr(trace, "finished", True):
                trace.finish(attempt.status)
        except Exception:
            pass

    def _on_conn_lost(self, gen: int, reason: str) -> None:
        with self._conn_lock:
            if gen != self._conn_gen or self._sock is None:
                return  # stale reader, or teardown already ran
            sock, self._sock = self._sock, None
        try:
            sock.close()
        except OSError:
            pass
        self._snapshot = {"running": False, "failure": reason}
        self._fail_pending(reason)
        self._fail_attempts(f"engine failure: worker connection lost ({reason})")
        self._emit("worker_conn_lost", replica=self.index, reason=reason)

    def _fail_pending(self, reason: str) -> None:
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for rid, q in pending.items():
            q.put({"id": rid, "error": "conn_lost", "message": reason})

    def _fail_attempts(self, reason: str) -> None:
        """Terminal every live attempt the way EngineLoop.stop fails its
        requests — ``engine failure`` reasons are what the router's
        pump recognizes as redrivable."""
        with self._attempts_lock:
            attempts, self._attempts = self._attempts, {}
        for attempt in attempts.values():
            attempt.status = "error"
            attempt.info.setdefault("reason", reason)
            self._finish_trace(attempt)
            attempt.out_q.put(("end", "error", dict(attempt.info)))

    # -- RPC ----------------------------------------------------------

    def _backoff_s(self, attempt_k: int) -> float:
        with self._rng_lock:
            u = self._rng.random()
        return (
            self._backoff_base_s
            * (2.0 ** (attempt_k - 1))
            * (1.0 + self._backoff_jitter_frac * u)
        )

    def _rpc(
        self,
        op: str,
        payload: Optional[Dict[str, Any]] = None,
        *,
        timeout: Optional[float] = None,
        retries: Optional[int] = None,
        conn_lost_on_timeout: bool = True,
    ) -> Any:
        timeout = self.rpc_timeout_s if timeout is None else timeout
        retries = self.rpc_retries if retries is None else retries
        for k in range(retries + 1):
            if k:
                self._c_retries.inc()
                self._emit(
                    "rpc_retry", replica=self.index, op=op, attempt=k
                )
                time.sleep(self._backoff_s(k))
            with self._conn_lock:
                sock, gen = self._sock, self._conn_gen
            if sock is None:
                raise ReplicaUnavailable(
                    f"replica {self.index} worker not connected"
                )
            with self._pending_lock:
                self._rpc_seq += 1
                rid = self._rpc_seq
                q: "queue.Queue" = queue.Queue()
                self._pending[rid] = q
            frame = {"op": op, "id": rid, **(payload or {})}
            t0 = time.monotonic()
            # perf_counter bracket for the clock estimator: the worker
            # stamps ITS perf_counter into v2 hello/health replies, and
            # offset = midpoint(t_send, t_recv) - t_remote maps its
            # epoch into ours with error <= rtt/2.
            t_send = time.perf_counter()
            try:
                with self._wlock:
                    send_frame(sock, frame)
                reply = q.get(timeout=timeout)
            except ConnectionLost as e:
                self._on_conn_lost(gen, f"send failed during {op}: {e}")
                raise ReplicaUnavailable(
                    f"replica {self.index} worker connection lost "
                    f"during {op}: {e}"
                ) from e
            except queue.Empty:
                self._c_timeouts.inc()
                if k >= retries:
                    # Lease-mode health polls pass conn_lost_on_timeout=
                    # False: a timeout there is lease evidence, not a
                    # verdict — tearing the socket down would destroy
                    # the stale-frame backlog a healed partition must
                    # deliver (and be counted against).
                    if conn_lost_on_timeout:
                        self._on_conn_lost(
                            gen, f"rpc {op} timed out after {timeout}s"
                        )
                    raise ReplicaUnavailable(
                        f"replica {self.index} rpc {op} timed out "
                        f"after {timeout}s"
                    )
                continue
            finally:
                with self._pending_lock:
                    self._pending.pop(rid, None)
            t_recv = time.perf_counter()
            self._h_rpc.observe(time.monotonic() - t0)
            self._last_ok = time.monotonic()
            if "ok" in reply:
                ok = reply["ok"]
                if isinstance(ok, dict) and "clock" in ok:
                    try:
                        self._observe_clock(
                            gen, t_send, t_recv, float(ok["clock"])
                        )
                    except (TypeError, ValueError):
                        pass
                return ok
            kind = reply.get("error", "runtime")
            message = str(reply.get("message", kind))
            if kind == "conn_lost":
                raise ReplicaUnavailable(
                    f"replica {self.index} worker connection lost "
                    f"during {op}: {message}"
                )
            raise _RPC_ERRORS.get(kind, _raise_runtime)(reply, message)
        raise AssertionError("unreachable")

    # -- the Replica surface ------------------------------------------

    @property
    def proc(self) -> Optional[subprocess.Popen]:
        """The live worker process, if any (fleet drills SIGKILL it)."""
        return self._proc

    @property
    def accepting(self) -> bool:
        return self.state == "active" and self.loop is not None and (
            self.loop.running
        )

    @property
    def alive(self) -> bool:
        return self.loop is not None and self.loop.running

    def load(self) -> int:
        return len(self._attempts)

    # -- KV-page migration (frontend/kv_transfer.py) ------------------

    @property
    def kv_capable(self) -> bool:
        """Whether this worker can take part in a KV migration: alive
        and speaking proto >= 3 (the kv_fetch/kv_page ops). A capable
        worker without a prefix cache simply answers every fetch with
        zero pages and rejects every push — graceful, not special."""
        return self.alive and self._peer_proto >= 3

    def fetch_kv_pages(
        self,
        prompt: Any,
        *,
        max_pages: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Pull the longest cached KV chain for ``prompt`` from this
        worker as a transfer dict, or None. Best-effort by contract:
        every failure mode (not capable, timeout, torn stream, nothing
        cached) returns None — the router falls back to a colocated
        prefill, never an error. Single attempt, no retries: a fetch is
        an optimization racing a request that could just run."""
        if not self.kv_capable:
            return None
        timeout = self.rpc_timeout_s if timeout is None else float(timeout)
        with self._conn_lock:
            sock, gen = self._sock, self._conn_gen
        if sock is None:
            return None
        # The collector must exist before the request hits the wire:
        # the worker streams page frames ahead of the summary reply.
        with self._pending_lock:
            self._rpc_seq += 1
            rid = self._rpc_seq
            q: "queue.Queue" = queue.Queue()
            self._pending[rid] = q
        frames: List[Dict[str, Any]] = []
        with self._kv_rx_lock:
            self._kv_rx[rid] = frames
        payload: Dict[str, Any] = {
            "op": "kv_fetch",
            "id": rid,
            "prompt": [int(t) for t in prompt],
        }
        if max_pages is not None:
            payload["max_pages"] = int(max_pages)
        t0 = time.monotonic()
        try:
            try:
                with self._wlock:
                    send_frame(sock, payload)
                reply = q.get(timeout=timeout)
            except ConnectionLost as e:
                self._on_conn_lost(gen, f"send failed during kv_fetch: {e}")
                return None
            except queue.Empty:
                return None
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)
            with self._kv_rx_lock:
                self._kv_rx.pop(rid, None)
        self._h_rpc.observe(time.monotonic() - t0)
        self._last_ok = time.monotonic()
        ok = reply.get("ok")
        if not isinstance(ok, dict) or int(ok.get("pages", 0) or 0) < 1:
            return None
        try:
            return kv_transfer.join_frames(frames)
        except ValueError:
            return None  # torn mid-stream (reconnect raced the fetch)

    def push_kv_pages(
        self, xfer: Dict[str, Any], *, timeout: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """Stream a transfer dict to this worker and adopt it behind
        its prefix-cache publish path. Returns the worker's adoption
        summary (``inserted``/``rejected``/``published``/``reason``) or
        None if the push could not run. Interior frames ride without an
        id; the final frame is a normal RPC so the adoption verdict
        comes back on the pending queue."""
        if not self.kv_capable:
            return None
        take = (
            getattr(self.faults, "take_kv_corruption", None)
            if self.faults is not None
            else None
        )
        if take is not None and take(self.index):
            kv_transfer.corrupt_first_page(xfer)
            self._emit(
                "fault_fired", fault="corrupt_kv_migration", replica=self.index
            )
        frames = kv_transfer.split_frames(xfer)
        with self._pending_lock:
            self._rpc_seq += 1
            xid = f"kvpush-{self._rpc_seq}"
        with self._conn_lock:
            sock, gen = self._sock, self._conn_gen
        if sock is None:
            return None
        try:
            for fr in frames[:-1]:
                with self._wlock:
                    send_frame(sock, {"op": "kv_page", "xfer": xid, **fr})
        except ConnectionLost as e:
            self._on_conn_lost(gen, f"send failed during kv_page push: {e}")
            return None
        try:
            res = self._rpc(
                "kv_page",
                {"xfer": xid, **frames[-1]},
                timeout=timeout,
                retries=0,
            )
        except Exception:
            return None
        return dict(res) if isinstance(res, dict) else None

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
        trace: Any = _TRACE_UNSET,
        traceparent: Optional[str] = None,
        priority: int = 0,
    ) -> FrontendRequest:
        with self._lock:
            if not self.accepting:
                raise ReplicaUnavailable(
                    f"replica {self.index} is {self.state}"
                )
            if self.faults is not None and self.faults.should_reject(
                self.index
            ):
                raise RejectedBusy(
                    f"replica {self.index} refusing (injected reject_storm)",
                    0.05,
                )
        attempt = self._wire_submit(
            prompt,
            max_new_tokens,
            deadline_s=deadline_s,
            priority=priority,
            lane="replica",
            trace=trace,
            traceparent=traceparent,
        )
        with self._lock:
            self.submits += 1
            nth = self.submits
        if self.faults is not None:
            self.faults.on_submit(self.index, nth)
            self._execute_process_faults()
        return attempt

    def _wire_submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float],
        priority: int,
        lane: str,
        trace: Any = _TRACE_UNSET,
        traceparent: Optional[str] = None,
    ) -> FrontendRequest:
        prompt_ids = [int(t) for t in prompt]
        now = time.monotonic()
        attempt = FrontendRequest(
            prompt=prompt_ids,
            max_new=int(max_new_tokens),
            deadline=(now + deadline_s) if deadline_s else None,
            submitted_s=now,
        )
        if trace is not _TRACE_UNSET:
            attempt.trace = trace
        attempt.priority = int(priority)
        with self._pending_lock:
            self._rpc_seq += 1
            wrid = self._rpc_seq
        attempt.rid = wrid
        # Register BEFORE sending: the worker may stream the first
        # token before the submit reply is even processed here.
        with self._attempts_lock:
            self._attempts[wrid] = attempt
        payload = {
            "rid": wrid,
            "prompt": prompt_ids,
            "max_new": int(max_new_tokens),
            "deadline_s": deadline_s,
            "priority": int(priority),
            "lane": lane,
        }
        # Context propagation (v2 peers only — a v1 worker would still
        # ignore the extra key, but being explicit keeps the contract
        # legible): the worker joins this trace, parenting its local
        # span tree under the router's placement-attempt span.
        if traceparent is not None and self._peer_proto >= 2:
            payload["traceparent"] = str(traceparent)
        try:
            self._rpc(
                "submit",
                payload,
                retries=0,  # NEVER retried: ambiguous submits must fail
            )
        except Exception:
            with self._attempts_lock:
                self._attempts.pop(wrid, None)
            raise
        return attempt

    def _execute_process_faults(self) -> None:
        take = getattr(self.faults, "take_process_faults", None)
        if take is None:
            return
        for kind in take(self.index):
            self._emit("fault_fired", fault=kind, replica=self.index)
            if kind == "worker_kill":
                proc = self._proc
                if proc is not None:
                    try:
                        proc.kill()
                    except OSError:
                        pass
            elif kind == "worker_stall":
                with self._conn_lock:
                    sock = self._sock
                if sock is not None:
                    try:
                        with self._wlock:
                            send_frame(sock, {"op": "stall"})
                    except ConnectionLost:
                        pass
            elif kind == "conn_drop":
                with self._conn_lock:
                    sock = self._sock
                if sock is not None:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
            elif kind == "partition":
                self.partition()
            elif kind == "wire_delay":
                self.set_wire_delay(_WIRE_DELAY_S, jitter_frac=0.5)

    # -- partition / fencing / lease surface --------------------------

    def partition(self) -> None:
        """Blackhole the live connection: reads hang, writes buffer —
        no RST, no EOF (unlike ``conn_drop``). Detection is therefore
        the lease machinery, never the socket."""
        with self._conn_lock:
            gate = self._sock
        if gate is None:
            return
        # Remember which gate was partitioned: a relaunch swaps _sock
        # for a fresh connection, but heal() must still heal THIS one.
        self._parted_gate = gate
        gate.partition()
        self._emit("partition_injected", replica=self.index)

    def heal(self) -> None:
        """Heal the (most recently) partitioned connection: buffered
        writes flush, and the backlog the worker streamed into the void
        becomes readable — the stale-generation flood the fence filter
        exists to drop."""
        gate, self._parted_gate = self._parted_gate, None
        if gate is None:
            with self._conn_lock:
                gate = self._sock
        if gate is None:
            return
        gate.heal()
        self._emit("partition_healed", replica=self.index)

    def set_wire_delay(
        self, delay_s: float, jitter_frac: float = 0.0
    ) -> None:
        """Add one-way delay (+ jitter) to every recv on the current
        connection — a slow WAN link, injectable distinctly from a full
        partition."""
        with self._conn_lock:
            gate = self._sock
        if gate is None:
            return
        gate.set_delay(delay_s, jitter_frac)
        self._emit(
            "wire_delay_set",
            replica=self.index,
            delay_s=float(delay_s),
            jitter_frac=float(jitter_frac),
        )

    def bump_fence(self, reason: str) -> int:
        """Advance this replica's fence generation (router calls this
        on eject). Every frame the worker produced under the old
        generation — including everything buffered behind a partition —
        is dropped on arrival from now on."""
        self.fence += 1
        self._emit(
            "fence_bump", replica=self.index, fence=self.fence, reason=reason
        )
        return self.fence

    def sever(self) -> None:
        """Abrupt, event-free disconnect — the router-crash simulation.
        No shutdown RPC, no attempt terminals, no events: exactly what
        the worker observes when the router process dies mid-flight.
        The worker itself survives (attach mode: its lease expires and
        it parks; a restarted router re-attaches)."""
        with self._conn_lock:
            sock, self._sock = self._sock, None
            # Make the reader's _on_conn_lost stale so the close below
            # stays silent (no failure snapshot, no conn-lost event).
            self._conn_gen += 1
        self._parted_gate = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._snapshot = {"running": False}

    def debug_snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "replica": self.index,
            "state": self.state,
            "generation": self.generation,
            "submits": self.submits,
            "alive": self.alive,
            "mode": self.mode,
            "role": self.role,
            "fence": self.fence,
            "pid": self._proc.pid if self._proc is not None else None,
        }
        if self.attach:
            out["attach"] = self.attach
        loop = self.loop
        if loop is not None:
            out["draining"] = loop.draining
            out["last_turn_age_s"] = round(loop.last_turn_age_s(), 3)
            out["active_requests"] = loop.active_requests
            if loop.failure is not None:
                out["failure"] = loop.failure
        return out

    def health_pull(self) -> Dict[str, Any]:
        """Worker-side gauges + serialized latency sketches for the
        fleet health snapshot (Router.fleet_health). Doubles as a lease
        heartbeat like every health poll. A v<4 peer never sees the op:
        the cached plain-health snapshot is returned instead, flagged
        ``proto_fallback`` so the aggregate says WHY a replica has no
        gauge section rather than silently thinning out."""
        if self._connected() and self._peer_proto >= 4:
            try:
                out = dict(
                    self._rpc(
                        "health_pull",
                        {"fence": self.fence, "lease_s": self.lease_s},
                        timeout=self.rpc_timeout_s,
                    )
                )
                out["proto"] = self._peer_proto
                return out
            except Exception:
                pass  # fall through to the cached snapshot
        snap = dict(self._snapshot)
        snap["proto_fallback"] = True
        return snap

    # -- internals ----------------------------------------------------

    def _ensure_health_thread(self) -> None:
        if self._health_thread is not None and self._health_thread.is_alive():
            return
        self._health_stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_poll,
            name=f"remote-replica-{self.index}-health",
            daemon=True,
        )
        self._health_thread.start()

    def _health_poll(self) -> None:
        stop = self._health_stop
        while not stop.wait(self.health_interval_s):
            if not self._connected():
                continue
            lease = self.lease_s
            # Health polls double as the lease heartbeat: each carries
            # the current fence generation + lease term the worker
            # should honor (the hello only covers connect time; fence
            # bumps between ejects arrive this way).
            hb = {"fence": self.fence, "lease_s": lease}
            if lease > 0:
                with self._conn_lock:
                    gen = self._conn_gen
                if self._lease_fired_gen == gen:
                    # Lease already expired on this connection: stop
                    # heartbeating into the void; the router's backoff
                    # relaunch (detach + reconnect) resumes polling.
                    continue
                try:
                    snap = self._rpc(
                        "health",
                        hb,
                        timeout=min(
                            self.rpc_timeout_s, max(0.05, lease / 4.0)
                        ),
                        retries=0,
                        conn_lost_on_timeout=False,
                    )
                except Exception:
                    self._maybe_expire_lease(gen)
                    continue
            else:
                try:
                    snap = self._rpc("health", hb, timeout=self.rpc_timeout_s)
                except Exception:
                    continue  # conn-lost path already updated the snapshot
            snap["t"] = self._clock()
            self._snapshot = snap

    def _maybe_expire_lease(self, gen: int) -> None:
        """Declare the lease expired if no RPC has succeeded for a full
        lease term. Fails live attempts with the redrivable ``engine
        failure`` prefix but deliberately does NOT close the socket:
        when the partition heals, the frames the worker streamed into
        the void must still arrive — stamped with a stale generation —
        to be counted and dropped by the fence filter."""
        lease = self.lease_s
        last = self._last_ok
        if lease <= 0 or last is None:
            return
        age = time.monotonic() - last
        if age <= lease:
            return
        if self._lease_fired_gen == gen:
            return
        self._lease_fired_gen = gen
        self._c_lease.inc()
        reason = f"worker lease expired (no contact for {age:.2f}s)"
        self._snapshot = {"running": False, "failure": reason}
        self._fail_attempts(f"engine failure: {reason}")
        self._emit(
            "lease_expired", replica=self.index, age_s=round(age, 3)
        )

    def _set_state(self, state: str, reason: str) -> None:
        assert state in REPLICA_STATES, state
        self.state = state
        self._emit(
            "replica_state",
            replica=self.index,
            state=state,
            reason=reason,
            generation=self.generation,
        )
        hook = self.on_state
        if hook is not None:
            hook(self, state, reason)

    def _emit(self, kind: str, **fields: Any) -> None:
        if self._bus is None:
            return
        try:
            self._bus.emit(kind, **fields)
        except Exception:
            pass


def _raise_runtime(reply: Dict[str, Any], message: str) -> Exception:
    return ReplicaUnavailable(message)


def _raise_invalid(reply: Dict[str, Any], message: str) -> Exception:
    return ValueError(message)


def _raise_busy(reply: Dict[str, Any], message: str) -> Exception:
    return RejectedBusy(message, float(reply.get("retry_after_s", 1.0)))


def _raise_infeasible(reply: Dict[str, Any], message: str) -> Exception:
    from .admission import RejectedInfeasible

    return RejectedInfeasible(message, float(reply.get("estimate_s", 0.0)))


_RPC_ERRORS = {
    "invalid": _raise_invalid,
    "busy": _raise_busy,
    "infeasible": _raise_infeasible,
    "unavailable": _raise_runtime,
    "runtime": _raise_runtime,
}
