"""One restartable engine replica: engine factory + EngineLoop + identity.

A ``Replica`` is the unit the fleet router schedules over: it owns an
engine built by ``engine_factory`` (so a crashed replica can be relaunched
with a FRESH engine — same supervisor semantics as the training side's
relaunch-from-checkpoint, except serving state is the requests themselves
and the router redrives those), the ``EngineLoop`` driving it, its own
per-replica ``AdmissionController`` (the replica budget; the router holds
the fleet budget), and its own ``MetricsRegistry`` carrying a constant
``replica`` label so N replicas share one metric vocabulary without
stomping each other (observability.metrics.render_merged joins them).

Lifecycle states (the ``replica_state`` event/gauge vocabulary):

  active    accepting and serving traffic;
  draining  alive but refusing new work (rolling restart: the router
            redrives its in-flight requests, then stops the loop);
  ejected   declared dead/wedged by the router's health loop; relaunch is
            scheduled with exponential backoff.

Observability: the replica wraps the shared fleet EventBus in a tagging
proxy that stamps ``replica=i`` onto every event the EngineLoop emits, so
per-replica ``req_*``/``cap_window``/``decision`` streams interleave in one
JSONL and obs_report --fleet can attribute them without new emit sites.

Fault injection: when a ``ServingFaultInjector`` is attached, accepted
submissions feed its request-count clock and ``engine.pipeline_tick`` is
shadowed by its shim (an instance attribute over the class method — the
same trick the throttle tests use), so ``replica_crash@req_n`` style plans
fire deterministically under a seeded load schedule.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from pretraining_llm_tpu.frontend.admission import (
    AdmissionController,
    RejectedBusy,
)
from pretraining_llm_tpu.frontend.engine_loop import (
    _TRACE_UNSET,
    EngineLoop,
    FrontendRequest,
)
from pretraining_llm_tpu.frontend import kv_transfer
from pretraining_llm_tpu.observability.metrics import MetricsRegistry

REPLICA_STATES = ("active", "draining", "ejected")

# Disaggregation roles: what traffic the router may place here.
#   both     the classic colocated replica (prefill + decode);
#   decode   serves client requests, receives migrated KV pages;
#   prefill  dedicated prefill tier — computes prompts (max_new=1 legs
#            via the direct loop lane) and ships the published pages;
#            the router never routes client decode traffic to it.
REPLICA_ROLES = ("prefill", "decode", "both")

# Gauge encoding for the typed ``replica_state`` metric: chosen so "is it
# taking traffic" is a simple ``== 1`` and alerting thresholds are stable.
REPLICA_STATE_VALUES = {"ejected": 0.0, "active": 1.0, "draining": 2.0}


class ReplicaUnavailable(Exception):
    """The replica is not accepting work (draining, ejected, or stopped);
    the router treats this as 'pick another replica', never a client
    error."""


class _TaggedBus:
    """EventBus proxy stamping ``replica=i`` on every emit. The EngineLoop
    keeps its single ``self.bus`` attribute and zero fleet knowledge."""

    def __init__(self, inner: Any, replica: int) -> None:
        self._inner = inner
        self.replica = int(replica)

    def emit(self, kind: str, *, step: Optional[int] = None, **fields: Any) -> Any:
        fields.setdefault("replica", self.replica)
        return self._inner.emit(kind, step=step, **fields)

    def subscribe(self, fn: Any) -> None:
        self._inner.subscribe(fn)

    def close(self) -> None:
        # The fleet bus outlives any one replica; closing is the owner's job.
        pass


class Replica:
    """See module docstring. ``engine_factory`` is called once per
    (re)launch and must return a fresh ServingEngine-compatible object;
    ``admission_factory(registry)`` likewise returns the replica's own
    AdmissionController (None = no per-replica admission).

    ``on_state(replica, state, reason)`` is the router's hook for keeping
    the fleet's typed ``replica_state`` gauge in step with transitions
    this object performs itself (start/drain/eject/relaunch).
    """

    def __init__(
        self,
        index: int,
        engine_factory: Callable[[], Any],
        *,
        bus: Any = None,
        tracer: Any = None,
        registry_prefix: str = "pllm_serving_",
        registry_labels: Optional[Dict[str, Any]] = None,
        admission_factory: Optional[Callable[[Any], AdmissionController]] = None,
        fault_injector: Any = None,
        clock: Any = time.monotonic,
        loop_kwargs: Optional[Dict[str, Any]] = None,
        role: str = "both",
    ) -> None:
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        self.role = role
        self.index = int(index)
        self._engine_factory = engine_factory
        self._bus = bus
        self._tracer = tracer
        self._admission_factory = admission_factory
        self.faults = fault_injector
        self._clock = clock
        self._loop_kwargs = dict(loop_kwargs or {})
        # One registry per replica, same names fleet-wide, distinguished by
        # the constant label; survives relaunches so counters stay totals.
        # ``registry_labels`` carries fleet-wide constant labels (e.g. the
        # quant_dtype the whole fleet serves at); the replica index wins
        # any collision because it is what tells the series apart.
        self.registry = MetricsRegistry(
            registry_prefix,
            const_labels={**(registry_labels or {}), "replica": self.index},
        )
        self.state = "ejected"  # not launched yet; start() flips to active
        self.generation = 0     # bumped per (re)launch
        self.submits = 0        # accepted submissions (the fault clock)
        self.on_state: Optional[Callable[["Replica", str, str], None]] = None
        self._lock = threading.Lock()
        self.engine: Any = None
        self.admission: Optional[AdmissionController] = None
        self.loop: Optional[EngineLoop] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Replica":
        with self._lock:
            self._launch_locked("start")
        return self

    def relaunch(
        self, *, stop_timeout: float = 1.0, hold: bool = False
    ) -> "Replica":
        """Replace a dead/wedged/drained engine with a fresh one. The old
        loop is stopped best-effort (a wedged thread is abandoned — it is
        a daemon and EngineLoop.stop already failed its requests).

        ``hold=True`` parks the fresh engine in "draining" WITHOUT
        draining the loop: it accepts direct ``loop.submit`` work (the
        probe-vetting lane) but the router will not route traffic to it
        and the health loop leaves it alone — the rolling-upgrade
        vetting window. ``activate()`` promotes it."""
        with self._lock:
            old = self.loop
            if old is not None:
                try:
                    old.stop(timeout=stop_timeout)
                except Exception:
                    pass
            self._launch_locked("relaunch", hold=hold)
        return self

    def activate(self, reason: str = "activate") -> None:
        """Promote a held (vetting) replica to traffic-eligible."""
        with self._lock:
            self._set_state("active", reason)

    # -- live weight upgrades ------------------------------------------------

    def update_snapshot(self) -> Callable[[], Any]:
        """The current engine factory — hold this to roll an upgrade
        back (the process-mode twin snapshots the worker spec)."""
        return self._engine_factory

    def apply_update(
        self, update: Optional[Callable[[], Any]], *, replace: bool = False
    ) -> None:
        """Swap the engine factory (e.g. one closing over a new
        checkpoint's params); takes effect at the next (re)launch.
        ``None`` means relaunch-as-is. A factory is already a complete
        replacement, so ``replace`` (which process-mode spec patches
        need for rollback) changes nothing here."""
        if update is None:
            return
        with self._lock:
            self._engine_factory = update

    def _launch_locked(self, reason: str, hold: bool = False) -> None:
        engine = self._engine_factory()
        if self.faults is not None:
            engine.pipeline_tick = self.faults.wrap_tick(
                self.index, engine.pipeline_tick
            )
            # Corruption faults (corrupt_kv_page/corrupt_weights/wrong_token)
            # mutate engine state directly; re-attached on every relaunch so
            # the injector never fires into a stopped engine's generation.
            self.faults.attach_engine(self.index, engine)
        admission = (
            self._admission_factory(self.registry)
            if self._admission_factory is not None
            else None
        )
        bus = _TaggedBus(self._bus, self.index) if self._bus is not None else None
        self.engine = engine
        self.admission = admission
        self.loop = EngineLoop(
            engine,
            admission=admission,
            bus=bus,
            tracer=self._tracer,
            registry=self.registry,
            clock=self._clock,
            **self._loop_kwargs,
        )
        self.loop.start()
        self.generation += 1
        self._set_state("draining" if hold else "active", reason)

    def drain(self) -> None:
        """Refuse new work; in-flight requests keep decoding (the router
        redrives them, then calls stop())."""
        with self._lock:
            if self.loop is not None:
                self.loop.begin_drain()
            self._set_state("draining", "drain")

    def eject(self, reason: str) -> None:
        """Router verdict: dead or wedged. Routing stops immediately; the
        loop (possibly a wedged daemon thread) is left to stop()/relaunch."""
        with self._lock:
            self._set_state("ejected", reason)

    def stop(self, timeout: float = 5.0) -> bool:
        """Stop the loop (outstanding requests get error terminals — see
        EngineLoop.stop). Returns False when the loop thread had to be
        abandoned wedged."""
        loop = self.loop
        if loop is None:
            return True
        return loop.stop(timeout=timeout)

    def _set_state(self, state: str, reason: str) -> None:
        assert state in REPLICA_STATES, state
        self.state = state
        if self._bus is not None:
            self._bus.emit(
                "replica_state", replica=self.index, state=state,
                reason=reason, generation=self.generation,
            )
        if self.on_state is not None:
            self.on_state(self, state, reason)

    # -- traffic ------------------------------------------------------------

    @property
    def accepting(self) -> bool:
        loop = self.loop
        return self.state == "active" and loop is not None and loop.running

    @property
    def alive(self) -> bool:
        loop = self.loop
        return loop is not None and loop.running

    def load(self) -> int:
        """Requests in this replica's system (inbox + engine), the spill
        signal for affinity routing."""
        loop = self.loop
        return loop.active_requests if loop is not None else 0

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
        trace: Any = _TRACE_UNSET,
        traceparent: Optional[str] = None,
        priority: int = 0,
    ) -> FrontendRequest:
        """Submit through the replica: availability gate, injected
        reject_storm gate, then the loop (validation + replica admission).

        ``traceparent`` exists for signature parity with RemoteReplica
        (the router hands every attempt both the trace object and its
        wire form) and is ignored here: an in-process replica records
        straight into the shared recorder through ``trace`` — there is
        no process boundary to carry a header across.
        The fault clock counts ACCEPTED submissions and arms only after
        the loop took the request, so an armed crash always fires with
        its triggering request in flight — the redrive path, not just
        routing, is what the drill exercises."""
        with self._lock:
            if not self.accepting:
                raise ReplicaUnavailable(
                    f"replica {self.index} is {self.state}"
                )
            if self.faults is not None and self.faults.should_reject(self.index):
                retry = (
                    self.admission.retry_after_s
                    if self.admission is not None else 1.0
                )
                raise RejectedBusy(
                    f"replica {self.index} refusing (injected reject_storm)",
                    retry,
                )
            req = self.loop.submit(
                prompt, max_new_tokens, deadline_s=deadline_s, trace=trace,
                priority=priority,
            )
            self.submits += 1
            nth = self.submits
        if self.faults is not None:
            self.faults.on_submit(self.index, nth)
        return req

    # -- KV-page migration (frontend/kv_transfer.py) ------------------------

    @property
    def kv_capable(self) -> bool:
        """Whether this replica can send/receive migrated KV pages: it
        needs a live engine with a prefix cache (the publish path the
        pages enter and leave through)."""
        eng = self.engine
        return (
            self.alive
            and eng is not None
            and getattr(eng, "prefix_cache", None) is not None
        )

    def fetch_kv_pages(
        self,
        prompt: Any,
        *,
        max_pages: Optional[int] = None,
        timeout: float = 30.0,
    ) -> Optional[Dict[str, Any]]:
        """Serialize the longest cached chain covering ``prompt`` from
        this replica's pool; None when nothing is cached. Round-trips
        through the frame codec even in-process so both fleet modes
        exercise the one serialization path the wire uses."""
        eng = self.engine
        if eng is None or not self.alive:
            return None
        xfer = kv_transfer.snapshot_chain(eng, prompt, max_pages=max_pages)
        if xfer is None:
            return None
        return kv_transfer.join_frames(kv_transfer.split_frames(xfer))

    def push_kv_pages(
        self, xfer: Dict[str, Any], *, timeout: float = 30.0
    ) -> Optional[Dict[str, Any]]:
        """Adopt migrated pages into this replica's pool (loop-thread
        insertion via run_on_loop). Returns adopt_chain's summary, or
        None when the replica cannot take pages right now. An armed
        ``corrupt_kv_migration`` fault flips bytes in the transfer
        in flight, exactly like the wire-level drill."""
        loop = self.loop
        if loop is None or not self.alive:
            return None
        take = getattr(self.faults, "take_kv_corruption", None)
        if take is not None and take(self.index):
            kv_transfer.corrupt_first_page(xfer)
            if self._bus is not None:
                self._bus.emit(
                    "fault_fired",
                    fault="corrupt_kv_migration",
                    replica=self.index,
                )
        eng = self.engine
        try:
            return loop.run_on_loop(
                lambda: kv_transfer.adopt_chain(eng, xfer), timeout=timeout
            )
        except (RuntimeError, TimeoutError):
            return None

    # -- introspection ------------------------------------------------------

    def debug_snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "replica": self.index,
            "state": self.state,
            "role": self.role,
            "generation": self.generation,
            "submits": self.submits,
            "alive": self.alive,
        }
        loop = self.loop
        if loop is not None:
            out["draining"] = loop.draining
            out["last_turn_age_s"] = round(loop.last_turn_age_s(), 6)
            out["active_requests"] = loop.active_requests
            if loop.failure is not None:
                out["failure"] = repr(loop.failure)
        return out

    def health_pull(self) -> Dict[str, Any]:
        """Surface parity with RemoteReplica.health_pull: the same gauge
        shape assembled locally (no wire hop, no sketches — in-process
        events land on the router's bus directly, so the router-side SLO
        engine already holds this replica's distributions)."""
        out = self.debug_snapshot()
        loop = self.loop
        if loop is None or not self.alive:
            out["proto_fallback"] = True
            return out
        out["running"] = bool(loop.running)
        out["fence"] = 0  # in-process replicas are never fenced
        gauges: Dict[str, Any] = {}
        eng = self.engine
        hg = getattr(eng, "health_gauges", None) if eng is not None else None
        if hg is not None:
            gauges.update(hg())
        gauges["active_requests"] = int(loop.active_requests)
        if loop.admission is not None:
            adm = loop.admission.snapshot()
            gauges["admission_depth"] = int(adm.get("live_requests", 0))
            gauges["admission_outstanding_tokens"] = int(
                adm.get("outstanding_tokens", 0)
            )
        out["gauges"] = gauges
        try:
            from pretraining_llm_tpu.observability.device import (
                DeviceTelemetry,
            )

            hbm = DeviceTelemetry(bus=None).sample()
        except Exception:
            hbm = {}
        if hbm:
            out["hbm"] = hbm
        return out
