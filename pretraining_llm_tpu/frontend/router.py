"""Fleet router: one serving surface over N engine replicas.

The router duck-types the EngineLoop surface the gateway consumes
(``submit``/``cancel``/``metrics``/``last_turn_age_s``/``readiness``/
``debug_*``/``tracer``), so ``serve.py --replicas N`` swaps it in without
touching the HTTP layer. What it adds over a single loop:

Placement — prefix-affinity with spill. Requests route by rendezvous hash
of their prompt-prefix digest (first ``affinity_tokens`` tokens), so a hot
prefix keeps landing on the replica whose prefix cache already holds it;
when the affinity choice is ``spill_margin`` requests deeper than the
least-loaded healthy replica, load wins over affinity (a hot prefix must
not melt one replica while others idle).

Health — ejection with exponential backoff. The health thread watches each
active replica for a dead loop thread (engine crash) or a stale
``last_turn_age_s`` past ``wedged_after_s`` (the serving twin of the
training step watchdog: a wedged turn means a wedged device dispatch).
Either verdict ejects the replica (stops routing), schedules a relaunch
with doubling backoff, and redrives its work.

Redrive — the robustness core. Every router request owns its committed
token frontier (tokens already streamed to the client are never
retracted). When a replica crashes, hangs, or is drained, its queued AND
mid-decode requests fail over to survivors as ``prompt + committed_tokens``
with ``max_new`` reduced by what was delivered; greedy decoding makes the
continuation bit-identical to an undisturbed run, and the prefix cache
makes the re-prefill cheap (the dead replica's pages are gone, but shared
prefixes on survivors still hit). Failed-over requests keep their router
request id (``frid``) and fleet admission ticket; ``redrives_total`` and
per-request ``info["redrives"]`` account the cost.

Brownout — partial capacity sheds partially. When the healthy fraction
drops below ``brownout_min_healthy_frac``, the router sheds the work that
can best tolerate it — priority below ``brownout_min_priority``, or
deadline longer than ``brownout_max_deadline_s`` (longest-deadline work
has the most slack to retry later) — with 429 + Retry-After instead of
failing everything.

Lineage tracing — every client request is ONE trace tree across all its
placement attempts. The router owns the root span (``req.request``,
``finish_deferred`` keeps replica loops from closing it early) and mints
a child ``req.attempt`` span per placement, tagged (replica, fence
generation, redrive index, outcome). In-process attempts record their
engine spans straight into the shared recorder; remote attempts get a
``traceparent`` pointing at the attempt span, so the worker's local span
tree — shipped back in batched span-export frames and clock-aligned by
RemoteReplica — nests under it. Redrives and journal replays link into
the SAME tree: the journal's submit records carry ``trace_id``, so a
recovered router continues the original trace instead of minting an
orphan. The fleet event stream (``fleet_req_submit``/``redrive``/
``fleet_req_terminal`` keyed by ``frid``) remains the flat audit log the
trace tree is cross-checked against (obs_report --fleet-trace).
"""

from __future__ import annotations

import hashlib
import queue
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from pretraining_llm_tpu.frontend import kv_transfer
from pretraining_llm_tpu.frontend.admission import (
    AdmissionController,
    RejectedBusy,
    RejectedInfeasible,
    Ticket,
)
from pretraining_llm_tpu.frontend.engine_loop import (
    _TRACE_UNSET,
    TERMINAL_STATUSES,
    FrontendRequest,
)
from pretraining_llm_tpu.frontend.journal import FleetJournal
from pretraining_llm_tpu.frontend.replica import (
    REPLICA_STATE_VALUES,
    Replica,
    ReplicaUnavailable,
)
from pretraining_llm_tpu.observability.capacity import DecisionLog
from pretraining_llm_tpu.observability.metrics import render_merged
from pretraining_llm_tpu.observability.tracing import (
    RequestTrace,
    SpanContext,
    format_traceparent,
)


def prefix_digest(prompt: Any, n_tokens: int) -> bytes:
    """Stable digest of the routing prefix (first ``n_tokens`` ids)."""
    h = hashlib.blake2b(digest_size=8)
    for t in list(prompt)[:n_tokens]:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


def _rendezvous_score(digest: bytes, replica: int) -> int:
    h = hashlib.blake2b(
        digest + int(replica).to_bytes(4, "little"), digest_size=8
    )
    return int.from_bytes(h.digest(), "little")


class RouterRequest:
    """One request as the CLIENT sees it, stable across redrives: the
    stream surface mirrors FrontendRequest (``out_q`` carries
    ``("token", t)`` then one ``("end", status, info)``;
    ``events()``/``result()`` drain it), while ``_attempt`` — the current
    per-replica FrontendRequest — may be replaced under ``_lock`` when the
    router fails the request over."""

    def __init__(
        self,
        frid: int,
        prompt: List[int],
        max_new: int,
        *,
        deadline: Optional[float],
        submitted_s: float,
        priority: int = 0,
        ticket: Optional[Ticket] = None,
        trace: Any = None,
    ) -> None:
        self.frid = frid
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline  # absolute on the router clock, None = none
        self.submitted_s = submitted_s
        self.priority = priority
        self.ticket = ticket
        self.trace = trace
        self.out_q: "queue.Queue[Tuple]" = queue.Queue()
        self.status = "queued"
        self.tokens: List[int] = []  # committed frontier (streamed, final)
        self.info: Dict[str, Any] = {}
        self.cancel_requested = False
        self.redrives = 0
        self.replica: Optional[int] = None
        self._attempt: Optional[FrontendRequest] = None
        # Open placement-attempt span: (span_id, t0, replica, fence).
        # Spans are recorded at completion, so the router carries the
        # pre-minted id here until the attempt ends (terminal/redrive).
        self.attempt_span: Optional[Tuple[str, float, int, int]] = None
        self._lock = threading.Lock()

    def events(self, timeout: Optional[float] = None) -> Iterator[Tuple]:
        while True:
            try:
                ev = self.out_q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no stream event within {timeout}s (status={self.status})"
                )
            yield ev
            if ev[0] == "end":
                return

    def result(
        self, timeout: Optional[float] = None
    ) -> Tuple[str, List[int], Dict[str, Any]]:
        for _ in self.events(timeout=timeout):
            pass
        return self.status, self.tokens, self.info


class Router:
    """See module docstring. ``replicas`` are constructed outside (they
    carry the engine factories); the router starts/stops them with itself.

    ``admission`` is the FLEET budget (scope it with ``scope="fleet"`` on
    a shared registry); each replica's own controller still applies at its
    loop. ``registry`` holds the fleet-level typed series
    (``replica_state``, ``redrives_total``, brownout) and leads the merged
    exposition.
    """

    def __init__(
        self,
        replicas: List[Replica],
        *,
        admission: Optional[AdmissionController] = None,
        bus: Any = None,
        registry: Any = None,
        tracer: Any = None,
        clock: Any = time.monotonic,
        affinity_tokens: int = 32,
        spill_margin: int = 4,
        wedged_after_s: float = 0.0,
        eject_backoff_s: float = 0.5,
        eject_backoff_max_s: float = 8.0,
        backoff_jitter_frac: float = 0.25,
        backoff_seed: int = 0,
        redrive_max: int = 3,
        health_interval_s: float = 0.02,
        brownout_min_healthy_frac: float = 0.0,
        brownout_min_priority: int = 1,
        brownout_max_deadline_s: float = 0.0,
        probe_interval_s: float = 0.0,
        probe_count: int = 2,
        probe_max_new: int = 4,
        probe_timeout_s: float = 30.0,
        probe_set: Optional[List[Any]] = None,
        journal_path: str = "",
        journal_rotate_bytes: int = 0,
        recover: bool = False,
        kv_migrate_timeout_s: float = 30.0,
        kv_home_max: int = 4096,
        slo: Any = None,
    ) -> None:
        if not replicas:
            raise ValueError("Router needs at least one replica")
        if recover and not journal_path:
            raise ValueError("recover=True needs a journal_path")
        if journal_rotate_bytes < 0:
            raise ValueError(
                f"journal_rotate_bytes must be >= 0, got "
                f"{journal_rotate_bytes}"
            )
        if affinity_tokens < 1:
            raise ValueError(
                f"affinity_tokens must be >= 1, got {affinity_tokens}"
            )
        if spill_margin < 1:
            raise ValueError(f"spill_margin must be >= 1, got {spill_margin}")
        if redrive_max < 0:
            raise ValueError(f"redrive_max must be >= 0, got {redrive_max}")
        if not 0.0 <= brownout_min_healthy_frac <= 1.0:
            raise ValueError(
                f"brownout_min_healthy_frac must be in [0, 1], got "
                f"{brownout_min_healthy_frac}"
            )
        if probe_interval_s < 0:
            raise ValueError(
                f"probe_interval_s must be >= 0, got {probe_interval_s}"
            )
        if probe_count < 1:
            raise ValueError(f"probe_count must be >= 1, got {probe_count}")
        if probe_max_new < 1:
            raise ValueError(
                f"probe_max_new must be >= 1, got {probe_max_new}"
            )
        if probe_timeout_s <= 0:
            raise ValueError(
                f"probe_timeout_s must be > 0, got {probe_timeout_s}"
            )
        self.replicas = list(replicas)
        self.admission = admission
        self.bus = bus
        self.registry = registry
        self.tracer = tracer
        # Optional live SLO engine (observability/slo.py). The router
        # never feeds it — it subscribes to the bus on its own — but
        # holding the handle here lets the gateway serve GET /slo and
        # fleet_health() fold worker sketches into the same snapshot.
        self.slo = slo
        self._clock = clock
        self.affinity_tokens = int(affinity_tokens)
        self.spill_margin = int(spill_margin)
        self.wedged_after_s = float(wedged_after_s)
        self.eject_backoff_s = float(eject_backoff_s)
        self.eject_backoff_max_s = float(eject_backoff_max_s)
        if not 0.0 <= backoff_jitter_frac <= 1.0:
            raise ValueError(
                f"backoff_jitter_frac must be in [0, 1], got "
                f"{backoff_jitter_frac}"
            )
        self.backoff_jitter_frac = float(backoff_jitter_frac)
        # Seeded: a crash-looping FLEET must not relaunch in lockstep
        # (decorrelated thundering herds), yet drills stay reproducible.
        self._backoff_rng = random.Random(backoff_seed)
        self.redrive_max = int(redrive_max)
        self.health_interval_s = float(health_interval_s)
        self.brownout_min_healthy_frac = float(brownout_min_healthy_frac)
        self.brownout_min_priority = int(brownout_min_priority)
        self.brownout_max_deadline_s = float(brownout_max_deadline_s)
        # Output-integrity sentinel (resilience/integrity.py). 0 disables
        # the layer entirely: no probe set is built, the health loop never
        # probes, and no fingerprint is read. ``probe_set`` lets tests
        # inject pinned probes; production pins them in start() from the
        # first replica's reference greedy path.
        self.probe_interval_s = float(probe_interval_s)
        self.probe_count = int(probe_count)
        self.probe_max_new = int(probe_max_new)
        self.probe_timeout_s = float(probe_timeout_s)
        self._probe_set: Optional[List[Any]] = (
            list(probe_set) if probe_set is not None else None
        )
        self._probe_lock = threading.Lock()
        self._probe_inflight: Set[int] = set()
        self._last_probe_ok: Dict[int, bool] = {}
        self._last_probe_t: Dict[int, float] = {}
        self._probe_idx = 0
        self._next_probe_at = 0.0
        if kv_migrate_timeout_s <= 0:
            raise ValueError(
                f"kv_migrate_timeout_s must be > 0, got {kv_migrate_timeout_s}"
            )
        if kv_home_max < 1:
            raise ValueError(f"kv_home_max must be >= 1, got {kv_home_max}")
        self.kv_migrate_timeout_s = float(kv_migrate_timeout_s)
        self.kv_home_max = int(kv_home_max)
        # KV placement map: prefix digest -> replica index that most
        # recently ADOPTED migrated pages for that prefix. Generalizes
        # prefix-affinity: rendezvous hashing predicts where a prefix
        # SHOULD live; this records where its pages actually ARE, so
        # follow-up requests land on the warmed decode worker. Insertion
        # ordered, capped at kv_home_max (oldest entry evicted) — a
        # stale entry only costs a cold prefill, never correctness.
        self._kv_home: Dict[bytes, int] = {}
        self._kv_home_lock = threading.Lock()
        self.decisions = DecisionLog(maxlen=256, bus=bus)
        self._live: Dict[int, RouterRequest] = {}
        self._live_lock = threading.Lock()
        self._next_frid = 0
        self._stopping = False
        self._draining = False
        self._started = clock()
        self._stop_ev = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._backoff: Dict[int, float] = {}
        self._relaunch_at: Dict[int, float] = {}
        self.brownout_active = False
        self._counters_lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "cancelled": 0, "expired": 0,
            "errors": 0, "redrives": 0, "brownout_shed": 0, "ejects": 0,
            "probes": 0, "probe_failures": 0, "quarantines": 0,
            "relaunches": 0, "upgrades": 0, "upgrades_refused": 0,
            "journal_replays": 0,
            "kv_migrations": 0, "kv_pages_migrated": 0,
            "kv_migration_rejects": 0,
        }
        self._g_state: Dict[int, Any] = {}
        self._g_backoff: Dict[int, Any] = {}
        self._c_redrives = self._c_shed = self._c_ejects = None
        self._c_probes = self._c_probe_fail = self._c_quarantines = None
        self._c_relaunches = None
        self._c_replays = None
        self._g_brownout = None
        self._c_kv_pages = self._c_kv_bytes = self._c_kv_rejects = None
        if registry is not None:
            for rep in self.replicas:
                self._g_state[rep.index] = registry.gauge(
                    "replica_state",
                    "replica lifecycle (0=ejected, 1=active, 2=draining)",
                    replica=rep.index,
                )
                self._g_backoff[rep.index] = registry.gauge(
                    "replica_backoff_s",
                    "currently scheduled relaunch backoff (0 = not backing "
                    "off) — a crash-looping replica shows as a climb to "
                    "the cap instead of silent retries",
                    replica=rep.index,
                )
            self._c_redrives = registry.counter(
                "redrives_total",
                "in-flight requests failed over to a surviving replica")
            self._c_shed = registry.counter(
                "brownout_shed_total",
                "requests shed at the router during brownout")
            self._c_ejects = registry.counter(
                "replica_ejects_total",
                "replicas declared dead/wedged by the health loop")
            self._c_relaunches = registry.counter(
                "replica_relaunch_total",
                "replica engines (re)launched after eject/drain/upgrade")
            self._g_brownout = registry.gauge(
                "brownout_active", "1 while the fleet is in brownout")
            self._c_probes = registry.counter(
                "integrity_probes_total",
                "golden probes completed against replicas")
            self._c_probe_fail = registry.counter(
                "integrity_probe_failures_total",
                "golden probes whose output diverged from the pinned reference")
            self._c_quarantines = registry.counter(
                "quarantines_total",
                "replicas quarantined by the integrity sentinel")
            self._c_replays = registry.counter(
                "router_journal_replays_total",
                "journaled in-flight requests redriven by a recovering "
                "router")
            self._c_kv_pages = registry.counter(
                "kv_pages_migrated_total",
                "KV pages adopted by decode workers from prefill-tier "
                "migrations")
            self._c_kv_bytes = registry.counter(
                "kv_migrated_bytes_total",
                "serialized bytes of KV transfers pushed to decode workers")
            self._c_kv_rejects = registry.counter(
                "kv_migration_rejects_total",
                "migrated KV pages a decode worker refused (checksum "
                "mismatch, capacity, stale fence, layout)")
        # Write-ahead fleet journal (crash-recoverable control plane).
        # With recover=True the previous router's journal is folded into
        # a recovery plan BEFORE this router touches any worker: fence
        # generations advance past everything the dead router granted,
        # so every frame its workers still hold in flight is stale by
        # construction, and frids continue past the old allocator.
        self.journal: Optional[FleetJournal] = None
        self.recovered: Dict[int, RouterRequest] = {}
        self._recover_plan: Optional[Dict[str, Any]] = None
        if journal_path:
            if recover:
                plan = FleetJournal.recovery_plan(
                    FleetJournal.load(journal_path)
                )
                self._recover_plan = plan
                self._next_frid = max(
                    self._next_frid, int(plan["next_frid"])
                )
                for rep in self.replicas:
                    if hasattr(rep, "fence"):
                        rep.fence = max(
                            rep.fence,
                            int(plan["fences"].get(rep.index, 0)) + 1,
                        )
            self.journal = FleetJournal(
                journal_path, rotate_bytes=int(journal_rotate_bytes)
            )
        for rep in self.replicas:
            rep.on_state = self._on_replica_state

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Router":
        for rep in self.replicas:
            if rep.loop is None:
                rep.start()
        if self.probe_interval_s > 0 and self._probe_set is None:
            # Pin the golden set once, from the REFERENCE generate path on
            # known-good weights (the loops are idle at this point; no
            # request has touched any engine yet). probe_len spans exactly
            # one full KV block past the boundary so probe #0 publishes the
            # shared prefix to the prefix cache and every later probe
            # re-acquires it — a corrupted cached page then surfaces as
            # probe divergence, not just as wrong client outputs.
            from pretraining_llm_tpu.resilience.integrity import (
                GoldenProbe, build_probe_set,
            )
            engine = next(
                (r.engine for r in self.replicas if r.engine is not None),
                None,
            )
            if engine is None:
                raise RuntimeError(
                    "probe_interval_s > 0 needs a launched replica to pin "
                    "the golden probe set against"
                )
            if engine.temperature != 0.0:
                raise ValueError(
                    "golden probes compare outputs bit-for-bit and need "
                    "deterministic decode: probe_interval_s > 0 requires "
                    f"temperature=0, got {engine.temperature} (a sampling "
                    "engine draws fresh noise per decode, so every probe "
                    "would diverge and quarantine healthy replicas)"
                )
            # Clamp to the model context: a large serving block size on a
            # short-context model must not make the probe itself infeasible
            # (the cache-coverage property just degrades to a partial page).
            probe_len = min(
                engine.block_size + 1,
                engine.cfg.context_length - self.probe_max_new,
            )
            if probe_len < 2:
                raise ValueError(
                    f"context_length={engine.cfg.context_length} leaves no "
                    f"room for a probe with probe_max_new="
                    f"{self.probe_max_new}"
                )
            # Process-mode replicas expose a build_probe_set facade (the
            # params live in the worker); in-process engines fall through
            # to the local reference path.
            builder = getattr(engine, "build_probe_set", None)
            if builder is not None:
                self._probe_set = builder(
                    n_probes=self.probe_count,
                    probe_len=probe_len,
                    max_new=self.probe_max_new,
                )
            else:
                self._probe_set = build_probe_set(
                    engine.params, engine.cfg,
                    n_probes=self.probe_count,
                    probe_len=probe_len,
                    max_new=self.probe_max_new,
                )
            # Re-pin the expected tokens from the SERVING path itself. The
            # reference generate above vets the prompts, but at bf16 its
            # argmax near-ties can legitimately differ from the paged
            # serving engine's — a baseline from a different code path
            # would quarantine every healthy replica. Serving is
            # deterministic and identical across same-config replicas, so
            # the unanimous startup answer is the bit-exact contract every
            # healthy replica must keep; replicas that disagree before any
            # traffic means no trustworthy baseline exists at all.
            self._probe_set = [
                GoldenProbe(prompt=p.prompt, expected=exp)
                for p, exp in zip(
                    self._probe_set,
                    self._pin_serving_baseline(self._probe_set),
                )
            ]
        if self.journal is not None:
            # Membership + fence baseline first, so even a journal with
            # zero requests lets the next recovery fence everything.
            for rep in self.replicas:
                self.journal.append({
                    "rec": "member",
                    "replica": rep.index,
                    "mode": getattr(rep, "mode", "inproc"),
                    "attach": getattr(rep, "attach", ""),
                    "generation": rep.generation,
                })
                self.journal.append({
                    "rec": "fence",
                    "replica": rep.index,
                    "fence": int(getattr(rep, "fence", 0)),
                })
        # Replay journaled in-flight requests BEFORE the health thread
        # starts interleaving ejects: the replicas are launched and
        # idle, so every replay places deterministically.
        self._replay_journal()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router-health", daemon=True
        )
        self._health_thread.start()
        return self

    def _replay_journal(self) -> None:
        """Redrive every journaled in-flight request from its last
        committed frontier (recover=True). Replays bypass fleet
        admission — they were admitted by the previous router and their
        tickets died with it; re-gating them could deadlock recovery
        behind fresh traffic. Deadlines are not resurrected (they were
        absolute on the dead router's clock). Greedy decode from
        ``prompt + tokens`` makes each completion bit-identical to the
        undisturbed output."""
        plan = self._recover_plan
        if not plan or not plan["live"]:
            return
        for frid in sorted(plan["live"]):
            ent = plan["live"][frid]
            # Continue the ORIGINAL distributed trace, not a fresh one:
            # the journaled trace_id re-keys this request into the same
            # lineage tree its pre-crash spans already belong to (the
            # root span id is fresh — the old root died unrecorded with
            # the old router — but every grouping key matches).
            trace = None
            journaled_tid = ent.get("trace_id")
            if self.tracer is not None and journaled_tid:
                trace = RequestTrace(
                    self.tracer.recorder, str(journaled_tid)
                )
                trace.finish_deferred = True
            rreq = RouterRequest(
                int(frid), list(ent["prompt"]), int(ent["max_new"]),
                deadline=None, submitted_s=self._clock(),
                priority=int(ent["priority"]), trace=trace,
            )
            rreq.tokens = list(ent["tokens"])
            rreq.redrives = int(ent["redrives"])
            self.recovered[rreq.frid] = rreq
            with self._live_lock:
                self._live[rreq.frid] = rreq
            with self._counters_lock:
                self.counters["submitted"] += 1
                self.counters["journal_replays"] += 1
            if self._c_replays is not None:
                self._c_replays.inc()
            if self.bus is not None:
                fields = (
                    {"trace_id": trace.trace_id} if trace is not None else {}
                )
                self.bus.emit(
                    "fleet_req_submit", frid=rreq.frid, replica=None,
                    n_prompt=len(rreq.prompt), max_new=rreq.max_new,
                    priority=rreq.priority, replayed=True, **fields,
                )
            replica: Optional[int] = None
            with rreq._lock:
                if len(rreq.tokens) >= rreq.max_new:
                    # The journal frontier already covers the whole
                    # greedy output: the old router died between the
                    # last commit and its terminal bookkeeping.
                    self._finish_locked(
                        rreq, "done", {"completed_at_replay": True}
                    )
                else:
                    try:
                        replica = self._assign_locked(rreq, exclude=set())
                    except Exception as e:
                        self._finish_locked(
                            rreq, "error",
                            {"reason": f"journal replay failed: {e}"},
                        )
            if self.bus is not None:
                self.bus.emit(
                    "journal_replay", frid=rreq.frid, replica=replica,
                    n_committed=len(rreq.tokens), redrives=rreq.redrives,
                )

    def stop(self, timeout: float = 10.0) -> bool:
        """Stop the fleet. In-flight requests get error terminals (via
        each loop's shutdown path); returns False if any loop thread had
        to be abandoned wedged."""
        self._stopping = True
        self._stop_ev.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        clean = True
        for rep in self.replicas:
            clean = rep.stop(timeout=timeout) and clean
        # Belt and suspenders: anything the loops could not terminate
        # (e.g. a request whose attempt was abandoned mid-redrive when
        # stop hit) gets its terminal here, so no client hangs.
        for rreq in self._live_snapshot():
            with rreq._lock:
                self._finish_locked(rreq, "error", {"reason": "router shutdown"})
        if self.journal is not None:
            self.journal.close()
        return clean

    def abort(self) -> None:
        """Simulate a router CRASH (the recovery drill's kill switch):
        no shutdown RPCs, no request terminals, no events — workers and
        clients are simply cut off, exactly as if the process died.
        Attached workers' leases expire and they park; a new Router
        built with ``recover=True`` on the same journal re-attaches,
        fences the old generation, and redrives the journaled work."""
        self._stopping = True
        self._stop_ev.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        for rep in self.replicas:
            sever = getattr(rep, "sever", None)
            if sever is not None:
                sever()
        if self.journal is not None:
            self.journal.close()

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int,
        *,
        deadline_s: Optional[float] = None,
        trace: Any = _TRACE_UNSET,
        priority: int = 0,
    ) -> RouterRequest:
        """Gateway-facing submit: validate, brownout gate, fleet
        admission, place on a replica, start the pump. Raises exactly what
        EngineLoop.submit raises (ValueError / RejectedBusy /
        RejectedInfeasible / RuntimeError) so the gateway's status mapping
        is unchanged."""
        if self._stopping:
            raise RuntimeError("Router is stopped")
        if self._draining:
            raise RuntimeError("Router is draining")
        if trace is _TRACE_UNSET:
            trace = (
                self.tracer.begin_request() if self.tracer is not None else None
            )
        if trace is not None:
            # The router owns the lineage-tree root: replica loops record
            # their spans into it but must not close it — an attempt-level
            # terminal (replica crash) is not the request's fate.
            trace.finish_deferred = True
        engine = next(
            (r.engine for r in self.replicas if r.engine is not None), None
        )
        if engine is None:
            raise RuntimeError("Router has no launched replica")
        try:
            max_new = engine.validate_request(prompt, max_new_tokens)
        except ValueError:
            if self.bus is not None:
                self.bus.emit("req_rejected", reason="invalid", fleet=True)
            if trace is not None:
                trace.finish("rejected", reason="invalid")
            raise
        prompt = [int(t) for t in prompt]
        if self.brownout_active and self._brownout_sheds(priority, deadline_s):
            retry = (
                self.admission.retry_after_s
                if self.admission is not None else 1.0
            )
            reason = (
                f"fleet brownout: shedding priority<"
                f"{self.brownout_min_priority} / long-deadline work"
            )
            with self._counters_lock:
                self.counters["brownout_shed"] += 1
            if self._c_shed is not None:
                self._c_shed.inc()
            self.decisions.record(
                "brownout_shed", priority=priority, deadline_s=deadline_s,
                trace_id=trace.trace_id if trace is not None else None,
            )
            if trace is not None:
                trace.finish("rejected", reason="brownout")
            raise RejectedBusy(reason, retry)
        ticket = None
        if self.admission is not None:
            cached = self._best_cached(prompt)
            try:
                ticket = self.admission.try_admit(
                    len(prompt), max_new, deadline_s=deadline_s,
                    cached_tokens=cached,
                )
            except (RejectedBusy, RejectedInfeasible):
                if self.bus is not None:
                    self.bus.emit(
                        "req_rejected", reason="fleet_budget", fleet=True,
                    )
                if trace is not None:
                    trace.finish("rejected", reason="fleet_budget")
                raise
        now = self._clock()
        with self._live_lock:
            frid = self._next_frid
            self._next_frid += 1
        if self.journal is not None:
            # Write-AHEAD of placement: a router that dies between this
            # record and the replica ack still redrives the request on
            # recovery (at-least-once into the fleet; the fence makes
            # delivery to the client at-most-once per generation).
            self.journal.append({
                "rec": "submit", "frid": frid, "prompt": prompt,
                "max_new": max_new, "priority": int(priority),
                "deadline_s": deadline_s,
                # Lineage across router restarts: a recovering router
                # CONTINUES this trace id instead of minting an orphan.
                "trace_id": trace.trace_id if trace is not None else None,
            })
        rreq = RouterRequest(
            frid, prompt, max_new,
            deadline=(now + deadline_s) if deadline_s is not None else None,
            submitted_s=now, priority=int(priority), ticket=ticket,
            trace=trace,
        )
        # Disaggregated prefill (no-op without a prefill tier): may
        # commit the first token and warm the decode target's cache, so
        # it runs before placement — _assign_locked then submits the
        # continuation exactly the way a redrive would.
        self._maybe_disaggregate(rreq)
        try:
            with rreq._lock:
                replica = self._assign_locked(rreq, exclude=set())
        except BaseException as e:
            if ticket is not None:
                self.admission.release(ticket)
            if self.journal is not None:
                # The client saw the rejection; recovery must not
                # resurrect it.
                self.journal.append(
                    {"rec": "terminal", "frid": frid, "status": "rejected"}
                )
            if self.bus is not None:
                # Every replica refused (busy, storming, or unavailable):
                # the client got a 429 the fleet COULD not absorb. The SLO
                # engine counts this as availability burn — per-replica
                # refusals that spill to a peer never reach here.
                self.bus.emit(
                    "req_rejected", reason="placement", fleet=True,
                    **(
                        {"trace_id": trace.trace_id}
                        if trace is not None else {}
                    ),
                )
            # Deferred-finish means no replica loop closed the root on
            # our behalf; the router must, or the tree never terminates.
            if trace is not None and not trace.finished:
                trace.finish("rejected", reason=f"placement failed: {e}")
            raise
        with self._live_lock:
            self._live[frid] = rreq
        with self._counters_lock:
            self.counters["submitted"] += 1
        if self.bus is not None:
            fields = {"trace_id": trace.trace_id} if trace is not None else {}
            self.bus.emit(
                "fleet_req_submit", frid=frid, replica=replica,
                n_prompt=len(prompt), max_new=max_new, priority=priority,
                **fields,
            )
        return rreq

    def cancel(self, rreq: RouterRequest) -> None:
        rreq.cancel_requested = True
        with rreq._lock:
            attempt, idx = rreq._attempt, rreq.replica
        if attempt is None or idx is None:
            return
        loop = self.replicas[idx].loop
        if loop is not None:
            loop.cancel(attempt)

    def _brownout_sheds(
        self, priority: int, deadline_s: Optional[float]
    ) -> bool:
        if priority < self.brownout_min_priority:
            return True
        if self.brownout_max_deadline_s > 0 and (
            deadline_s is None or deadline_s > self.brownout_max_deadline_s
        ):
            return True
        return False

    def _best_cached(self, prompt: List[int]) -> int:
        """Fleet admission's prefix-cache hint: the BEST hit any replica
        could serve (optimistic — affinity usually sends the request
        there, and an optimistic hint only discounts the token budget,
        never unsounds it)."""
        best = 0
        for rep in self.replicas:
            cache = getattr(rep.engine, "prefix_cache", None)
            if cache is not None and rep.accepting:
                try:
                    best = max(best, cache.peek(prompt))
                except Exception:
                    pass
        return best

    # -- disaggregated prefill/decode ---------------------------------------

    def _decode_holds_prefix(
        self, rep: Replica, prompt: List[int], block_size: int
    ) -> bool:
        """Would a migration to ``rep`` be redundant — does it already
        hold at least one full block of this prefix? In-process replicas
        answer from their cache; remote ones from the KV-placement map
        (the router's only view of a worker's cache contents)."""
        digest = prefix_digest(prompt, self.affinity_tokens)
        with self._kv_home_lock:
            if self._kv_home.get(digest) == rep.index:
                return True
        cache = getattr(rep.engine, "prefix_cache", None)
        if cache is None:
            return False
        try:
            return cache.peek(prompt) >= block_size
        except Exception:
            return False

    def _maybe_disaggregate(self, rreq: RouterRequest) -> None:
        """Disaggregated prefill: run the prompt's prefill (plus the
        first token) on a dedicated prefill-tier worker, migrate the
        resulting KV pages to the decode target, and commit the first
        token to the client — the continuation then decodes on the
        warmed target via the ordinary assignment path (``prompt +
        committed`` with ``max_new`` reduced, the same machinery
        redrives use).

        Strictly best-effort: every failure mode — no prefill tier, the
        prefill leg dying mid-flight, a torn/corrupt/rejected transfer —
        falls back to the colocated path with zero client-visible
        difference, because greedy decoding makes the first token
        correct regardless of where the pages ended up, and a decode
        worker without the pages simply re-prefills. Never raises."""
        if rreq.max_new < 2:
            return  # no decode phase to disaggregate
        prompt = rreq.prompt
        pre = [
            r for r in self.replicas
            if getattr(r, "role", "both") == "prefill"
            and r.accepting and getattr(r, "kv_capable", False)
        ]
        if not pre:
            return
        digest = prefix_digest(prompt, self.affinity_tokens)
        P = max(pre, key=lambda r: _rendezvous_score(digest, r.index))
        D = self._pick(prompt, set())
        if (
            D is None
            or D.index == P.index
            or not getattr(D, "kv_capable", False)
        ):
            return
        block_size = int(getattr(D.engine, "block_size", 0) or 0)
        if block_size < 1 or len(prompt) - 1 < block_size:
            return  # no full page would migrate; colocated is strictly better
        if self._decode_holds_prefix(D, prompt, block_size):
            return  # the target is already warm; migration saves nothing
        t_mig0 = time.perf_counter()
        # Prefill leg: loop lane (not client traffic — no fleet ticket,
        # no fault clock, no frid). max_new=1 so the leg both builds the
        # KV chain AND yields the greedy first token, which is correct
        # to commit no matter what happens to the pages.
        try:
            leg = P.loop.submit(
                list(prompt), 1, trace=None, priority=rreq.priority
            )
            status, tokens, _info = leg.result(
                timeout=self.kv_migrate_timeout_s
            )
        except Exception:
            return  # prefill tier died mid-leg: silent colocated fallback
        if status != "done" or len(tokens) != 1:
            return
        t0 = int(tokens[0])
        inserted = rejected = nbytes = 0
        reject_reason: Optional[str] = None
        try:
            xfer = P.fetch_kv_pages(prompt)
        except Exception:
            xfer = None
        if xfer is not None:
            nbytes = kv_transfer.transfer_bytes(xfer)
            try:
                res = D.push_kv_pages(
                    xfer, timeout=self.kv_migrate_timeout_s
                )
            except Exception:
                res = None
            if isinstance(res, dict):
                inserted = int(res.get("inserted", 0) or 0)
                rejected = int(res.get("rejected", 0) or 0)
                if res.get("reason"):
                    reject_reason = str(res["reason"])
        # Commit the prefill leg's token: it is the greedy t0 of this
        # prompt on fleet-identical weights, valid whether or not a
        # single page survived the trip.
        with rreq._lock:
            if rreq.status in TERMINAL_STATUSES or rreq.cancel_requested:
                return
            rreq.tokens.append(t0)
            rreq.out_q.put(("token", t0))
        if self.journal is not None:
            # Same frontier record redrives write: a router that dies
            # right here still resumes from prompt + [t0] on recovery.
            self.journal.append({
                "rec": "frontier", "frid": rreq.frid,
                "tokens": list(rreq.tokens), "redrives": rreq.redrives,
            })
        saved_tokens = inserted * block_size
        with self._counters_lock:
            self.counters["kv_migrations"] += 1
            self.counters["kv_pages_migrated"] += inserted
            self.counters["kv_migration_rejects"] += rejected
        if inserted and self._c_kv_pages is not None:
            self._c_kv_pages.inc(inserted)
        if nbytes and self._c_kv_bytes is not None:
            self._c_kv_bytes.inc(nbytes)
        if rejected and self._c_kv_rejects is not None:
            self._c_kv_rejects.inc(rejected)
        if rreq.trace is not None:
            rreq.trace.span(
                "req.kv_migrate", t_mig0,
                from_replica=P.index, to_replica=D.index,
                pages=inserted, bytes=nbytes, rejected=rejected,
                saved_tokens=saved_tokens,
            )
        tid = rreq.trace.trace_id if rreq.trace is not None else None
        self.decisions.record(
            "kv_migrate", frid=rreq.frid, from_replica=P.index,
            to_replica=D.index, pages=inserted, rejected=rejected,
            trace_id=tid,
        )
        if self.bus is not None:
            self.bus.emit(
                "kv_migrate", frid=rreq.frid, from_replica=P.index,
                to_replica=D.index, pages=inserted, bytes=nbytes,
                rejected=rejected, saved_tokens=saved_tokens,
            )
        if rejected:
            # Rejected pages are DROPPED pages — the decode worker
            # refused to adopt them (checksum mismatch, capacity, stale
            # fence). The request is unharmed (it re-prefills), but the
            # verdict must be auditable.
            self.decisions.record(
                "kv_migration_reject", frid=rreq.frid, replica=D.index,
                rejected=rejected, reason=reject_reason, trace_id=tid,
            )
            if self.bus is not None:
                self.bus.emit(
                    "kv_migration_reject", frid=rreq.frid,
                    replica=D.index, rejected=rejected,
                    reason=reject_reason,
                )
        if inserted:
            with self._kv_home_lock:
                self._kv_home[digest] = D.index
                while len(self._kv_home) > self.kv_home_max:
                    self._kv_home.pop(next(iter(self._kv_home)))

    # -- placement ----------------------------------------------------------

    def _pick(self, prompt: List[int], tried: Set[int]) -> Optional[Replica]:
        # Dedicated prefill workers never take client decode traffic —
        # their capacity is reserved for prefill legs. If the fleet is
        # SO degraded that only prefill workers accept, serve anyway
        # (colocated on the prefill worker beats a 429).
        cands = [
            r for r in self.replicas
            if r.index not in tried and r.accepting
            and getattr(r, "role", "both") != "prefill"
        ]
        if not cands:
            cands = [
                r for r in self.replicas
                if r.index not in tried and r.accepting
            ]
        if not cands:
            return None
        digest = prefix_digest(prompt, self.affinity_tokens)
        loads = {r.index: r.load() for r in cands}
        min_load = min(loads.values())
        # KV-placement affinity generalizes prefix-affinity: rendezvous
        # predicts where a prefix SHOULD live, but a completed migration
        # records where its pages actually ARE. Honor the recorded home
        # unless it is spill-margin deeper than the least-loaded
        # candidate (the same imbalance rule affinity itself obeys).
        with self._kv_home_lock:
            home = self._kv_home.get(digest)
        if home is not None:
            rep = next((r for r in cands if r.index == home), None)
            if (
                rep is not None
                and loads[rep.index] < min_load + self.spill_margin
            ):
                return rep
        by_score = sorted(
            cands, key=lambda r: _rendezvous_score(digest, r.index),
            reverse=True,
        )
        chosen = by_score[0]
        if loads[chosen.index] >= min_load + self.spill_margin:
            # Affinity lost to imbalance: take the least-loaded candidate,
            # rendezvous order breaking ties so the spill is deterministic.
            chosen = min(
                by_score, key=lambda r: (loads[r.index], by_score.index(r))
            )
        return chosen

    def _assign_locked(
        self, rreq: RouterRequest, exclude: Set[int]
    ) -> int:
        """Place ``rreq``'s next attempt (rreq._lock held). Walks replicas
        in affinity order, spilling past busy/unavailable ones; raises the
        last rejection when nobody can take it."""
        tried: Set[int] = set(exclude)
        last_exc: Optional[Exception] = None
        delivered = len(rreq.tokens)
        deadline_s = None
        if rreq.deadline is not None:
            deadline_s = rreq.deadline - self._clock()
            if deadline_s <= 0:
                raise RejectedInfeasible("deadline already expired", 0.0)
        # The continuation resumes from the committed frontier; greedy
        # decoding makes it bit-identical to the undisturbed suffix.
        prompt = rreq.prompt + rreq.tokens if delivered else rreq.prompt
        max_new = rreq.max_new - delivered
        trace = (
            rreq.trace
            if rreq.trace is not None and not rreq.trace.finished
            else None
        )
        while True:
            rep = self._pick(prompt, tried)
            if rep is None:
                raise last_exc if last_exc is not None else RejectedBusy(
                    "no replica available",
                    self.admission.retry_after_s
                    if self.admission is not None else 1.0,
                )
            tried.add(rep.index)
            # Every placement is a child span of the lineage root. The
            # span id is minted BEFORE the submit so the traceparent can
            # point at it: a remote worker parents its whole local span
            # tree under this attempt, and in-process loops record into
            # the same trace directly. The span itself is recorded when
            # the attempt ends (replicas that refuse record it here).
            span_id: Optional[str] = None
            tp: Optional[str] = None
            t_att0 = time.perf_counter()
            if trace is not None:
                span_id = trace.new_span_id()
                tp = format_traceparent(
                    SpanContext(trace.trace_id, span_id, sampled=True)
                )
            try:
                attempt = rep.submit(
                    prompt, max_new, deadline_s=deadline_s, trace=trace,
                    traceparent=tp, priority=rreq.priority,
                )
            except (ReplicaUnavailable, RuntimeError) as e:
                if trace is not None:
                    trace.span(
                        "req.attempt", t_att0, span_id=span_id,
                        outcome="unavailable", replica=rep.index,
                        redrive=rreq.redrives,
                    )
                last_exc = RejectedBusy(
                    str(e),
                    self.admission.retry_after_s
                    if self.admission is not None else 1.0,
                )
                continue
            except RejectedBusy as e:
                if trace is not None:
                    trace.span(
                        "req.attempt", t_att0, span_id=span_id,
                        outcome="busy", replica=rep.index,
                        redrive=rreq.redrives,
                    )
                last_exc = e
                continue
            rreq._attempt = attempt
            rreq.replica = rep.index
            if trace is not None and span_id is not None:
                rreq.attempt_span = (
                    span_id, t_att0, rep.index,
                    int(getattr(rep, "fence", 0)),
                )
            threading.Thread(
                target=self._pump,
                args=(rreq, attempt, rep.index),
                name=f"pump-{rreq.frid}.{rreq.redrives}",
                daemon=True,
            ).start()
            return rep.index

    # -- pump (one thread per attempt) --------------------------------------

    def _pump(
        self, rreq: RouterRequest, attempt: FrontendRequest, rep_index: int
    ) -> None:
        """Forward one attempt's stream to the router request, redriving
        on replica failure. Abandonment protocol: whoever replaces
        ``rreq._attempt`` under the lock owns the stream from then on; a
        pump that observes the mismatch exits silently (a non-event tuple
        pushed onto the old attempt's queue wakes a blocked pump)."""
        for ev in attempt.events():
            if ev[0] == "token":
                with rreq._lock:
                    if rreq._attempt is not attempt:
                        return
                    rreq.tokens.append(ev[1])
                    rreq.out_q.put(("token", ev[1]))
                continue
            if ev[0] != "end":  # abandonment wake-up marker
                with rreq._lock:
                    if rreq._attempt is not attempt:
                        return
                continue
            _, status, info = ev
            with rreq._lock:
                if rreq._attempt is not attempt:
                    return
                if (
                    status == "error"
                    and self._redrivable(info)
                    and not rreq.cancel_requested
                    and not self._stopping
                ):
                    reason = str(info.get("reason", "replica failure"))
                    if rreq.redrives < self.redrive_max:
                        if self._redrive_locked(rreq, rep_index, reason):
                            return
                    else:
                        # Attempt cap hit: the REQUEST is the poison (it
                        # has killed every replica it landed on). A clean
                        # terminal stops the redrive storm; the fleet
                        # recovers replica-by-replica behind it.
                        info = {
                            "reason": (
                                f"redrive budget exhausted after {reason}"
                            )
                        }
                self._finish_locked(rreq, status, info)
            return

    @staticmethod
    def _redrivable(info: Dict[str, Any]) -> bool:
        """Error terminals that mean 'the REPLICA failed, not the
        request': engine crash, loop shutdown under the request, wedged
        stop. Anything else (per-request validation fallback) stays an
        error to the client."""
        reason = str(info.get("reason", ""))
        return (
            reason.startswith("engine failure")
            or reason.startswith("shutdown")
            or reason.startswith("drain")
        )

    def _close_attempt_span(
        self, rreq: RouterRequest, outcome: str, **meta: Any
    ) -> None:
        """Record the open placement-attempt span (rreq._lock held):
        the attempt is over — terminal, redrive, or abandonment — so its
        pre-minted span id finally gets its [t0, now] extent, tagged with
        where it ran and how it ended."""
        ent, rreq.attempt_span = rreq.attempt_span, None
        if ent is None or rreq.trace is None:
            return
        span_id, t0, rep_idx, fence = ent
        rreq.trace.span(
            "req.attempt", t0, span_id=span_id, outcome=outcome,
            replica=rep_idx, fence=fence, redrive=rreq.redrives, **meta,
        )

    def _redrive_locked(
        self, rreq: RouterRequest, from_idx: int, reason: str
    ) -> bool:
        """Fail ``rreq`` over to a survivor (rreq._lock held). Returns
        True when the request found a new home (or finished outright);
        False means the caller should deliver the failure terminal."""
        delivered = len(rreq.tokens)
        self._close_attempt_span(
            rreq, "redriven", reason=reason, n_committed=delivered
        )
        # Abandon the old attempt unconditionally: every path below either
        # re-homes the request or terminates it, and a pump blocked on a
        # wedged replica's stream must be woken to exit either way.
        old_attempt = rreq._attempt
        rreq._attempt = None
        if old_attempt is not None:
            old_attempt.out_q.put(("abandoned", None))
        if rreq.deadline is not None and self._clock() >= rreq.deadline:
            self._finish_locked(
                rreq, "expired", {"reason": "deadline passed during redrive"}
            )
            return True
        if delivered >= rreq.max_new:
            # The replica died between the last committed token and its
            # finish bookkeeping: the client already has the whole greedy
            # output, so this IS completion.
            self._finish_locked(rreq, "done", {"completed_at_redrive": True})
            return True
        rreq.redrives += 1
        try:
            to_idx = self._assign_locked(rreq, exclude={from_idx})
        except (RejectedBusy, RejectedInfeasible, RuntimeError, ValueError) as e:
            self._finish_locked(
                rreq, "error",
                {"reason": f"redrive failed: {e}", "redrive_from": from_idx},
            )
            return True
        if self.journal is not None:
            # The committed frontier at the moment of failover — token
            # VALUES, not a count, so a recovering router can re-submit
            # ``prompt + tokens`` and greedy-decode the identical tail.
            self.journal.append({
                "rec": "frontier", "frid": rreq.frid,
                "tokens": list(rreq.tokens), "redrives": rreq.redrives,
            })
        with self._counters_lock:
            self.counters["redrives"] += 1
        if self._c_redrives is not None:
            self._c_redrives.inc()
        self.decisions.record(
            "redrive", frid=rreq.frid, from_replica=from_idx,
            to_replica=to_idx, n_committed=delivered, reason=reason,
            trace_id=rreq.trace.trace_id if rreq.trace is not None else None,
        )
        if self.bus is not None:
            self.bus.emit(
                "redrive", frid=rreq.frid, from_replica=from_idx,
                to_replica=to_idx, n_committed=delivered,
                n_prompt=len(rreq.prompt), reason=reason,
            )
        return True

    def _finish_locked(
        self, rreq: RouterRequest, status: str, info: Dict[str, Any]
    ) -> None:
        """Deliver the router-level terminal exactly once (rreq._lock
        held); later callers (a racing pump vs. shutdown sweep) no-op."""
        if rreq.status in TERMINAL_STATUSES:
            return
        rreq.status = status
        if self.journal is not None:
            self.journal.append(
                {"rec": "terminal", "frid": rreq.frid, "status": status}
            )
        info = dict(info)
        info["redrives"] = rreq.redrives
        info["n_tokens"] = len(rreq.tokens)
        # Which replica served the FINAL attempt — with redrives the
        # client-visible answer crossed hosts; the gateway surfaces this
        # alongside trace_id so a curl away from the trace tree.
        info.setdefault("replica", rreq.replica)
        # Router-level e2e spans ALL attempts; the attempt-local timings
        # (ttft/queue_wait) describe only the last one.
        info["e2e_s"] = self._clock() - rreq.submitted_s
        if rreq.trace is not None:
            info.setdefault("trace_id", rreq.trace.trace_id)
        rreq.info = info
        # Close the lineage tree: the last attempt span, then the root
        # (replica loops saw finish_deferred and left it open for us).
        self._close_attempt_span(rreq, status)
        if rreq.trace is not None and not rreq.trace.finished:
            rreq.trace.finish(
                status, n_tokens=len(rreq.tokens), redrives=rreq.redrives
            )
        if self.admission is not None and rreq.ticket is not None:
            self.admission.release(rreq.ticket)
        with self._live_lock:
            self._live.pop(rreq.frid, None)
        counter = {
            "done": "completed", "cancelled": "cancelled",
            "expired": "expired", "error": "errors",
        }[status]
        with self._counters_lock:
            self.counters[counter] += 1
        if self.bus is not None:
            self.bus.emit(
                "fleet_req_terminal", frid=rreq.frid, status=status,
                redrives=rreq.redrives, n_tokens=len(rreq.tokens),
                replica=rreq.replica, e2e_s=info["e2e_s"],
            )
        rreq.out_q.put(("end", status, info))

    def _live_snapshot(self) -> List[RouterRequest]:
        with self._live_lock:
            return list(self._live.values())

    # -- health / drain / brownout ------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop_ev.wait(self.health_interval_s):
            now = self._clock()
            for rep in self.replicas:
                if rep.state == "active":
                    loop = rep.loop
                    if loop is None or not loop.running:
                        self._eject(rep, "loop dead (engine crash)")
                        continue
                    age = loop.last_turn_age_s()
                    if (
                        self.wedged_after_s > 0
                        and age > self.wedged_after_s
                        and loop.active_requests > 0
                    ):
                        self._eject(rep, f"wedged: last turn {age:.2f}s ago")
                elif rep.state == "ejected":
                    at = self._relaunch_at.get(rep.index)
                    if at is not None and now >= at:
                        self._relaunch_at.pop(rep.index, None)
                        try:
                            rep.relaunch(stop_timeout=0.5)
                            self._count_relaunch(rep.index)
                        except Exception:
                            backoff = self._next_backoff(rep.index)
                            self._relaunch_at[rep.index] = (
                                self._clock() + backoff
                            )
            self._sentinel_tick(now)
            self._update_brownout()

    def _next_backoff(self, index: int) -> float:
        cur = self._backoff.get(index, self.eject_backoff_s)
        self._backoff[index] = min(cur * 2.0, self.eject_backoff_max_s)
        cur *= 1.0 + self.backoff_jitter_frac * self._backoff_rng.random()
        gauge = self._g_backoff.get(index)
        if gauge is not None:
            gauge.set(cur)
        return cur

    def _count_relaunch(self, index: int) -> None:
        with self._counters_lock:
            self.counters["relaunches"] += 1
        if self._c_relaunches is not None:
            self._c_relaunches.inc()
        gauge = self._g_backoff.get(index)
        if gauge is not None:
            gauge.set(0.0)

    def _eject(self, rep: Replica, reason: str) -> None:
        rep.eject(reason)
        with self._counters_lock:
            self.counters["ejects"] += 1
        if self._c_ejects is not None:
            self._c_ejects.inc()
        self.decisions.record(
            "eject_replica", replica=rep.index, reason=reason,
            generation=rep.generation,
        )
        # Fence BEFORE redriving: from this point every frame the
        # ejected worker already produced (or will produce behind a
        # partition) is stale — the redriven copies on survivors own
        # the streams, so partition-then-heal cannot double-serve.
        bump = getattr(rep, "bump_fence", None)
        if bump is not None:
            fence = bump(reason)
            if self.journal is not None:
                self.journal.append(
                    {"rec": "fence", "replica": rep.index, "fence": fence}
                )
        backoff = self._next_backoff(rep.index)
        self._relaunch_at[rep.index] = self._clock() + backoff
        self._redrive_from(rep.index, reason)

    def _pin_serving_baseline(
        self, probes: List[Any]
    ) -> List[Tuple[int, ...]]:
        """Decode every probe on every launched replica (idle at startup)
        and return the unanimous answers. Runs before the health thread
        starts, so plain blocking waits are fine."""
        live = [r for r in self.replicas if r.loop is not None]
        expected: List[Tuple[int, ...]] = []
        for probe in probes:
            per_probe: List[Tuple[int, Tuple[int, ...]]] = []
            for rep in live:
                attempt = rep.loop.submit(
                    list(probe.prompt), len(probe.expected), priority=-1,
                )
                try:
                    status, tokens, _info = attempt.result(
                        timeout=self.probe_timeout_s
                    )
                except TimeoutError:
                    raise RuntimeError(
                        f"replica {rep.index} did not answer a golden "
                        f"probe within {self.probe_timeout_s}s at startup; "
                        "cannot pin an integrity baseline"
                    )
                if status != "done":
                    raise RuntimeError(
                        f"replica {rep.index} failed a golden probe at "
                        f"startup (status={status!r}); cannot pin an "
                        "integrity baseline"
                    )
                per_probe.append((rep.index, tuple(tokens)))
            base = per_probe[0][1]
            diverged = [i for i, t in per_probe if t != base]
            if diverged:
                raise RuntimeError(
                    "replicas disagree on a golden probe before any "
                    f"traffic (replica {per_probe[0][0]} vs {diverged}); "
                    "no trustworthy integrity baseline exists"
                )
            expected.append(base)
        return expected

    # -- integrity sentinel --------------------------------------------------
    #
    # Runs on the health thread. Two detectors per tick: (1) the live
    # weight fingerprint each loop thread computes between turns, compared
    # against the value it pinned at launch — drift means the weights the
    # replica is SERVING are not the weights it started with; (2) golden
    # probes — pinned greedy (prompt -> tokens) pairs injected through the
    # normal admission lane at strict-lowest priority, one outstanding per
    # replica, outputs compared bit-for-bit against the reference. Either
    # detector firing quarantines the replica: pull it from service via
    # the eject machinery (redrive its in-flight work onto survivors,
    # relaunch with fresh weights from the factory after backoff).
    # Quarantine means "the replica answered WRONG" — a probe that errors,
    # expires, or times out is recorded but left to the health checks
    # above, which own "the replica didn't answer".

    def _sentinel_tick(self, now: float) -> None:
        if self.probe_interval_s <= 0 or self._probe_set is None:
            return
        for rep in self.replicas:
            loop = rep.loop
            if rep.state != "active" or loop is None:
                continue
            fp0 = loop.weight_fingerprint0
            fp = loop.weight_fingerprint
            if fp0 is not None and fp is not None and fp != fp0:
                if self.bus is not None:
                    self.bus.emit(
                        "integrity_weight_mismatch", replica=rep.index,
                        pinned=fp0, current=fp,
                        fleet={
                            str(r.index): r.loop.weight_fingerprint
                            for r in self.replicas if r.loop is not None
                        },
                    )
                self._quarantine(
                    rep,
                    f"weight fingerprint drift ({fp0!r} -> {fp!r})",
                    None,
                )
        if now < self._next_probe_at:
            return
        self._next_probe_at = now + self.probe_interval_s
        probe = self._probe_set[self._probe_idx % len(self._probe_set)]
        self._probe_idx += 1
        for rep in self.replicas:
            loop = rep.loop
            if rep.state != "active" or loop is None or loop.draining:
                continue
            with self._probe_lock:
                if rep.index in self._probe_inflight:
                    continue  # one outstanding probe per replica
                self._probe_inflight.add(rep.index)
            generation = rep.generation
            try:
                # Straight to the loop: probes must not consume fleet
                # admission budget or count as client traffic (frid
                # conservation, fault clocks). priority=-1 is below every
                # client request, so brownout-style shedding hits probes
                # first. A busy replica skips this round — probes yield.
                attempt = loop.submit(
                    list(probe.prompt), len(probe.expected), priority=-1,
                )
            except Exception:
                with self._probe_lock:
                    self._probe_inflight.discard(rep.index)
                continue
            threading.Thread(
                target=self._probe_pump,
                args=(rep, attempt, probe, generation),
                name=f"probe-{rep.index}",
                daemon=True,
            ).start()

    def _probe_pump(
        self, rep: Replica, attempt: FrontendRequest, probe: Any,
        generation: int,
    ) -> None:
        try:
            status, tokens, _info = attempt.result(
                timeout=self.probe_timeout_s
            )
        except TimeoutError:
            # Wedge/overload territory — the health loop's verdict, not
            # the sentinel's. Cancel so the probe can't complete into a
            # replaced inflight slot later.
            status, tokens = "timeout", []
            loop = rep.loop
            if loop is not None:
                try:
                    loop.cancel(attempt)
                except Exception:
                    pass
        finally:
            with self._probe_lock:
                self._probe_inflight.discard(rep.index)
        ok = status == "done" and list(tokens) == list(probe.expected)
        with self._probe_lock:
            self._last_probe_ok[rep.index] = ok
            self._last_probe_t[rep.index] = self._clock()
        with self._counters_lock:
            self.counters["probes"] += 1
            if not ok:
                self.counters["probe_failures"] += 1
        if self._c_probes is not None:
            self._c_probes.inc()
        if not ok and self._c_probe_fail is not None:
            self._c_probe_fail.inc()
        trace = getattr(attempt, "trace", None)
        trace_id = trace.trace_id if trace is not None else None
        if self.bus is not None:
            fields = {"trace_id": trace_id} if trace_id is not None else {}
            self.bus.emit(
                "integrity_probe", replica=rep.index, ok=ok, status=status,
                n_tokens=len(tokens), **fields,
            )
        if ok or self._stopping:
            return
        if status != "done":
            return  # didn't answer — the health loop owns that verdict
        if rep.state != "active" or rep.generation != generation:
            return  # already ejected/relaunched under this probe
        self._quarantine(rep, "probe divergence", trace_id)

    def _quarantine(
        self, rep: Replica, reason: str, trace_id: Optional[str]
    ) -> None:
        with self._counters_lock:
            self.counters["quarantines"] += 1
        if self._c_quarantines is not None:
            self._c_quarantines.inc()
        self.decisions.record(
            "quarantine", replica=rep.index, reason=reason,
            generation=rep.generation, trace_id=trace_id,
        )
        if self.bus is not None:
            self.bus.emit(
                "integrity_quarantine", replica=rep.index, reason=reason,
            )
        self._eject(rep, f"quarantine: {reason}")

    def drain(self, index: int, *, stop_timeout: float = 5.0) -> bool:
        """Administrative drain: stop routing to the replica, redrive its
        in-flight work to survivors, then stop its loop. The replica
        stays ``draining`` (not-ready on /readyz) until ``restore``."""
        rep = self.replicas[index]
        rep.drain()
        self._redrive_from(index, "drain")
        return rep.stop(timeout=stop_timeout)

    def restore(self, index: int) -> None:
        """Bring a drained/ejected replica back with a fresh engine (the
        second half of a rolling restart) and reset its backoff."""
        rep = self.replicas[index]
        rep.relaunch()
        self._count_relaunch(index)
        self._backoff.pop(index, None)
        self._relaunch_at.pop(index, None)

    # -- fleet drain (graceful shutdown) -------------------------------------

    def begin_drain(self) -> None:
        """Fleet-level graceful shutdown gate (serve.py's SIGTERM path):
        stop admitting — the gateway 503s new submissions — while
        in-flight requests run to their terminals on their replicas;
        /readyz flips not-ready so load balancers stop sending."""
        self._draining = True
        self.decisions.record("fleet_drain")

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def active_requests(self) -> int:
        """Router-level in-flight count (the graceful-drain wait
        condition; mirrors EngineLoop.active_requests)."""
        with self._live_lock:
            return sum(
                1
                for r in self._live.values()
                if r.status not in TERMINAL_STATUSES
            )

    # -- rolling weight upgrades ---------------------------------------------

    def upgrade_replica(
        self,
        index: int,
        update: Any = None,
        *,
        stop_timeout: float = 5.0,
    ) -> bool:
        """One step of a rolling upgrade: drain replica ``index``
        (in-flight work redrives to survivors), apply ``update`` (a new
        engine factory in-process; a worker-spec patch such as
        ``{"model_path": ...}`` in process mode), relaunch HELD, and run
        the pinned golden probes against the fresh engine BEFORE it
        takes any traffic. Bit-exact probes promote it to active; any
        divergence, probe error, or crash inside the vetting window
        refuses the upgrade — the old weights are restored, re-vetted,
        and reactivated, and clients only ever saw the vetted fleet.

        Returns True when the upgrade took traffic, False when it was
        refused (the replica is back on its previous weights — or
        ejected into the health loop's backoff if even the rollback
        engine cannot come up)."""
        rep = self.replicas[index]
        old = rep.update_snapshot()
        with self._counters_lock:
            self.counters["upgrades"] += 1
        if self.bus is not None:
            self.bus.emit(
                "upgrade_start", replica=index, generation=rep.generation
            )
        self.drain(index, stop_timeout=stop_timeout)
        rep.apply_update(update)
        ok, detail = self._relaunch_vetted(rep)
        if ok:
            rep.activate("upgrade")
            self._count_relaunch(index)
            self._backoff.pop(index, None)
            self._relaunch_at.pop(index, None)
            if self.bus is not None:
                self.bus.emit(
                    "upgrade_vetted", replica=index, detail=detail,
                    generation=rep.generation,
                )
            return True
        with self._counters_lock:
            self.counters["upgrades_refused"] += 1
        self.decisions.record(
            "upgrade_refused", replica=index, reason=detail
        )
        if self.bus is not None:
            self.bus.emit("upgrade_refused", replica=index, reason=detail)
        rep.apply_update(old, replace=True)
        ok, detail = self._relaunch_vetted(rep)
        if ok:
            rep.activate("upgrade rollback")
            self._count_relaunch(index)
            self._backoff.pop(index, None)
            self._relaunch_at.pop(index, None)
        else:
            # Even the previous weights cannot come up vetted — hand the
            # replica to the health loop's eject/backoff machinery.
            rep.eject(f"upgrade rollback failed: {detail}")
            self._relaunch_at[index] = (
                self._clock() + self._next_backoff(index)
            )
        if self.bus is not None:
            self.bus.emit(
                "upgrade_rolled_back", replica=index, restored=ok,
                detail=detail,
            )
        return False

    def rolling_upgrade(
        self, updates: Any = None, *, stop_timeout: float = 5.0
    ) -> Dict[int, bool]:
        """Upgrade the fleet one replica at a time (i is fully vetted
        and back in traffic — or rolled back — before i+1 drains).
        ``updates``: one update for every replica, or a dict keyed by
        replica index (missing keys relaunch-as-is)."""
        results: Dict[int, bool] = {}
        for rep in self.replicas:
            up = (
                updates.get(rep.index)
                if isinstance(updates, dict)
                else updates
            )
            results[rep.index] = self.upgrade_replica(
                rep.index, up, stop_timeout=stop_timeout
            )
        return results

    def _relaunch_vetted(self, rep: Replica) -> Tuple[bool, str]:
        """Relaunch ``rep`` held out of traffic and decode every pinned
        probe on it, requiring bit-exact agreement with the fleet
        baseline. With no pinned set (sentinel off and no probe_set
        given) the launch is accepted unvetted — stated in the detail
        so the event stream records the weaker guarantee."""
        try:
            rep.relaunch(stop_timeout=0.5, hold=True)
        except Exception as e:
            return False, f"relaunch failed: {e!r}"
        probes = self._probe_set or []
        if not probes:
            return True, "unvetted (no probe set pinned)"
        for n, probe in enumerate(probes):
            try:
                attempt = rep.loop.submit(
                    list(probe.prompt), len(probe.expected), priority=-1
                )
                status, tokens, _info = attempt.result(
                    timeout=self.probe_timeout_s
                )
            except Exception as e:
                return False, f"vetting probe {n} failed: {e!r}"
            if status != "done":
                return False, f"vetting probe {n} status={status!r}"
            if list(tokens) != list(probe.expected):
                return False, (
                    f"vetting probe {n} diverged from the pinned reference"
                )
        return True, f"{len(probes)} probes bit-exact"

    def _redrive_from(self, index: int, reason: str) -> None:
        """Fail over every live request currently on ``index``. Races
        benignly with the pumps doing the same from the terminal side:
        both paths take rreq._lock, and whoever moves ``_attempt`` first
        wins (the loser sees the mismatch / the changed replica)."""
        for rreq in self._live_snapshot():
            with rreq._lock:
                if rreq.status in TERMINAL_STATUSES:
                    continue
                if rreq.replica != index or rreq._attempt is None:
                    continue
                if rreq.cancel_requested or self._stopping:
                    continue
                if rreq.redrives >= self.redrive_max:
                    self._finish_locked(
                        rreq, "error",
                        {"reason": f"redrive budget exhausted after {reason}"},
                    )
                    continue
                self._redrive_locked(rreq, index, reason)

    def _update_brownout(self) -> None:
        if self.brownout_min_healthy_frac <= 0:
            return
        total = len(self.replicas)
        healthy = sum(1 for r in self.replicas if r.accepting)
        want = (healthy / total) < self.brownout_min_healthy_frac
        if want == self.brownout_active:
            return
        self.brownout_active = want
        if self._g_brownout is not None:
            self._g_brownout.set(1.0 if want else 0.0)
        if self.bus is not None:
            self.bus.emit(
                "brownout", active=want, healthy=healthy, total=total
            )

    def _on_replica_state(self, rep: Replica, state: str, reason: str) -> None:
        g = self._g_state.get(rep.index)
        if g is not None:
            g.set(REPLICA_STATE_VALUES[state])

    # -- gateway surface (parity with EngineLoop) ----------------------------

    def last_turn_age_s(self) -> float:
        """Fleet liveness: the FRESHEST active replica's turn age — one
        healthy replica keeps /healthz green (capacity is /readyz's and
        brownout's business, not liveness's)."""
        ages = [
            rep.loop.last_turn_age_s()
            for rep in self.replicas
            if rep.state == "active" and rep.loop is not None
        ]
        if not ages:
            return max(0.0, self._clock() - self._started)
        return min(ages)

    def _integrity_snapshot(self) -> Dict[str, Any]:
        """Sentinel state for /readyz and /debug/engine: per-replica last
        probe verdict + age, quarantine count, fingerprint pair."""
        now = self._clock()
        with self._probe_lock:
            ok = dict(self._last_probe_ok)
            at = dict(self._last_probe_t)
        probes: Dict[str, Any] = {}
        for rep in self.replicas:
            rec: Dict[str, Any] = {"ok": ok.get(rep.index)}
            t = at.get(rep.index)
            rec["age_s"] = round(now - t, 6) if t is not None else None
            loop = rep.loop
            if loop is not None and loop.weight_fingerprint0 is not None:
                rec["fingerprint_pinned"] = loop.weight_fingerprint0
                rec["fingerprint"] = loop.weight_fingerprint
            probes[str(rep.index)] = rec
        with self._counters_lock:
            n_quar = self.counters["quarantines"]
            n_probes = self.counters["probes"]
            n_fail = self.counters["probe_failures"]
        return {
            "enabled": self.probe_interval_s > 0,
            "probes_run": n_probes,
            "probes_failed": n_fail,
            "quarantines": n_quar,
            "replicas": probes,
        }

    def readiness(self) -> Dict[str, Any]:
        per = {rep.index: rep.state for rep in self.replicas}
        ready = (
            any(rep.accepting for rep in self.replicas)
            and not self._draining
        )
        out = {
            "ready": ready,
            "replicas": per,
            "brownout": self.brownout_active,
            "draining": self._draining,
        }
        if self.probe_interval_s > 0:
            out["integrity"] = self._integrity_snapshot()
        return out

    def metrics(self) -> Dict[str, float]:
        """Aggregated counter snapshot (the /metrics extra-gauges path):
        fleet counters + per-replica loop counters summed + fleet
        admission, mirroring EngineLoop.metrics keys so /healthz and
        existing dashboards keep working."""
        with self._counters_lock:
            out: Dict[str, float] = dict(self.counters)
        agg: Dict[str, float] = {}
        active = 0
        for rep in self.replicas:
            loop = rep.loop
            if loop is None:
                continue
            if rep.accepting:
                active += 1
            for k, v in loop.metrics().items():
                if k.startswith("admission_"):
                    continue  # per-replica budgets; fleet budget below
                agg[k] = agg.get(k, 0.0) + v
        for k in ("active_requests", "tokens_streamed"):
            if k in agg:
                out[k] = agg[k]
        for k, v in agg.items():
            if k.startswith("engine_"):
                out[k] = v
        # "_count" not "_total": these are gauges and the exposition linter
        # reserves the _total suffix for counters.
        out["replicas_count"] = len(self.replicas)
        out["replicas_active"] = active
        out["brownout_active"] = 1.0 if self.brownout_active else 0.0
        if self.admission is not None:
            for k, v in self.admission.snapshot().items():
                out[f"admission_{k}"] = v
        return out

    def render_metrics(self, extra_gauges: Optional[Dict[str, float]] = None) -> str:
        """One merged exposition: the fleet registry leads, each
        replica's labeled registry follows (see metrics.render_merged)."""
        regs = []
        if self.registry is not None:
            regs.append(self.registry)
        regs.extend(rep.registry for rep in self.replicas)
        if not regs:
            from pretraining_llm_tpu.observability.export import (
                prometheus_lines,
            )
            return prometheus_lines(
                extra_gauges or {}, prefix="pllm_serving_"
            )
        return render_merged(regs, extra_gauges)

    def debug_requests(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for rep in self.replicas:
            if rep.loop is None:
                continue
            for rec in rep.loop.debug_requests():
                rec["replica"] = rep.index
                out.append(rec)
        for rreq in self._live_snapshot():
            out.append({
                "frid": rreq.frid,
                "status": rreq.status,
                "replica": rreq.replica,
                "redrives": rreq.redrives,
                "n_tokens": len(rreq.tokens),
                "priority": rreq.priority,
                "fleet": True,
            })
        return out

    def debug_engine(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "fleet": {
                "replicas": [rep.debug_snapshot() for rep in self.replicas],
                "brownout_active": self.brownout_active,
                "live_requests": len(self._live_snapshot()),
                "counters": dict(self.counters),
                "decisions": {
                    "counts": self.decisions.counts_snapshot(),
                    "tail": self.decisions.tail(16),
                },
            },
        }
        if self.admission is not None:
            out["fleet"]["admission"] = self.admission.snapshot()
        if self.probe_interval_s > 0:
            out["fleet"]["integrity"] = self._integrity_snapshot()
        out["replicas"] = {
            str(rep.index): rep.loop.debug_engine()
            for rep in self.replicas
            if rep.loop is not None and rep.alive
        }
        return out

    def fleet_health(self) -> Dict[str, Any]:
        """One aggregated fleet health snapshot (the GET /slo ``fleet``
        section): per-replica ``health_pull`` gauges — KV pool
        occupancy, queue/admission depths, lease/fence generations,
        KV-migration counters, device HBM watermarks — plus fleet-wide
        sums, and the worker-side latency sketches merged order-
        invariantly (sketches.DigestSketch.merge_all) as a cross-check
        against the bus-fed SLO distributions. In-process replicas
        answer locally; process/attached workers answer over the wire
        (proto >= 4), older peers degrade to their cached health
        snapshot flagged ``proto_fallback``."""
        replicas: Dict[str, Any] = {}
        sums: Dict[str, float] = {}
        worker_sketches: Dict[str, List[Any]] = {}
        hbm_peak = 0.0
        active = 0
        max_fence = 0
        for rep in self.replicas:
            pull = getattr(rep, "health_pull", None)
            snap = pull() if pull is not None else rep.debug_snapshot()
            replicas[str(rep.index)] = snap
            if rep.accepting:
                active += 1
            max_fence = max(max_fence, int(snap.get("fence") or 0))
            for key, val in (snap.get("gauges") or {}).items():
                if isinstance(val, (int, float)):
                    sums[key] = sums.get(key, 0.0) + val
            for dev in (snap.get("hbm") or {}).values():
                hbm_peak = max(hbm_peak, float(dev.get("bytes_in_use", 0.0)))
            for metric, payload in (snap.get("sketches") or {}).items():
                worker_sketches.setdefault(metric, []).append(payload)
        with self._counters_lock:
            counters = dict(self.counters)
        fleet: Dict[str, Any] = {
            "replicas_total": len(self.replicas),
            "replicas_active": active,
            "brownout_active": self.brownout_active,
            "draining": self._draining,
            "max_fence": max_fence,
            "gauges": sums,
            "counters": counters,
        }
        if hbm_peak:
            fleet["hbm_peak_bytes_in_use"] = hbm_peak
        if worker_sketches:
            from pretraining_llm_tpu.observability.sketches import (
                DigestSketch,
            )

            fleet["worker_sketches"] = {
                metric: DigestSketch.merge_all(
                    DigestSketch.from_dict(p) for p in payloads
                ).summary()
                for metric, payloads in sorted(worker_sketches.items())
            }
        return {"replicas": replicas, "fleet": fleet}

    def slo_snapshot(self) -> Dict[str, Any]:
        """The GET /slo body behind a fleet router: the SLO engine's
        distributions/budgets/alerts plus the aggregated fleet health."""
        out: Dict[str, Any] = (
            self.slo.snapshot() if self.slo is not None else {}
        )
        out["fleet_health"] = self.fleet_health()
        return out
