"""Length-prefixed JSON framing for the worker socket protocol.

One frame = 4-byte big-endian payload length + UTF-8 JSON object. Both
sides of the worker protocol (frontend/worker.py serving, frontend/
remote_replica.py consuming) speak exactly this — the framing layer
knows nothing about ops, so it can be unit-tested without JAX or a
subprocess.

Failure surface is deliberately small: every way the peer can vanish
(EOF mid-length, EOF mid-payload, ECONNRESET, EPIPE, a closed fd)
raises ``ConnectionLost`` so callers have a single except clause for
"the other process is gone"; a frame that parses but is not a JSON
object, or whose declared length exceeds ``MAX_FRAME_BYTES``, raises
``ProtocolError`` — that peer is speaking garbage, not dying, and the
two must not be conflated because only the first is redrivable.
"""

from __future__ import annotations

import json
import select
import socket
import struct
import time
from typing import Any, Dict, Optional

# Protocol revision spoken by this build. Exchanged in the hello (each
# side sends its own; the reply echoes the worker's), so new frame kinds
# are NEGOTIABLE: a sender only emits a frame the peer's advertised
# version understands, instead of crashing an old peer on an unknown op.
# A peer whose hello carries no ``proto`` field is version 1.
#   1  original op set (hello/submit/cancel/.../stall + token/end/event)
#   2  adds the batched span-export frame ({"op": "spans", ...}) and
#      clock samples in hello/health replies
#   3  adds KV-page migration: the ``kv_fetch`` request (serialize a
#      cached prefix chain) and ``kv_page`` page-stream frames
#      (frontend/kv_transfer.py owns the payload layout); pages ride
#      base64-encoded inside the JSON frame and carry the same ``g``
#      fence stamp as every other worker frame, so stale-generation
#      pages are dropped by the existing fence filter
#   4  adds the ``health_pull`` request: like ``health`` (it doubles as
#      a lease heartbeat + clock sample the same way) but the reply also
#      carries worker-side gauges — engine row/KV-pool occupancy, queue
#      depths, KV-migration counters, device HBM watermarks — and the
#      worker's rolling-window latency sketches serialized via
#      observability/sketches.py, so the router can aggregate one fleet
#      health snapshot (GET /slo) without a debug_engine round-trip per
#      replica. A v<4 peer never sees the op; the router falls back to
#      the fields the plain health reply already carries.
PROTO_VERSION = 4

# A frame is one JSON op or one token batch — 64 MiB means a corrupt
# length prefix fails fast instead of attempting a multi-GB recv.
MAX_FRAME_BYTES = 64 * 1024 * 1024

# A peer that will not drain one frame's worth of bytes in this long is
# as gone as one that sent RST: its kernel buffer is full and nothing
# is reading (blackholed route, wedged process). Sends past the
# deadline raise ConnectionLost so the slow-peer case converges on the
# same redrive path as outright death.
SEND_DEADLINE_S = 30.0

_LEN = struct.Struct(">I")

# Per-call non-blocking send (Linux): the socket itself must stay
# blocking — it is shared with a reader thread, and both settimeout and
# setblocking are socket-wide. Elsewhere the flag degrades to 0 and the
# send falls back to kernel blocking semantics.
_MSG_DONTWAIT = getattr(socket, "MSG_DONTWAIT", 0)


class ConnectionLost(Exception):
    """The peer process went away (EOF / reset / closed socket)."""


class ProtocolError(Exception):
    """The peer sent bytes that do not decode as a protocol frame."""


def _json_default(obj: Any) -> Any:
    """Last-resort encoder for numpy scalars and other debug payload
    values; token ids and counters are native ints before they get
    here, so this only runs for debug_engine-style snapshots."""
    for cast in (int, float):
        try:
            return cast(obj)
        except (TypeError, ValueError):
            continue
    return str(obj)


def encode_frame(payload: Dict[str, Any]) -> bytes:
    """Serialize one frame (length prefix + JSON) to bytes."""
    body = json.dumps(
        payload, separators=(",", ":"), default=_json_default
    ).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {len(body)} bytes exceeds MAX_FRAME_BYTES"
        )
    return _LEN.pack(len(body)) + body


def send_frame(
    sock: socket.socket,
    payload: Dict[str, Any],
    deadline_s: Optional[float] = SEND_DEADLINE_S,
) -> None:
    """Send one frame; any OS-level send failure means the peer died.

    The send loop is explicit over ``sendall`` boundaries: each pass
    waits (via select) for the socket to accept bytes, bounded by a
    per-FRAME deadline, then writes one partial chunk with
    ``MSG_DONTWAIT`` — select only promises SOME buffer space, and a
    plain blocking ``send`` of the large remainder would sleep in the
    kernel until ALL of it fit, hanging the caller exactly like the
    ``sendall`` this loop replaces. A peer that stops draining (full
    kernel buffer behind a blackholed route) therefore surfaces as
    ``ConnectionLost`` within ``deadline_s``. select is used rather
    than ``settimeout``/``setblocking`` because the socket is shared
    with a reader thread and both are socket-wide.
    """
    data = encode_frame(payload)
    deadline = (
        time.monotonic() + deadline_s if deadline_s is not None else None
    )
    sent = 0
    try:
        while sent < len(data):
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionLost(
                        f"send deadline exceeded: peer accepted only "
                        f"{sent}/{len(data)} bytes in {deadline_s}s"
                    )
                _, writable, _ = select.select([], [sock], [], remaining)
            else:
                _, writable, _ = select.select([], [sock], [])
            if not writable:
                raise ConnectionLost(
                    f"send deadline exceeded: peer accepted only "
                    f"{sent}/{len(data)} bytes in {deadline_s}s"
                )
            try:
                n = sock.send(data[sent:], _MSG_DONTWAIT)
            except BlockingIOError:
                # The buffer filled between select and send; wait again.
                continue
            if n == 0:
                raise ConnectionLost("send returned 0 bytes: peer gone")
            sent += n
    except (OSError, ValueError) as e:  # ValueError: fd closed under us
        raise ConnectionLost(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (OSError, ValueError) as e:
            raise ConnectionLost(f"recv failed: {e}") from e
        if not chunk:
            raise ConnectionLost(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Dict[str, Any]:
    """Receive one frame; blocks until a full frame or the peer dies."""
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"declared frame length {length} exceeds MAX_FRAME_BYTES"
        )
    body = _recv_exact(sock, length)
    try:
        payload = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ProtocolError(f"frame payload is not JSON: {e}") from e
    if not isinstance(payload, dict):
        raise ProtocolError(
            f"frame payload must be a JSON object, got "
            f"{type(payload).__name__}"
        )
    return payload
