"""Out-of-process serving worker: one Replica behind a socket.

``python -m pretraining_llm_tpu.frontend.worker --spec-json '...'``
owns exactly one :class:`frontend.replica.Replica` (engine factory +
admission + per-replica registry — the same internals the in-process
fleet uses) and serves it over the length-prefixed JSON protocol in
``frontend/wire.py``. The parent side is
:class:`frontend.remote_replica.RemoteReplica`; together they move the
replica fault domain across a real process boundary so a kill -9, a
wedged loop, or a dropped connection exercises the SAME eject/redrive
machinery the in-process drills do.

Startup handshake: the worker binds an ephemeral port and prints one
line — ``{"worker": {"port": ..., "pid": ...}}`` — to stdout BEFORE the
slow engine build, then builds the engine and starts accepting. The
parent connects immediately (the connect lands in the listen backlog)
and sends ``hello``; the reply arrives once the engine is up, so the
parent's hello timeout is the engine-build budget.

Client protocol (every request frame carries ``id``; replies echo it):

==============  ======================================================
op              semantics
==============  ======================================================
hello           engine construction constants (validate_request inputs)
submit          lane="replica" -> Replica.submit (state gate + fault
                clock); lane="loop" -> EngineLoop.submit directly (the
                sentinel/vetting path, priority -1, no fault clock) —
                reply carries rid; token/end frames stream after it
cancel          EngineLoop.cancel by rid
drain           Replica.drain() (loop.begin_drain + state)
health          running/draining/active_requests/last_turn_age_s/...
health_pull     the health reply PLUS worker-side gauges (engine row/
                KV-pool occupancy, queue + admission depths, KV-
                migration counters, stale-frame drops, device HBM
                watermarks) and the worker's rolling-window latency
                sketches (observability/sketches.py, serialized) — the
                router's fleet health snapshot aggregates these. Doubles
                as a lease heartbeat exactly like ``health``. proto >= 4
                peers only (the parent gates sends).
metrics         EngineLoop.metrics() snapshot
debug_requests  EngineLoop.debug_requests()
debug_engine    EngineLoop.debug_engine()
probe_set       build_probe_set on the worker's own params (serialized
                prompts/expected) — runs on a side thread so health
                polls stay live during the reference generates
kv_fetch        serialize the longest cached KV chain for ``prompt``
                (frontend/kv_transfer.py): the pages stream back as
                unsolicited ``kv_page`` frames keyed by ``fetch``=id,
                then the reply summarizes pages/bytes/frames. Runs on a
                side thread (device pulls per page) so health polls stay
                live. proto >= 3 peers only (the parent gates sends).
kv_page         one inbound frame of a page PUSH (router -> this worker,
                the decode tier's receive side): frames accumulate per
                ``xfer`` id; the final frame (the one carrying ``id``)
                triggers loop-thread adoption behind the prefix-cache
                publish path and the summary reply. Frames whose fence
                generation predates this worker's current fence are
                dropped — stale pages from before an eject never enter
                the pool.
shutdown        reply ok, then loop.stop() and exit 0
stall           NO reply, stop reading frames (fault drill: the parent
                sees RPC timeouts from a process that is still alive)
==============  ======================================================

Unsolicited frames: ``{"token": rid, "t": tok}`` and ``{"end": rid,
"status": ..., "info": ...}`` per streamed request, and ``{"op":
"event", ...}`` forwarding the replica's bus events to the parent
(``replica_state`` is filtered out — the parent's state machine is
authoritative for fleet lifecycle events).

Robustness hooks baked into the worker itself:

- orphan detection: a reader thread blocks on stdin (the parent holds
  the write end of the pipe and never writes); EOF means the parent
  died, so the worker drains, waits briefly for in-flight work, and
  exits — killed routers never leak workers. SIGTERM takes the same
  path.
- multi-host attach mode: ``--listen host:port --token <secret>``
  serves a PRE-SPAWNED worker over TCP. The router connects by address
  instead of spawning; the first frame on every connection must be a
  ``hello`` carrying the shared token (the reply carries the engine
  weight fingerprint, so the router can refuse a worker serving the
  wrong weights). There is no stdin pipe to watch, so the orphan watch
  is replaced by a **heartbeat lease**: every frame from the router
  (health polls are the heartbeat carrier) refreshes the lease; if the
  router is unreachable for the ``lease_s`` the hello granted, the
  worker stops admitting, cancels its in-flight work (the router has
  redriven it elsewhere by now — serving it further risks double
  serve), and PARKS listening for the next attach instead of exiting.
- fencing: the hello (and every health heartbeat) carries the router's
  monotonically increasing fence generation for this replica; the
  worker stamps the generation it held AT SUBMIT TIME onto every
  stream frame (``"g"``) and the current generation onto replies and
  events. After a partition-then-heal, frames from before the router
  ejected this replica carry a stale generation and the parent drops
  them — a healed worker can never stream duplicate tokens into a
  request a survivor already answered.
- ``kill_after_submits: N`` in the spec: SIGKILL *itself* right after
  acknowledging the Nth wire submit (either lane) — this is how the
  mid-upgrade-kill drill crashes the upgrading worker inside its
  probe-vetting window, deterministically.
- ``corrupt_weights: true`` in the spec: the engine factory flips the
  sign of the largest weight leaf after build (same mutation as the
  ``corrupt_weights`` serving fault) — a checkpoint that serves wrong
  answers without crashing, for refused-upgrade drills.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time
from typing import Any, Dict, Optional

from ..observability.sketches import WindowedSketch
from ..observability.slo import LATENCY_METRICS, TERMINAL_KINDS
from ..observability.spans import SpanRecorder
from ..observability.tracing import Tracer
from .wire import (
    PROTO_VERSION,
    ConnectionLost,
    ProtocolError,
    recv_frame,
    send_frame,
)

_ORPHAN_DRAIN_S = 10.0


def build_engine_factory(spec: Dict[str, Any]):
    """Engine factory from a worker spec. Two weight sources:

    - ``model_path``: load a checkpoint exactly like scripts/serve.py
      (load_model_for_inference -> cast_params_for_inference ->
      optional quantize_params_for_serving).
    - ``preset`` + ``init_seed``: deterministic random init, the form
      every CPU test and CI gate uses (both sides of a fleet init the
      same params from the same seed, so cross-replica redrive
      bit-identity holds without any checkpoint on disk).

    Imports live here, not at module top: argparse errors and wire unit
    tests must not pay (or require) the JAX import.
    """
    import dataclasses

    import jax

    from ..config import get_preset
    from ..generation.serving import ServingEngine

    model_path = str(spec.get("model_path") or "")
    if model_path:
        from ..generation.generate import (
            cast_params_for_inference,
            load_model_for_inference,
        )

        params, full_cfg = load_model_for_inference(
            model_path, use_ema=bool(spec.get("ema", False))
        )
        cfg = full_cfg.model
        params = cast_params_for_inference(params, cfg)
    else:
        from ..models import transformer

        cfg = get_preset(str(spec.get("preset", "tiny"))).model
        overrides = dict(spec.get("model_overrides") or {})
        if overrides:
            cfg = dataclasses.replace(cfg, **overrides)
        params = transformer.init_params(
            cfg, jax.random.key(int(spec.get("init_seed", 0)))
        )

    quantize = str(spec.get("quantize") or "none")
    if quantize != "none":
        from ..models import quantize as quantize_mod

        params = quantize_mod.quantize_params_for_serving(params, cfg)

    if spec.get("corrupt_weights"):
        from ..resilience.faults import ServingFaultInjector

        holder = type("_ParamsHolder", (), {})()
        holder.params = params
        ServingFaultInjector._fire_corrupt_weights(holder)
        params = holder.params

    engine_kw = dict(spec.get("engine") or {})
    engine_kw.setdefault("temperature", 0.0)
    if quantize != "none":
        engine_kw.setdefault("quantize", quantize)

    def factory():
        return ServingEngine(params, cfg, **engine_kw)

    return factory


class _ForwardBus:
    """Bus facade handed to the worker's Replica: forwards events over
    the wire instead of writing JSONL. ``replica_state`` is dropped
    (the parent Replica state machine emits those); everything else is
    buffered until a client is connected, then streamed."""

    def __init__(self, worker: "WorkerServer") -> None:
        self._worker = worker

    def emit(self, kind: str, step: int = 0, **fields: Any) -> None:
        if kind == "replica_state":
            return
        self._worker.send_event(kind, step, fields)

    def close(self) -> None:  # Replica's _TaggedBus calls this; no-op
        pass


class WorkerServer:
    def __init__(self, spec: Dict[str, Any]) -> None:
        self.spec = spec
        self.index = int(spec.get("index", 0))
        self._kill_after = int(spec.get("kill_after_submits", 0))
        self._wire_submits = 0
        self._shutdown = threading.Event()
        self._conn: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._event_buf: list = []
        # wrid -> (attempt, fence generation held when it was submitted):
        # stream frames carry the SUBMIT-time generation, so work from
        # before an eject stays distinguishable after a heal/re-attach.
        self._attempts: Dict[int, Any] = {}
        self.replica = None  # set in start_replica()

        # Cross-process tracing: the worker records the SAME engine span
        # set an in-process replica would (queue/prefill/window/...) into
        # a local recorder, then ships them to the router in batched
        # ``spans`` frames after each stream ends. sample=0.0 means the
        # worker NEVER originates a trace of its own — it only joins
        # traces the router propagates via ``traceparent`` on submit
        # (begin_request honors the inbound sampled flag verbatim). Each
        # process has its own perf_counter epoch; the parent's clock
        # estimator maps these timestamps into its own timeline.
        self.recorder = SpanRecorder(
            max_events=int(spec.get("trace_buffer", 20000))
        )
        self.tracer = Tracer(self.recorder, sample=0.0, seed=self.index)
        # Wire protocol version of the CURRENTLY connected peer (learned
        # from its hello; absent field = v1). Spans frames are only sent
        # to peers that advertised v2+.
        self._peer_proto = 1

        # Fencing + lease state (attach mode; inert for spawned children
        # until a hello grants a lease).
        self._token = str(spec.get("token") or "")
        # Disaggregation role ("prefill"|"decode"|"both"); advertised in
        # the hello so the router can place traffic without config skew.
        self.role = str(spec.get("role") or "both")
        # In-flight inbound kv-page transfers: xfer id -> frame list.
        # Cleared on every (re)connect — a half-received transfer from a
        # dead connection must never complete against a new sender.
        self._kv_rx: Dict[Any, list] = {}
        self._kv_stale_frames = 0
        # Worker-local rolling latency sketches, fed off the SAME event
        # stream this worker forwards to the router (send_event). The
        # router's SLO engine sketches the forwarded events too; these
        # local copies are the worker's own ground truth, shipped inside
        # health_pull replies so a router that attached mid-run (or
        # missed forwards across a partition) still aggregates a
        # complete fleet view.
        self._lat_sketches: Dict[str, WindowedSketch] = {
            m: WindowedSketch(window_s=60.0, buckets=6)
            for m in LATENCY_METRICS
        }
        self._fence = 0
        self._lease_s = 0.0
        self._last_contact = time.monotonic()
        self._lease_expiries = 0
        self.attached = bool(spec.get("listen"))

        listen = str(spec.get("listen") or "")
        if listen:
            host, _, port_s = listen.rpartition(":")
            if not port_s:
                raise ValueError(
                    f"--listen must be host:port, got {listen!r}"
                )
            self._listener = socket.create_server(
                (host or "127.0.0.1", int(port_s))
            )
        else:
            host = str(spec.get("host", "127.0.0.1"))
            self._listener = socket.create_server((host, 0))
        self._listener.listen(4)
        self.port = int(self._listener.getsockname()[1])

    # ---- lifecycle --------------------------------------------------

    def announce(self) -> None:
        sys.stdout.write(
            json.dumps({"worker": {"port": self.port, "pid": os.getpid()}})
            + "\n"
        )
        sys.stdout.flush()

    def start_replica(self) -> None:
        from ..frontend.admission import AdmissionController
        from ..frontend.replica import Replica

        faults = None
        fault_spec = str(self.spec.get("serving_faults") or "")
        if fault_spec:
            from ..resilience.faults import ServingFaultInjector

            faults = ServingFaultInjector(fault_spec, bus=_ForwardBus(self))

        admission_kw = dict(self.spec.get("admission") or {})
        loop_kw = dict(self.spec.get("loop") or {})

        def make_admission(reg, scope=""):
            return AdmissionController(
                registry=reg, scope=scope, **admission_kw
            )

        self.replica = Replica(
            self.index,
            build_engine_factory(self.spec),
            bus=_ForwardBus(self),
            tracer=None,
            registry_labels=dict(self.spec.get("registry_labels") or {}),
            admission_factory=make_admission,
            fault_injector=faults,
            loop_kwargs=loop_kw,
            role=self.role,
        )
        self.replica.start()

    def start_orphan_watch(self) -> None:
        threading.Thread(
            target=self._watch_parent, name="worker-orphan", daemon=True
        ).start()

    def _watch_parent(self) -> None:
        try:
            # The parent holds our stdin pipe open and never writes;
            # read() returning means the parent process is gone.
            sys.stdin.buffer.read()
        except Exception:
            pass
        self._drain_and_exit("orphaned (parent pipe closed)")

    def start_lease_watch(self) -> None:
        threading.Thread(
            target=self._watch_lease, name="worker-lease", daemon=True
        ).start()

    def _watch_lease(self) -> None:
        """Attach-mode replacement for the orphan watch: a router that
        stays unreachable for a full lease term has either died or
        already redriven our work onto survivors — keep serving it and
        a heal would double-serve. Expire the lease: drop the
        connection (the serve loop cancels every in-flight attempt,
        freeing decode slots and KV) and park listening for the next
        attach instead of exiting."""
        while not self._shutdown.wait(0.05):
            lease = self._lease_s
            if lease <= 0:
                continue
            with self._wlock:
                conn = self._conn
            if conn is None:
                continue
            age = time.monotonic() - self._last_contact
            if age <= lease:
                continue
            self._lease_expiries += 1
            sys.stderr.write(
                f"[worker {self.index}] lease expired (router silent "
                f"{age:.2f}s > lease {lease}s); draining and parking\n"
            )
            sys.stderr.flush()
            with self._wlock:
                if self._conn is conn:
                    self._conn = None
            try:
                # Wakes _serve_conn's blocking recv: its teardown path
                # cancels the attempts and returns to the accept loop.
                conn.close()
            except OSError:
                pass

    def _drain_and_exit(self, reason: str) -> None:
        try:
            sys.stderr.write(f"[worker {self.index}] {reason}; draining\n")
            sys.stderr.flush()
            rep = self.replica
            if rep is not None and rep.loop is not None:
                rep.loop.begin_drain()
                deadline = time.monotonic() + _ORPHAN_DRAIN_S
                while (
                    time.monotonic() < deadline
                    and rep.loop.active_requests > 0
                ):
                    time.sleep(0.05)
                rep.stop(timeout=5.0)
        finally:
            os._exit(0)

    # ---- wire output (single writer lock; drop when unconnected) ----

    def _send(self, payload: Dict[str, Any], g: Optional[int] = None) -> None:
        # Every outbound frame is stamped with a fence generation; the
        # parent drops (and counts) frames whose generation predates its
        # last eject of this replica. Stream frames pass the SUBMIT-time
        # generation; everything else carries the current one.
        payload = dict(payload)
        payload["g"] = self._fence if g is None else g
        with self._wlock:
            conn = self._conn
            if conn is None:
                return
            try:
                send_frame(conn, payload)
            except ConnectionLost:
                pass  # reader side notices and tears the connection down

    def send_event(self, kind: str, step: int, fields: Dict[str, Any]) -> None:
        if kind in TERMINAL_KINDS:
            for metric in LATENCY_METRICS:
                val = fields.get(metric)
                if isinstance(val, (int, float)):
                    self._lat_sketches[metric].observe(float(val))
        frame = {
            "op": "event", "kind": kind, "step": step, "fields": fields,
            "g": self._fence,
        }
        with self._wlock:
            conn = self._conn
            if conn is None:
                if len(self._event_buf) < 4096:
                    self._event_buf.append(frame)
                return
            try:
                send_frame(conn, frame)
            except ConnectionLost:
                pass

    # ---- serving ----------------------------------------------------

    def serve_forever(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._peer_proto = 1  # until this connection's hello says more
            self._kv_rx.clear()
            with self._wlock:
                self._conn = conn
                buffered, self._event_buf = self._event_buf, []
            for frame in buffered:
                self._send(frame)
            self._last_contact = time.monotonic()
            try:
                self._serve_conn(conn)
            except (ConnectionLost, ProtocolError):
                pass
            finally:
                with self._wlock:
                    self._conn = None
                try:
                    conn.close()
                except OSError:
                    pass
                # The client is gone: its streams have no reader, and the
                # parent will redrive them elsewhere — cancel so decode
                # slots and KV blocks free up before any reconnect.
                loop = self.replica.loop if self.replica else None
                if loop is not None:
                    for attempt, _g in list(self._attempts.values()):
                        try:
                            loop.cancel(attempt)
                        except Exception:
                            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        authed = not self._token
        while not self._shutdown.is_set():
            req = recv_frame(conn)
            self._last_contact = time.monotonic()
            op = str(req.get("op", ""))
            if op == "stall":
                # Fault drill: go silent without dying. Stop reading so
                # every parent RPC on this connection times out.
                while not self._shutdown.wait(3600.0):
                    pass
                return
            rid = req.get("id")
            if not authed:
                # Attach handshake: the FIRST frame must be a hello
                # presenting the shared token — anyone can reach a
                # listening TCP port; only the router holds the secret.
                if op != "hello" or str(req.get("token") or "") != self._token:
                    self._send(
                        {
                            "id": rid,
                            "error": "unauthorized",
                            "message": "bad or missing attach token",
                        }
                    )
                    return
                authed = True
            try:
                handled = self._dispatch(op, req)
            except Exception as e:  # handler bug: report, keep serving
                self._send(
                    {"id": rid, "error": "runtime", "message": repr(e)}
                )
                continue
            if not handled:
                self._send(
                    {
                        "id": rid,
                        "error": "runtime",
                        "message": f"unknown op {op!r}",
                    }
                )

    def _dispatch(self, op: str, req: Dict[str, Any]) -> bool:
        rid = req.get("id")
        rep = self.replica
        loop = rep.loop
        if op == "hello":
            self._adopt_lease(req)
            self._peer_proto = int(req.get("proto", 1))
            eng = loop.engine
            self._send(
                {
                    "id": rid,
                    "ok": {
                        "pid": os.getpid(),
                        "generation": rep.generation,
                        "vocab_size": int(eng.cfg.vocab_size),
                        "context_length": int(eng.cfg.context_length),
                        "max_seq": int(eng.max_seq),
                        "block_size": int(eng.block_size),
                        "n_blocks": int(eng.alloc.n_blocks),
                        "max_batch": int(eng.max_batch),
                        "temperature": float(eng.temperature),
                        # Attach handshake extras: the engine fingerprint
                        # lets the router refuse a worker serving the
                        # wrong weights; the echoed fence/lease confirm
                        # what this worker will stamp and honor.
                        "weight_fingerprint0": loop.weight_fingerprint0,
                        "weight_fingerprint": loop.weight_fingerprint,
                        "fence": self._fence,
                        "lease_s": self._lease_s,
                        "lease_expiries": self._lease_expiries,
                        # Protocol negotiation + clock alignment: the
                        # parent only sends/expects v2 frames if this
                        # advertises >= 2, and feeds the clock sample
                        # (our perf_counter epoch) into its min-RTT
                        # offset estimator.
                        "proto": PROTO_VERSION,
                        "clock": time.perf_counter(),
                        # Disaggregation: what traffic this worker takes.
                        "role": rep.role,
                    },
                }
            )
            return True
        if op == "submit":
            self._handle_submit(rid, req)
            return True
        if op == "cancel":
            ent = self._attempts.get(int(req.get("rid", -1)))
            if ent is not None:
                loop.cancel(ent[0])
            self._send({"id": rid, "ok": True})
            return True
        if op == "drain":
            rep.drain()
            self._send({"id": rid, "ok": True})
            return True
        if op == "health":
            # Health polls double as the lease heartbeat: each carries
            # the router's current fence generation + lease term.
            self._adopt_lease(req)
            self._send({"id": rid, "ok": self._health()})
            return True
        if op == "health_pull":
            # Heartbeat semantics identical to health; the reply adds
            # the gauge + sketch payload the fleet snapshot aggregates.
            self._adopt_lease(req)
            self._send({"id": rid, "ok": self._health_pull()})
            return True
        if op == "metrics":
            self._send({"id": rid, "ok": loop.metrics()})
            return True
        if op == "debug_requests":
            self._send({"id": rid, "ok": loop.debug_requests()})
            return True
        if op == "debug_engine":
            self._send({"id": rid, "ok": loop.debug_engine()})
            return True
        if op == "probe_set":
            threading.Thread(
                target=self._handle_probe_set,
                args=(rid, req),
                name="worker-probeset",
                daemon=True,
            ).start()
            return True
        if op == "kv_fetch":
            threading.Thread(
                target=self._handle_kv_fetch,
                args=(rid, req),
                name="worker-kvfetch",
                daemon=True,
            ).start()
            return True
        if op == "kv_page":
            self._handle_kv_page(req)
            return True
        if op == "shutdown":
            self._send({"id": rid, "ok": True})
            self._shutdown.set()
            threading.Thread(
                target=self._exit_clean, name="worker-exit", daemon=True
            ).start()
            return True
        return False

    def _handle_submit(self, rid: Any, req: Dict[str, Any]) -> None:
        from ..frontend.admission import RejectedBusy, RejectedInfeasible
        from ..frontend.replica import ReplicaUnavailable

        rep = self.replica
        prompt = [int(t) for t in req.get("prompt", [])]
        max_new = req.get("max_new", 1)
        deadline_s = req.get("deadline_s")
        priority = int(req.get("priority", 0))
        lane = str(req.get("lane", "replica"))
        # The PARENT assigns the stream id: it registers the attempt
        # before sending, so a token frame can never race the reply.
        wrid = int(req.get("rid", 0))
        # A submit carrying ``traceparent`` joins the router's trace: the
        # local RequestTrace inherits the trace id and parents its root
        # under the router's placement-attempt span, so the worker's
        # queue/prefill/window spans nest inside the fleet lineage tree
        # once exported. No header -> local tracing stays off (the
        # worker's own sample rate is 0).
        tp = req.get("traceparent")
        trace_kw: Dict[str, Any] = {}
        if tp is not None:
            trace_kw["trace"] = self.tracer.begin_request(str(tp))
        try:
            if lane == "loop":
                attempt = rep.loop.submit(
                    prompt, max_new, deadline_s=deadline_s,
                    priority=priority, **trace_kw
                )
            else:
                attempt = rep.submit(
                    prompt, max_new, deadline_s=deadline_s,
                    priority=priority, **trace_kw
                )
        except ValueError as e:
            self._send({"id": rid, "error": "invalid", "message": str(e)})
            return
        except RejectedBusy as e:
            self._send(
                {
                    "id": rid,
                    "error": "busy",
                    "message": e.reason,
                    "retry_after_s": e.retry_after_s,
                }
            )
            return
        except RejectedInfeasible as e:
            self._send(
                {
                    "id": rid,
                    "error": "infeasible",
                    "message": e.reason,
                    "estimate_s": e.estimate_s,
                }
            )
            return
        except (ReplicaUnavailable, RuntimeError) as e:
            self._send({"id": rid, "error": "unavailable", "message": str(e)})
            return
        self._wire_submits += 1
        g = self._fence
        self._attempts[wrid] = (attempt, g)
        self._send({"id": rid, "ok": {"rid": wrid}})
        threading.Thread(
            target=self._pump,
            args=(wrid, attempt, g),
            name=f"worker-pump-{wrid}",
            daemon=True,
        ).start()
        if self._kill_after and self._wire_submits >= self._kill_after:
            # mid-upgrade-kill drill: die AFTER acking the submit, so
            # the parent is committed to waiting on this stream.
            os.kill(os.getpid(), signal.SIGKILL)

    def _pump(self, wrid: int, attempt: Any, g: int) -> None:
        try:
            for ev in attempt.events():
                if ev[0] == "token":
                    self._send({"token": wrid, "t": int(ev[1])}, g=g)
                elif ev[0] == "end":
                    self._send(
                        {
                            "end": wrid,
                            "status": attempt.status,
                            "info": dict(attempt.info),
                        },
                        g=g,
                    )
                    self._export_spans(g)
        finally:
            self._attempts.pop(wrid, None)

    def _export_spans(self, g: int) -> None:
        """Ship every span completed since the last export in one
        batched frame (piggybacked on stream ends — the recorder only
        holds COMPLETED spans, so concurrent in-flight requests lose
        nothing; their spans ride a later batch). Gated on the peer's
        advertised protocol version: a v1 router would treat the frame
        as garbage. The drop count is a delta the parent feeds into a
        monotonic counter — a saturated worker buffer is visible, never
        silent."""
        if self._peer_proto < 2:
            return
        events, dropped = self.recorder.drain()
        if not events and not dropped:
            return
        self._send(
            {
                "op": "spans",
                "spans": [
                    {"name": name, "t0": t0, "dur": dur, "meta": meta}
                    for name, t0, dur, _tid, _depth, meta in events
                ],
                "dropped": dropped,
            },
            g=g,
        )

    # ---- KV-page migration (frontend/kv_transfer.py) ----------------

    def _handle_kv_fetch(self, rid: Any, req: Dict[str, Any]) -> None:
        """Serialize the longest cached chain for the prompt and stream
        it back as kv_page frames, then the summary reply. Side thread:
        the snapshot does a device pull per page, and health polls must
        stay live underneath it."""
        try:
            from . import kv_transfer

            prompt = [int(t) for t in req.get("prompt", [])]
            max_pages = req.get("max_pages")
            eng = self.replica.engine
            xfer = kv_transfer.snapshot_chain(
                eng, prompt,
                max_pages=int(max_pages) if max_pages else None,
            )
            if xfer is None:
                self._send(
                    {"id": rid, "ok": {"pages": 0, "bytes": 0, "frames": 0}}
                )
                return
            budget = int(
                req.get("budget") or kv_transfer.KV_FRAME_BUDGET_BYTES
            )
            frames = kv_transfer.split_frames(xfer, budget=budget)
            for fr in frames:
                self._send({"op": "kv_page", "fetch": rid, **fr})
            self._send(
                {
                    "id": rid,
                    "ok": {
                        "pages": len(xfer["pages"]),
                        "bytes": kv_transfer.transfer_bytes(xfer),
                        "frames": len(frames),
                    },
                }
            )
        except Exception as e:
            self._send({"id": rid, "error": "runtime", "message": repr(e)})

    def _handle_kv_page(self, req: Dict[str, Any]) -> None:
        """Receive side of a page push. Interior frames (no ``id``)
        accumulate; the final frame triggers reassembly + loop-thread
        adoption. A frame whose fence generation predates the worker's
        current fence poisons nothing: it is dropped (with its partial
        transfer) and the sender told why."""
        xid = req.get("xfer")
        rid = req.get("id")
        g = req.get("g")
        if g is not None and int(g) < self._fence:
            self._kv_stale_frames += 1
            self._kv_rx.pop(xid, None)
            if rid is not None:
                self._send(
                    {
                        "id": rid,
                        "error": "stale_fence",
                        "message": (
                            f"kv_page frame generation {g} predates "
                            f"fence {self._fence}; pages dropped"
                        ),
                    }
                )
            return
        frames = self._kv_rx.setdefault(xid, [])
        frames.append(req)
        if rid is None:
            return
        self._kv_rx.pop(xid, None)
        threading.Thread(
            target=self._adopt_kv_pages,
            args=(rid, frames),
            name="worker-kvadopt",
            daemon=True,
        ).start()

    def _adopt_kv_pages(self, rid: Any, frames: list) -> None:
        try:
            from . import kv_transfer

            xfer = kv_transfer.join_frames(frames)
            rep = self.replica
            eng = rep.engine
            res = rep.loop.run_on_loop(
                lambda: kv_transfer.adopt_chain(eng, xfer), timeout=30.0
            )
            self._send({"id": rid, "ok": res})
        except ValueError as e:  # torn transfer
            self._send({"id": rid, "error": "torn", "message": str(e)})
        except Exception as e:
            self._send({"id": rid, "error": "runtime", "message": repr(e)})

    def _adopt_lease(self, req: Dict[str, Any]) -> None:
        fence = req.get("fence")
        if fence is not None:
            # Monotonic: a delayed heartbeat from before an eject must
            # not roll the generation back.
            self._fence = max(self._fence, int(fence))
        lease_s = req.get("lease_s")
        if lease_s is not None:
            self._lease_s = max(0.0, float(lease_s))

    def _handle_probe_set(self, rid: Any, req: Dict[str, Any]) -> None:
        try:
            from ..resilience.integrity import build_probe_set

            eng = self.replica.engine
            probes = build_probe_set(
                eng.params,
                eng.cfg,
                n_probes=int(req.get("n_probes", 2)),
                probe_len=int(req.get("probe_len", 9)),
                max_new=int(req.get("max_new", 4)),
            )
            self._send(
                {
                    "id": rid,
                    "ok": [
                        {
                            "prompt": [int(t) for t in p.prompt],
                            "expected": [int(t) for t in p.expected],
                        }
                        for p in probes
                    ],
                }
            )
        except Exception as e:
            self._send({"id": rid, "error": "runtime", "message": repr(e)})

    def _health(self) -> Dict[str, Any]:
        rep = self.replica
        loop = rep.loop
        failure = loop.failure
        return {
            "running": bool(loop.running),
            "draining": bool(loop.draining),
            "active_requests": int(loop.active_requests),
            "last_turn_age_s": float(loop.last_turn_age_s()),
            "generation": int(rep.generation),
            "submits": int(rep.submits),
            "state": rep.state,
            "role": rep.role,
            "failure": repr(failure) if failure is not None else None,
            "weight_fingerprint0": loop.weight_fingerprint0,
            "weight_fingerprint": loop.weight_fingerprint,
            "lease_expiries": self._lease_expiries,
            "fence": self._fence,
            # Heartbeat clock sample: re-read on every health poll so the
            # parent's offset estimator tracks drift continuously.
            "clock": time.perf_counter(),
        }

    def _health_pull(self) -> Dict[str, Any]:
        """health fields + worker gauges + serialized latency sketches
        (proto >= 4 reply body; see the op table in the module doc)."""
        out = self._health()
        loop = self.replica.loop
        eng = loop.engine
        gauges: Dict[str, Any] = {}
        hg = getattr(eng, "health_gauges", None)
        if hg is not None:
            gauges.update(hg())
        gauges["active_requests"] = int(loop.active_requests)
        if loop.admission is not None:
            adm = loop.admission.snapshot()
            gauges["admission_depth"] = int(adm.get("live_requests", 0))
            gauges["admission_outstanding_tokens"] = int(
                adm.get("outstanding_tokens", 0)
            )
        gauges["kv_stale_frames"] = int(self._kv_stale_frames)
        out["gauges"] = gauges
        # Device HBM watermarks: a host-side allocator query, never a
        # device sync; CPU and API-less backends report {} and the
        # snapshot simply has no hbm section for this replica.
        try:
            from ..observability.device import DeviceTelemetry

            hbm = DeviceTelemetry(bus=None).sample()
        except Exception:
            hbm = {}
        if hbm:
            out["hbm"] = hbm
        out["sketches"] = {
            m: ws.merged().to_dict()
            for m, ws in self._lat_sketches.items()
        }
        return out

    def _exit_clean(self) -> None:
        try:
            self.replica.stop(timeout=5.0)
            try:
                self._listener.close()
            except OSError:
                pass
        finally:
            os._exit(0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Serving worker: one engine replica behind a socket"
    )
    parser.add_argument(
        "--spec-json",
        required=True,
        help="worker spec as a JSON object (see module docstring)",
    )
    parser.add_argument(
        "--listen",
        default="",
        help="host:port to serve on as a PRE-SPAWNED multi-host worker "
        "(port 0 binds an ephemeral port, announced on stdout); the "
        "router attaches by address instead of spawning this process",
    )
    parser.add_argument(
        "--token",
        default="",
        help="shared secret every attaching router must present in its "
        "hello (attach mode)",
    )
    parser.add_argument(
        "--role",
        default="",
        choices=["", "prefill", "decode", "both"],
        help="disaggregation role: 'prefill' computes prompts and ships "
        "KV pages to the decode tier (the router never routes client "
        "decode traffic here), 'decode' serves clients and receives "
        "migrated pages, 'both' (default) is the classic colocated "
        "worker; overrides any role in --spec-json",
    )
    args = parser.parse_args(argv)
    spec = json.loads(args.spec_json)
    if not isinstance(spec, dict):
        raise SystemExit("--spec-json must be a JSON object")
    if args.listen:
        spec["listen"] = args.listen
    if args.token:
        spec["token"] = args.token
    if args.role:
        spec["role"] = args.role

    server = WorkerServer(spec)
    server.announce()
    signal.signal(
        signal.SIGTERM,
        lambda signum, frame: threading.Thread(
            target=server._drain_and_exit,
            args=("SIGTERM",),
            daemon=True,
        ).start(),
    )
    if server.attached:
        # Pre-spawned workers have no parent pipe; the heartbeat lease
        # (granted by the attaching router's hello) replaces the orphan
        # watch — expiry parks the worker instead of exiting it.
        server.start_lease_watch()
    else:
        server.start_orphan_watch()
    server.start_replica()
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
