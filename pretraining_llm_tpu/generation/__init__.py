from pretraining_llm_tpu.generation.generate import generate, generate_text  # noqa: F401
from pretraining_llm_tpu.generation.sampling import sample_logits  # noqa: F401
