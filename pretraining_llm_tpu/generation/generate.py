"""KV-cached autoregressive generation, fully jitted.

The reference's `generate` re-forwards the entire window for every new token —
O(n * T^2) with no cache (`/root/reference/src/models/transformer.py:96-114`,
SURVEY §3.2). TPU-native redesign:

  - prefill once over the prompt (one big MXU-friendly forward),
  - then a `lax.scan` of single-token decode steps against a stacked KV cache
    (L, B, T, H, Dh) — O(n * T) total, one compiled program for the whole
    generation (no per-token Python dispatch),
  - sampling semantics match the reference by default (temperature-1
    categorical) with temperature/top-k/top-p extensions.

`generate_text` mirrors the reference CLI entry
(`/root/reference/scripts/generate_text.py:7-46`): load checkpoint, rebuild
model from its stored config, encode with GPT-2 BPE, generate, decode.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import Config, ModelConfig
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.generation.sampling import sample_logits


def _bucket_len(prompt_len: int, ctx: int, max_new_tokens: int) -> int:
    """Pad target for the prompt: next power of two (>=16), capped so the
    padded prompt + generation still fits the context. Prompt LENGTH is a
    traced value — only the bucket is a compile key, so all prompts in a
    bucket share one executable instead of one compile per length."""
    b = 16
    while b < prompt_len:
        b *= 2
    return max(prompt_len, min(b, ctx - max_new_tokens))


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "top_k", "top_p", "mesh"),
)
def _generate_jit(
    params: Any,
    prompt: jax.Array,  # (B, P_bucket) zero-padded prompt
    prompt_len: jax.Array,  # () int32 — true length, traced
    key: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    temperature: float,
    top_k: Optional[int],
    top_p: Optional[float],
    mesh: Any = None,
) -> jax.Array:
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    b = prompt.shape[0]
    total = prompt.shape[1] + max_new_tokens
    with activation_mesh(mesh):
        cache = transformer.make_kv_cache(cfg, b, total)

        # Prefill: one forward over the whole padded prompt. Causality keeps
        # pad positions (>= prompt_len) invisible to real ones, and each pad
        # slot's garbage K/V is overwritten by the decoded token that lands
        # there before the kv_mask ever exposes it.
        logits, cache = transformer.forward(
            params, prompt, cfg, kv_cache=cache, cache_index=jnp.int32(0)
        )
        key, sub = jax.random.split(key)
        idx = jnp.broadcast_to(
            (prompt_len - 1).astype(jnp.int32), (b, 1, logits.shape[-1])
        )
        last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
        next_tok = sample_logits(
            last, sub, temperature=temperature, top_k=top_k, top_p=top_p
        )

        def decode_step(carry, _):
            cache, tok, key, index = carry
            logits, cache = transformer.forward(
                params, tok[:, None], cfg, kv_cache=cache, cache_index=index
            )
            key, sub = jax.random.split(key)
            nxt = sample_logits(
                logits[:, 0], sub, temperature=temperature, top_k=top_k, top_p=top_p
            )
            return (cache, nxt, key, index + 1), tok

        (_, _, _, _), toks = jax.lax.scan(
            decode_step,
            (cache, next_tok, key, prompt_len.astype(jnp.int32)),
            None,
            length=max_new_tokens,
        )
    # Each step emits its carry-in token, so toks == the max_new_tokens
    # sampled ids in order (the final carry token is the unused n+1-th).
    return toks.T


def generate(
    params: Any,
    cfg: ModelConfig,
    prompt_tokens: jax.Array,
    max_new_tokens: int,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    mesh: Any = None,
) -> jax.Array:
    """Generate continuations. prompt_tokens: (B, P) or (P,) int32.

    Returns (B, max_new_tokens) of sampled ids. The whole prompt+generation
    must fit the model context (the KV cache is position-table bound).

    Prompts are zero-padded to a power-of-two bucket, so XLA compiles once
    per (bucket, max_new_tokens, batch) — not once per prompt length.

    ``mesh``: optional jax.sharding.Mesh for sharded decode of models too big
    for one chip — pass params already placed with
    `shard_params_for_inference`; activations follow the param shardings.
    """
    prompt = jnp.atleast_2d(jnp.asarray(prompt_tokens, jnp.int32))
    prompt_len = int(prompt.shape[1])
    if prompt_len + max_new_tokens > cfg.context_length:
        raise ValueError(
            f"prompt({prompt_len}) + max_new_tokens({max_new_tokens}) exceeds "
            f"context_length={cfg.context_length}"
        )
    # MoE prefill routes with a capacity proportional to the token count and
    # pad tokens would compete for expert slots, perturbing real tokens'
    # hidden states — bucketing is for dense models only.
    bucket = (
        prompt_len
        if cfg.n_experts
        else _bucket_len(prompt_len, cfg.context_length, max_new_tokens)
    )
    if bucket > prompt_len:
        prompt = jnp.pad(prompt, ((0, 0), (0, bucket - prompt_len)))
    return _generate_jit(
        params, prompt, jnp.int32(prompt_len), key, cfg, max_new_tokens,
        temperature, top_k, top_p, mesh,
    )


def shard_params_for_inference(params: Any, mesh: Any) -> Any:
    """Place params on a mesh with the training partition rules (TP/FSDP) so
    `generate(..., mesh=mesh)` decodes models that exceed one chip's HBM."""
    from pretraining_llm_tpu.parallel.sharding import named_sharding_tree, param_pspec_tree

    tensor_size = mesh.shape.get("tensor", 1)
    return jax.device_put(
        params,
        named_sharding_tree(mesh, param_pspec_tree(params, tensor_size=tensor_size)),
    )


# ---------------------------------------------------------------------------
# Checkpoint-driven text generation (CLI surface)
# ---------------------------------------------------------------------------


def load_model_for_inference(model_path: str) -> Tuple[Any, Config]:
    """Load params + config from a framework checkpoint directory."""
    from pretraining_llm_tpu.training import checkpoint as ckpt

    path = model_path
    if not path.rstrip("/").split("/")[-1].startswith("step-"):
        latest = ckpt.latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
        path = latest
    with open(f"{path}/metadata.json") as f:
        meta = json.load(f)
    cfg = Config.from_json(json.dumps(meta["extra"]["config"]))
    # Shape-only template: no throwaway init of the full model.
    template = jax.eval_shape(
        lambda: {"params": transformer.init_params(cfg.model, jax.random.key(0))}
    )
    restored, _ = ckpt.load_checkpoint(path, template)
    return jax.device_put(restored["params"]), cfg


def generate_text(
    model_path: str,
    input_text: str,
    max_new_tokens: int = 100,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    seed: int = 0,
    tokenizer: Optional[str] = None,
) -> str:
    """Mirror of the reference's `generate_text(model_path, input_text,
    max_new_tokens)` (generate_text.py:7): checkpoint -> text continuation.

    `tokenizer` overrides the name stored in the checkpoint's config (e.g. a
    checkpoint trained elsewhere whose BPE files aren't available here)."""
    from pretraining_llm_tpu.data.tokenizer import get_tokenizer

    params, cfg = load_model_for_inference(model_path)
    enc = get_tokenizer(tokenizer or cfg.data.tokenizer_name)
    ids = np.asarray(enc.encode_ordinary(input_text), np.int32)[None, :]
    out = generate(
        params,
        cfg.model,
        ids,
        max_new_tokens,
        jax.random.key(seed),
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
    )
    return input_text + enc.decode(np.asarray(out[0]).tolist())
