"""KV-cached autoregressive generation, fully jitted.

The reference's `generate` re-forwards the entire window for every new token —
O(n * T^2) with no cache (`/root/reference/src/models/transformer.py:96-114`,
SURVEY §3.2). TPU-native redesign:

  - prefill once over the prompt (one big MXU-friendly forward),
  - then a `lax.scan` of single-token decode steps against a stacked KV cache
    (L, B, T, H, Dh) — O(n * T) total, one compiled program for the whole
    generation (no per-token Python dispatch),
  - sampling semantics match the reference by default (temperature-1
    categorical) with temperature/top-k/top-p extensions.

`generate_text` mirrors the reference CLI entry
(`/root/reference/scripts/generate_text.py:7-46`): load checkpoint, rebuild
model from its stored config, encode with GPT-2 BPE, generate, decode.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import Config, ModelConfig
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.generation.sampling import sample_logits


def cast_params_for_inference(params: Any, cfg: ModelConfig) -> Any:
    """One-time fp32 -> compute-dtype cast of the matmul weights.

    Explicit serving-prep step (like `shard_params_for_inference`): call it
    once after checkpoint load and drop the fp32 tree. The forward casts
    every matmul weight to `compute_dtype` at its use site; fp32 params
    flowing into the decode scan therefore read 2x the bytes per step
    (fp32 source) unless XLA's loop-invariant code motion happens to hoist
    the converts — which it must trade against the extra live copy, so it
    is not guaranteed. Pre-casting makes the per-step weight traffic the
    bf16 minimum and (once the caller drops the fp32 tree) halves param
    HBM, with BIT-IDENTICAL results: the same cast happens at every use
    site anyway. Leaves the forward deliberately consumes in fp32 are NOT
    cast — norm scales/biases (fp32 norm math, layers.layernorm/rmsnorm),
    the lm_head bias (added to fp32 logits, transformer.py:585), and the
    MoE router (fp32 routing scores, moe.py) — casting those would change
    numerics.
    """
    cdt = jnp.dtype(cfg.compute_dtype)

    def cast(path, x):
        if not jnp.issubdtype(x.dtype, jnp.floating) or x.dtype == cdt:
            return x
        names = [str(getattr(k, "key", "")) for k in path]
        if any(n.startswith("ln") or "norm" in n for n in names):
            return x
        if names[-1] == "router":
            return x
        if len(names) >= 2 and names[-2] == "lm_head" and names[-1] == "bias":
            return x
        return x.astype(cdt)

    return jax.tree_util.tree_map_with_path(cast, params)


def decode_bench_workload(cfg: ModelConfig, batch: int, *,
                          quick: bool = False) -> Tuple[ModelConfig, Any, jax.Array, int]:
    """The canonical decode measurement workload, shared by `bench.py
    --mode decode` and `profile_capture.py --mode decode` so the profile
    always traces exactly the shape the benchmark measures.

    Returns (cfg, params, prompt, new_tokens): ring/ulysses fall back to
    the cached naive path, params are inference-cast, prompt is (batch,
    prompt_len) with prompt_len = min(64, ctx - new_tokens).
    """
    import dataclasses as _dc

    if cfg.attention_impl in ("ring", "ulysses"):
        cfg = _dc.replace(cfg, attention_impl="naive", sequence_parallel=False)
    new_tokens = min(64 if quick else 256, cfg.context_length // 2)
    prompt_len = min(64, cfg.context_length - new_tokens)
    params = cast_params_for_inference(
        transformer.init_params(cfg, jax.random.key(0)), cfg
    )
    prompt = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, cfg.vocab_size
    )
    return cfg, params, prompt, new_tokens


def _bucket_len(prompt_len: int, ctx: int, max_new_tokens: int) -> int:
    """Pad target for the prompt: next power of two (>=16), capped so the
    padded prompt + generation still fits the context. Prompt LENGTH is a
    traced value — only the bucket is a compile key, so all prompts in a
    bucket share one executable instead of one compile per length."""
    b = 16
    while b < prompt_len:
        b *= 2
    return max(prompt_len, min(b, ctx - max_new_tokens))


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "top_k", "top_p",
                     "min_p", "mesh"),
)
def _generate_jit(
    params: Any,
    prompt: jax.Array,  # (B, P_bucket) zero-padded prompt
    prompt_len: jax.Array,  # () int32 — true length, traced
    key: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    temperature: float,
    top_k: Optional[int],
    top_p: Optional[float],
    min_p: Optional[float] = None,
    mesh: Any = None,
    prompt_lengths: Optional[jax.Array] = None,  # (B,) int32 — ragged rows
    stop_token: Optional[jax.Array] = None,  # () int32 — traced, no recompile per id
) -> jax.Array:
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    b = prompt.shape[0]
    bucket = prompt.shape[1]
    total = bucket + max_new_tokens
    with activation_mesh(mesh):
        cache = transformer.make_kv_cache(cfg, b, total)

        key, sub = jax.random.split(key)
        if prompt_lengths is None:
            pad_off = None
            # Prefill: one forward over the whole padded prompt. Causality
            # keeps pad positions (>= prompt_len) invisible to real ones,
            # and each pad slot's garbage K/V is overwritten by the decoded
            # token that lands there before the kv_mask ever exposes it.
            logits, cache = transformer.forward(
                params, prompt, cfg, kv_cache=cache, cache_index=jnp.int32(0)
            )
            idx = jnp.broadcast_to(
                (prompt_len - 1).astype(jnp.int32), (b, 1, logits.shape[-1])
            )
            last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
            start_index = prompt_len.astype(jnp.int32)
        else:
            # RAGGED rows. Prefill runs RIGHT-padded — plain causal
            # attention, so real tokens never see the trailing pads, RoPE/
            # learned positions are already logical, and the FLASH prefill
            # shortcut applies (no (Tq, Tmax) scores at long prompts). The
            # written cache is then rolled right per row so every prompt
            # ends at slot bucket-1: the batch decodes in lockstep at
            # shared slot indices, with per-row pad_offsets driving logical
            # positions + the kv mask. Slots [0, offset_i) hold garbage
            # copies that the decode kv mask never exposes.
            pad_off = (bucket - prompt_lengths).astype(jnp.int32)
            logits, cache = transformer.forward(
                params, prompt, cfg, kv_cache=cache, cache_index=jnp.int32(0)
            )
            idx = jnp.broadcast_to(
                (prompt_lengths - 1).astype(jnp.int32)[:, None, None],
                (b, 1, logits.shape[-1]),
            )
            last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
            src = jnp.clip(
                jnp.arange(total)[None, :] - pad_off[:, None], 0, total - 1
            )  # (B, total)
            if "layers" in cache:
                # Unstacked layout: per-layer leaves are (B, T, ...).
                cache = jax.tree.map(
                    lambda c: jnp.take_along_axis(
                        c, src[:, :, None, None], axis=1
                    ),
                    cache,
                )
            else:
                cache = jax.tree.map(
                    lambda c: jnp.take_along_axis(
                        c, src[None, :, :, None, None], axis=2
                    ),
                    cache,
                )
            start_index = jnp.int32(bucket)
        next_tok = sample_logits(
            last, sub, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p,
        )

        def decode_step(carry, _):
            cache, tok, key, index = carry
            logits, cache = transformer.forward(
                params, tok[:, None], cfg, kv_cache=cache, cache_index=index,
                pad_offsets=pad_off,
            )
            key, sub = jax.random.split(key)
            nxt = sample_logits(
                logits[:, 0], sub, temperature=temperature, top_k=top_k,
                top_p=top_p, min_p=min_p,
            )
            if stop_token is not None:
                # A finished row keeps emitting its stop token: the scan
                # stays fixed-length (XLA-friendly), the caller truncates.
                done = tok == stop_token
                nxt = jnp.where(done, stop_token.astype(jnp.int32), nxt)
            return (cache, nxt, key, index + 1), tok

        (_, _, _, _), toks = jax.lax.scan(
            decode_step,
            (cache, next_tok, key, start_index),
            None,
            length=max_new_tokens,
        )
    # Each step emits its carry-in token, so toks == the max_new_tokens
    # sampled ids in order (the final carry token is the unused n+1-th).
    return toks.T


def generate(
    params: Any,
    cfg: ModelConfig,
    prompt_tokens: jax.Array,
    max_new_tokens: int,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
    prompt_lengths: Optional[Any] = None,
    stop_token: Optional[int] = None,
) -> jax.Array:
    """Generate continuations. prompt_tokens: (B, P) or (P,) int32.

    ``stop_token``: once a row samples it, the row keeps emitting it for
    the remaining steps (fixed-length device program; strip the trailing
    stop tokens host-side). The reference has no stop handling at all
    (generate loops a fixed count, transformer.py:96-114).

    ``prompt_lengths`` ((B,) int32) enables RAGGED batches: rows of
    different true lengths, right-padded to P on input. Internally each row
    is left-shifted so every prompt ends at the same slot and the whole
    batch decodes in lockstep — one compiled program, no per-row loops;
    row i's continuation starts right after its own last prompt token
    (serving-grade batched decode; the reference generates batch-1 only,
    generate_text.py:41-42). Not supported for MoE models (pad slots would
    compete for expert capacity during prefill).

    Returns (B, max_new_tokens) of sampled ids. The whole prompt+generation
    must fit the model context (the KV cache is position-table bound).

    Prompts are zero-padded to a power-of-two bucket, so XLA compiles once
    per (bucket, max_new_tokens, batch) — not once per prompt length.

    ``mesh``: optional jax.sharding.Mesh for sharded decode of models too big
    for one chip — pass params already placed with
    `shard_params_for_inference`; activations follow the param shardings.
    """
    if cfg.doc_mask_token >= 0:
        # Packed-document masking is a TRAINING-time attention structure; a
        # decode session is a single document, so the mask is vacuous — and
        # forward() rejects the combination with a KV cache. A checkpoint
        # trained with packing must still decode (the e2e contract), so
        # sanitize here like decode_bench_workload does for ring/ulysses.
        import dataclasses as _dc

        cfg = _dc.replace(cfg, doc_mask_token=-1)
    prompt = jnp.atleast_2d(jnp.asarray(prompt_tokens, jnp.int32))
    prompt_len = int(prompt.shape[1])
    if prompt_len + max_new_tokens > cfg.context_length:
        raise ValueError(
            f"prompt({prompt_len}) + max_new_tokens({max_new_tokens}) exceeds "
            f"context_length={cfg.context_length}"
        )
    if prompt_lengths is not None:
        if cfg.n_experts:
            raise ValueError(
                "ragged prompt_lengths is unsupported for MoE models: left-"
                "pad slots would compete for expert capacity during prefill"
            )
        lengths = jnp.asarray(prompt_lengths, jnp.int32).reshape(-1)
        if lengths.shape[0] != prompt.shape[0]:
            raise ValueError(
                f"prompt_lengths has {lengths.shape[0]} rows for a batch of "
                f"{prompt.shape[0]}"
            )
        if int(jnp.max(lengths)) > prompt_len or int(jnp.min(lengths)) < 1:
            raise ValueError(
                "prompt_lengths must lie in [1, P] for (B, P) prompt_tokens"
            )
    else:
        lengths = None
    # MoE prefill routes with a capacity proportional to the token count and
    # pad tokens would compete for expert slots, perturbing real tokens'
    # hidden states — bucketing is for dense models only.
    bucket = (
        prompt_len
        if cfg.n_experts
        else _bucket_len(prompt_len, cfg.context_length, max_new_tokens)
    )
    # Ragged rows occupy slots up to bucket+max_new (dead left-pads
    # included): always within the context, since the earlier prompt_len
    # check plus _bucket_len's cap give bucket <= ctx - max_new_tokens.
    assert bucket + max_new_tokens <= cfg.context_length
    if bucket > prompt_len:
        prompt = jnp.pad(prompt, ((0, 0), (0, bucket - prompt_len)))
    stop = jnp.int32(stop_token) if stop_token is not None else None
    return _generate_jit(
        params, prompt, jnp.int32(prompt_len), key, cfg, max_new_tokens,
        temperature, top_k, top_p, min_p, mesh, lengths, stop,
    )


def shard_params_for_inference(params: Any, mesh: Any) -> Any:
    """Place params on a mesh with the training partition rules (TP/FSDP) so
    `generate(..., mesh=mesh)` decodes models that exceed one chip's HBM."""
    from pretraining_llm_tpu.parallel.sharding import named_sharding_tree, param_pspec_tree

    tensor_size = mesh.shape.get("tensor", 1)
    return jax.device_put(
        params,
        named_sharding_tree(mesh, param_pspec_tree(params, tensor_size=tensor_size)),
    )


# ---------------------------------------------------------------------------
# Checkpoint-driven text generation (CLI surface)
# ---------------------------------------------------------------------------


def load_model_for_inference(
    model_path: str, *, use_ema: bool = False
) -> Tuple[Any, Config]:
    """Load params + config from a framework checkpoint directory.

    ``use_ema=True`` loads the exponential-moving-average shadow instead of
    the raw params (requires the run to have trained with
    `train.ema_decay > 0`; fails loudly otherwise)."""
    from pretraining_llm_tpu.training import checkpoint as ckpt

    path = model_path
    if not path.rstrip("/").split("/")[-1].startswith("step-"):
        latest = ckpt.latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
        path = latest
    with open(f"{path}/metadata.json") as f:
        meta = json.load(f)
    cfg = Config.from_json(json.dumps(meta["extra"]["config"]))
    key = "ema" if use_ema else "params"
    # Shape-only template: no throwaway init of the full model.
    template = jax.eval_shape(
        lambda: {key: transformer.init_params(cfg.model, jax.random.key(0))}
    )
    try:
        restored, _ = ckpt.load_checkpoint(path, template)
    except ValueError as e:
        if use_ema and "missing leaves" in str(e):
            raise ValueError(
                f"checkpoint {path} has no EMA shadow (the run trained "
                "with train.ema_decay=0); drop --ema or retrain with "
                "ema_decay > 0"
            ) from e
        raise
    # NOTE: returns the RAW checkpoint dtypes — callers that only run the
    # forward should apply cast_params_for_inference (the generation CLIs
    # below do); callers that re-export weights (export_torch_checkpoint)
    # need the fp32 masters untouched.
    return jax.device_put(restored[key]), cfg


def generate_text(
    model_path: str,
    input_text: str,
    max_new_tokens: int = 100,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    seed: int = 0,
    tokenizer: Optional[str] = None,
    stop_token: Optional[int] = None,
    ema: bool = False,
) -> str:
    """Mirror of the reference's `generate_text(model_path, input_text,
    max_new_tokens)` (generate_text.py:7): checkpoint -> text continuation.

    `tokenizer` overrides the name stored in the checkpoint's config (e.g. a
    checkpoint trained elsewhere whose BPE files aren't available here)."""
    return generate_text_batch(
        model_path,
        [input_text],
        max_new_tokens,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        min_p=min_p,
        seed=seed,
        tokenizer=tokenizer,
        stop_token=stop_token,
        ema=ema,
    )[0]


def generate_text_batch(
    model_path: str,
    input_texts: list,
    max_new_tokens: int = 100,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    seed: int = 0,
    tokenizer: Optional[str] = None,
    stop_token: Optional[int] = None,
    ema: bool = False,
) -> list:
    """Batched continuation of DIFFERENT-length prompts in one compiled
    ragged decode (`generate(..., prompt_lengths=...)`) — one device
    program for the whole batch instead of a per-prompt loop. Returns one
    continuation string per input; a row's output TRUNCATES at (excludes)
    its first ``stop_token``."""
    from pretraining_llm_tpu.data.tokenizer import get_tokenizer

    if not input_texts:
        raise ValueError("input_texts is empty (nothing to generate)")
    params, cfg = load_model_for_inference(model_path, use_ema=ema)
    # Serving prep: bf16 matmul weights (bit-identical forward — see
    # cast_params_for_inference); the fp32 tree is dropped here, halving
    # param HBM and the per-step weight reads for the generation CLIs.
    params = cast_params_for_inference(params, cfg.model)
    enc = get_tokenizer(tokenizer or cfg.data.tokenizer_name)
    encoded = [
        np.asarray(enc.encode_ordinary(t), np.int32) for t in input_texts
    ]
    empty = [i for i, e in enumerate(encoded) if len(e) == 0]
    if empty:
        raise ValueError(
            f"prompts at indices {empty} encode to zero tokens; ragged "
            "decode needs at least one real token per row"
        )
    lengths = np.asarray([len(e) for e in encoded], np.int32)
    pmax = int(lengths.max())
    batch = np.zeros((len(encoded), pmax), np.int32)
    for i, e in enumerate(encoded):
        batch[i, : len(e)] = e
    # MoE models reject ragged rows (pad slots would compete for expert
    # capacity); a uniform-length batch — incl. every single-prompt call —
    # needs no ragged machinery, which keeps generate_text working for MoE.
    uniform = bool((lengths == lengths[0]).all())
    if cfg.model.n_experts and not uniform:
        raise ValueError(
            "MoE models require equal-length prompts per batch (ragged "
            "left-pad slots would compete for expert capacity); generate "
            "each prompt separately or group by length"
        )
    use_lengths = None if uniform else lengths
    out = np.asarray(
        generate(
            params,
            cfg.model,
            batch,
            max_new_tokens,
            jax.random.key(seed),
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
            prompt_lengths=use_lengths,
            stop_token=stop_token,
        )
    )

    def ids(row: np.ndarray) -> list:
        toks = row.tolist()
        if stop_token is not None and stop_token in toks:
            toks = toks[: toks.index(stop_token)]
        return toks

    return [
        t + enc.decode(ids(out[i])) for i, t in enumerate(input_texts)
    ]


def generate_text_speculative(
    model_path: str,
    draft_model_path: str,
    input_text: str,
    max_new_tokens: int = 100,
    *,
    k: int = 4,
    temperature: float = 0.0,
    seed: int = 0,
    tokenizer: Optional[str] = None,
) -> str:
    """Speculative continuation: a small draft checkpoint proposes k tokens
    per round, the target verifies them in one forward (see
    generation.speculative; greedy output is identical to target-only
    decoding). Both checkpoints must share a vocabulary."""
    import sys as _sys

    from pretraining_llm_tpu.data.tokenizer import get_tokenizer
    from pretraining_llm_tpu.generation.speculative import generate_speculative

    params_t, cfg_t = load_model_for_inference(model_path)
    params_d, cfg_d = load_model_for_inference(draft_model_path)
    params_t = cast_params_for_inference(params_t, cfg_t.model)
    params_d = cast_params_for_inference(params_d, cfg_d.model)
    enc = get_tokenizer(tokenizer or cfg_t.data.tokenizer_name)
    prompt = np.asarray(enc.encode_ordinary(input_text), np.int32)
    if len(prompt) == 0:
        raise ValueError("prompt encodes to zero tokens")
    out, stats = generate_speculative(
        params_t, cfg_t.model, params_d, cfg_d.model, prompt[None],
        max_new_tokens, jax.random.key(seed), k=k, temperature=temperature,
    )
    rate = stats["accepted"] / max(stats["proposed"], 1)
    print(
        f"[speculative] rounds={stats['rounds']} "
        f"acceptance={stats['accepted']}/{stats['proposed']} ({rate:.0%})",
        file=_sys.stderr,
    )
    return input_text + enc.decode(np.asarray(out).tolist())
