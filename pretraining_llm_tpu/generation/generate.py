"""KV-cached autoregressive generation, fully jitted.

The reference's `generate` re-forwards the entire window for every new token —
O(n * T^2) with no cache (`/root/reference/src/models/transformer.py:96-114`,
SURVEY §3.2). TPU-native redesign:

  - prefill once over the prompt (one big MXU-friendly forward),
  - then a `lax.scan` of single-token decode steps against a stacked KV cache
    (L, B, T, H, Dh) — O(n * T) total, one compiled program for the whole
    generation (no per-token Python dispatch),
  - sampling semantics match the reference by default (temperature-1
    categorical) with temperature/top-k/top-p extensions.

`generate_text` mirrors the reference CLI entry
(`/root/reference/scripts/generate_text.py:7-46`): load checkpoint, rebuild
model from its stored config, encode with GPT-2 BPE, generate, decode.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import Config, ModelConfig
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.generation.sampling import sample_logits


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "prompt_len", "temperature", "top_k", "top_p"),
)
def _generate_jit(
    params: Any,
    prompt: jax.Array,  # (B, P) padded prompt
    prompt_len: int,
    key: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    temperature: float,
    top_k: Optional[int],
    top_p: Optional[float],
) -> jax.Array:
    b = prompt.shape[0]
    total = prompt_len + max_new_tokens
    cache = transformer.make_kv_cache(cfg, b, total)

    # Prefill: one forward over the whole prompt.
    logits, cache = transformer.forward(
        params, prompt, cfg, kv_cache=cache, cache_index=jnp.int32(0)
    )
    key, sub = jax.random.split(key)
    next_tok = sample_logits(
        logits[:, prompt_len - 1], sub, temperature=temperature, top_k=top_k, top_p=top_p
    )

    def decode_step(carry, _):
        cache, tok, key, index = carry
        logits, cache = transformer.forward(
            params, tok[:, None], cfg, kv_cache=cache, cache_index=index
        )
        key, sub = jax.random.split(key)
        nxt = sample_logits(
            logits[:, 0], sub, temperature=temperature, top_k=top_k, top_p=top_p
        )
        return (cache, nxt, key, index + 1), tok

    (_, _, _, _), toks = jax.lax.scan(
        decode_step,
        (cache, next_tok, key, jnp.int32(prompt_len)),
        None,
        length=max_new_tokens,
    )
    # Each step emits its carry-in token, so toks == the max_new_tokens
    # sampled ids in order (the final carry token is the unused n+1-th).
    return toks.T


def generate(
    params: Any,
    cfg: ModelConfig,
    prompt_tokens: jax.Array,
    max_new_tokens: int,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """Generate continuations. prompt_tokens: (B, P) or (P,) int32.

    Returns (B, max_new_tokens) of sampled ids. The whole prompt+generation
    must fit the model context (the KV cache is position-table bound).
    """
    prompt = jnp.atleast_2d(jnp.asarray(prompt_tokens, jnp.int32))
    prompt_len = int(prompt.shape[1])
    if prompt_len + max_new_tokens > cfg.context_length:
        raise ValueError(
            f"prompt({prompt_len}) + max_new_tokens({max_new_tokens}) exceeds "
            f"context_length={cfg.context_length}"
        )
    return _generate_jit(
        params, prompt, prompt_len, key, cfg, max_new_tokens, temperature, top_k, top_p
    )


# ---------------------------------------------------------------------------
# Checkpoint-driven text generation (CLI surface)
# ---------------------------------------------------------------------------


def load_model_for_inference(model_path: str) -> Tuple[Any, Config]:
    """Load params + config from a framework checkpoint directory."""
    from pretraining_llm_tpu.training import checkpoint as ckpt

    path = model_path
    if not path.rstrip("/").split("/")[-1].startswith("step-"):
        latest = ckpt.latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
        path = latest
    with open(f"{path}/metadata.json") as f:
        meta = json.load(f)
    cfg = Config.from_json(json.dumps(meta["extra"]["config"]))
    # Shape-only template: no throwaway init of the full model.
    template = jax.eval_shape(
        lambda: {"params": transformer.init_params(cfg.model, jax.random.key(0))}
    )
    restored, _ = ckpt.load_checkpoint(path, template)
    return jax.device_put(restored["params"]), cfg


def generate_text(
    model_path: str,
    input_text: str,
    max_new_tokens: int = 100,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    seed: int = 0,
    tokenizer: Optional[str] = None,
) -> str:
    """Mirror of the reference's `generate_text(model_path, input_text,
    max_new_tokens)` (generate_text.py:7): checkpoint -> text continuation.

    `tokenizer` overrides the name stored in the checkpoint's config (e.g. a
    checkpoint trained elsewhere whose BPE files aren't available here)."""
    from pretraining_llm_tpu.data.tokenizer import get_tokenizer

    params, cfg = load_model_for_inference(model_path)
    enc = get_tokenizer(tokenizer or cfg.data.tokenizer_name)
    ids = np.asarray(enc.encode_ordinary(input_text), np.int32)[None, :]
    out = generate(
        params,
        cfg.model,
        ids,
        max_new_tokens,
        jax.random.key(seed),
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
    )
    return input_text + enc.decode(np.asarray(out[0]).tolist())
