"""Paged KV cache: block pool + tables for continuous-batching serving.

The contiguous cache (`models.transformer.make_kv_cache`) sizes every row
for the worst case and fixes the batch at compile time — fine for offline
generation, wasteful for serving, where requests of wildly different
lengths come and go. The paged layout decouples memory from batch rows:

  - K/V live in a shared POOL of fixed-size blocks
    ((L, n_blocks, block_size, G, Dh), `make_paged_kv_pool`);
  - each live request owns an ordered list of pool block ids — a row of
    the int32 ``block_tables`` — plus its logical length in ``seq_lens``;
  - the decode program (`paged_decode_step`) is compiled ONCE for the
    engine's (max_batch, max_blocks) shape: admission, growth, and
    eviction only edit int32 tables host-side.

This is vLLM's PagedAttention memory model re-expressed for XLA: block
tables are gather/scatter indices into statically-shaped pools, not
pointers (the CUDA kernel's pointer-chasing would defeat XLA tiling).
Attention reads ride one `pool[tables]` gather per layer — the same HBM
bytes the dense ragged-decode path reads for an equal total length.

The reference has no serving path at all (generate is batch-1, fixed
count: /root/reference/src/models/transformer.py:96-114); this module +
`generation.serving` are beyond-reference capability.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.generation.sampling import (
    sample_logits,
    sample_logits_fused,
)
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.models.transformer import PagedInfo

# Pool-key names <- their contiguous-cache counterparts (prefill writes a
# dense per-request cache, then scatters its pages into the pools).
_POOL_OF_DENSE = {
    "k": "k_pool",
    "v": "v_pool",
    "k_scale": "k_scale_pool",
    "v_scale": "v_scale_pool",
}


def required_blocks(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache slots."""
    return -(-n_tokens // block_size)


def check_paged_bounds(block_tables, seq_lens, block_size: int) -> None:
    """Host-side guard for the PagedInfo capacity invariant: a decode step
    WRITES slot seq_len, so seq_len == max_blocks*block_size would clamp
    the page index onto the row's LAST table entry and silently overwrite
    a live block (jit gathers clamp, they don't raise). Call before
    dispatching paged_decode_step whenever you build tables yourself."""
    import numpy as np

    tables = np.asarray(block_tables)
    seq = np.asarray(seq_lens)
    cap = tables.shape[-1] * block_size
    if (seq >= cap).any() or (seq < 0).any():
        bad = np.nonzero((seq >= cap) | (seq < 0))[0].tolist()
        raise ValueError(
            f"paged rows {bad} violate 0 <= seq_len < capacity={cap}: a "
            f"step would overwrite a live block (seq_lens={seq[bad]})"
        )


class BlockAllocator:
    """Host-side free-list over pool block ids. Block 0 is reserved as the
    idle-row scratch target (see make_paged_kv_pool) and never handed out.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("need n_blocks >= 2 (block 0 is reserved)")
        self.n_blocks = n_blocks
        # LIFO free list: recently-freed blocks are reused first, keeping
        # the hot working set of pool pages small.
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._live: set = set()

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n block ids, or None if the pool cannot cover them (all-or-
        nothing: a partial grant would deadlock admission)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._live.update(ids)
        return ids

    def alloc_upto(self, n: int) -> List[int]:
        """Up to ``n`` block ids — possibly fewer, possibly empty. The
        opportunistic multi-window page-horizon path: the pipelined serving
        scheduler pre-grows rows toward ``window * pipeline_depth`` write
        slots from the free list only, so a page flush never has to land
        between an already-dispatched window and its reap. Grants beyond a
        row's true need are speculative; callers roll them back with
        ``free()`` (release, preemption, or the reclaim pass)."""
        if n < 0:
            raise ValueError(f"alloc_upto({n})")
        ids = [self._free.pop() for _ in range(min(n, len(self._free)))]
        self._live.update(ids)
        return ids

    def free(self, ids: Sequence[int]) -> None:
        for i in ids:
            if i not in self._live:
                raise ValueError(f"double free / foreign block id {i}")
            self._live.discard(i)
            self._free.append(i)


def _scatter_staged_pages(
    pools: transformer.KVCache,
    dense_cache: transformer.KVCache,
    flat_ids: jax.Array,  # (n_rows * n_pages,) int32 pool block ids
    n_chunks: int,  # n_rows * n_pages (static)
) -> transformer.KVCache:
    """ONE definition of the staged-cache -> pool page scatter, shared by
    the single-prompt and batched admission prefills. The staged cache is
    STACKED ((L, N, n_pages*bs, ...) fields); each field is cut into
    ``n_chunks`` pages and scattered at ``flat_ids`` (pad pages point at
    the reserved scratch block 0 — duplicate indices there are benign)."""

    def _fields(layer_pool, dense_layer):
        out = dict(layer_pool)
        scattered = 0
        for dense_key, pool_key in _POOL_OF_DENSE.items():
            if dense_key not in dense_cache:
                continue
            scattered += 1
            buf = dense_layer(dense_cache[dense_key])  # (N, P, ...) or (L, N, P, ...)
            lead = buf.shape[: buf.ndim - 4]  # () per-layer, (L,) stacked
            tail = buf.shape[-2:]
            pages = buf.reshape(lead + (n_chunks, -1) + tail)
            sel = (flat_ids,) if not lead else (slice(None), flat_ids)
            out[pool_key] = layer_pool[pool_key].at[sel].set(
                pages.astype(layer_pool[pool_key].dtype)
            )
        if not scattered:
            # A container-layout mismatch (e.g. an unstacked staging
            # cache) would otherwise silently prefill NOTHING.
            raise ValueError(
                f"no cache fields matched the pool mapping; staging cache "
                f"keys = {sorted(dense_cache)} (need the stacked layout)"
            )
        return out

    if "layers" in pools:
        return {
            "layers": tuple(
                _fields(pools["layers"][layer], lambda buf, _l=layer: buf[_l])
                for layer in range(len(pools["layers"]))
            )
        }
    return _fields(pools, lambda buf: buf)


@functools.partial(jax.jit, static_argnames=("n_pages",), donate_argnums=(0,))
def _scatter_pages(
    pools: transformer.KVCache,
    dense_cache: transformer.KVCache,
    block_ids: jax.Array,  # (n_pages,) int32
    n_pages: int,
) -> transformer.KVCache:
    """Scatter a (L, 1, n_pages*bs, ...) dense prefill cache into the pools
    (stacked or unstacked container) at ``block_ids``. Donated pools: the
    update is in-place on device. (The batch-1 form of
    ``_scatter_staged_pages``.)"""
    return _scatter_staged_pages(pools, dense_cache, block_ids, n_pages)


@functools.partial(jax.jit, static_argnames=("cfg", "p_bucket", "mesh"))
def _prefill_dense(
    params: Any,
    prompt: jax.Array,  # (1, p_bucket) int32, zero-padded
    prompt_len: jax.Array,  # () int32 — true length, traced
    cfg: ModelConfig,
    p_bucket: int,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """One causal forward over the padded prompt into a fresh dense cache
    sized exactly p_bucket. Returns (last real token's logits (V,), cache).

    Pad slots >= prompt_len hold garbage K/V, but in the paged layout the
    decode mask only exposes linear index j once j <= seq_len — and the
    decode write to slot seq_len lands BEFORE the mask exposes it, exactly
    the dense-prefill overwrite discipline (`generate._generate_jit`).
    """
    import dataclasses as _dc

    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    with activation_mesh(mesh):
        # The staging cache is consumed field-by-field by _scatter_pages
        # (reshape (L, 1, pages*bs, ...) -> pool pages), which needs the
        # STACKED container regardless of the model's decode default.
        cache = transformer.make_kv_cache(
            _dc.replace(cfg, decode_cache_layout="stacked"), 1, p_bucket
        )
        logits, cache = transformer.forward(
            params, prompt, cfg, kv_cache=cache, cache_index=jnp.int32(0)
        )
        idx = jnp.broadcast_to(
            (prompt_len - 1).astype(jnp.int32), (1, 1, logits.shape[-1])
        )
        last = jnp.take_along_axis(logits, idx, axis=1)[0, 0]
        return last, cache


def prefill_into_pool(
    params: Any,
    cfg: ModelConfig,
    pools: transformer.KVCache,
    prompt_ids: Sequence[int],
    block_ids: Sequence[int],
    *,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """Prefill one prompt and write its pages into the pool.

    ``block_ids`` must be exactly ceil(len(prompt)/block_size) pages
    (allocator output). Returns (last-token logits (V,) fp32, updated
    pools). Compiles once per page count, not per prompt length.
    """
    if "layers" in pools:
        block_size = int(pools["layers"][0]["k_pool"].shape[1])
    else:
        block_size = int(pools["k_pool"].shape[2])
    p = len(prompt_ids)
    if p == 0:
        raise ValueError("empty prompt")
    n_pages = required_blocks(p, block_size)
    if n_pages != len(block_ids):
        raise ValueError(
            f"prompt of {p} tokens needs exactly {n_pages} pages; got "
            f"{len(block_ids)} block ids"
        )
    p_bucket = n_pages * block_size
    prompt = jnp.zeros((1, p_bucket), jnp.int32)
    prompt = prompt.at[0, :p].set(jnp.asarray(prompt_ids, jnp.int32))
    last, dense = _prefill_dense(
        params, prompt, jnp.int32(p), cfg, p_bucket, mesh
    )
    pools = _scatter_pages(
        pools, dense, jnp.asarray(block_ids, jnp.int32), n_pages
    )
    return last, pools


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "p_bucket", "n_pages", "temperature", "top_k", "top_p",
        "min_p", "mesh",
    ),
    donate_argnums=(1,),
)
def _prefill_scatter_sample(
    params: Any,
    pools: transformer.KVCache,
    prompts: jax.Array,  # (N, p_bucket) int32, zero-padded rows
    prompt_lens: jax.Array,  # (N,) int32 — true lengths (>= 1)
    block_ids: jax.Array,  # (N, n_pages) int32 — 0 (scratch) for pad pages
    key: jax.Array,
    cfg: ModelConfig,
    p_bucket: int,
    n_pages: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """Batched admission in ONE device program: causal prefill over N
    padded prompts -> scatter every row's pages into the pools -> sample
    each row's first token. The per-request admission path paid one
    prefill program + one scatter + one host-synced sample PER request —
    N arrivals in a scheduling window cost N serialized tunnel round
    trips, the dominant term in the measured 8x serving/decode gap. Here
    N admissions are one dispatch and at most one sync (the engine defers
    even that in pipelined mode).

    Pad pages (rows shorter than the bucket) scatter to the reserved
    scratch block 0; duplicate scatter indices there are benign by the
    pool's scratch discipline. Pad ROWS (N rounded up to a bucket) carry
    all-zero tables and garbage tokens the caller slices away.
    """
    import dataclasses as _dc

    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    n_rows = prompts.shape[0]
    with activation_mesh(mesh):
        # Stacked staging cache regardless of the decode default — the
        # scatter consumes (L, N, pages*bs, ...) field layouts.
        cache = transformer.make_kv_cache(
            _dc.replace(cfg, decode_cache_layout="stacked"), n_rows, p_bucket
        )
        logits, cache = transformer.forward(
            params, prompts, cfg, kv_cache=cache, cache_index=jnp.int32(0)
        )
        idx = jnp.clip(prompt_lens - 1, 0, p_bucket - 1).astype(jnp.int32)
        last = jnp.take_along_axis(
            logits,
            jnp.broadcast_to(idx[:, None, None], (n_rows, 1, logits.shape[-1])),
            axis=1,
        )[:, 0]
        toks = sample_logits(
            last, key, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p,
        ).astype(jnp.int32)

        pools = _scatter_staged_pages(
            pools, cache, block_ids.reshape(-1), n_rows * n_pages
        )
        return toks, pools


def prefill_into_pool_batched(
    params: Any,
    cfg: ModelConfig,
    pools: transformer.KVCache,
    prompts: Sequence[Sequence[int]],
    rows_block_ids: Sequence[Sequence[int]],
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """Prefill N prompts and write all their pages into the pool in one
    device program; returns (first sampled token per prompt — a DEVICE
    (N,) int32 array, no host sync — and the updated pools).

    ``rows_block_ids[i]`` must be exactly ceil(len(prompts[i])/block_size)
    pages. Rows and pages are bucketed to powers of two so the jit cache
    stays at O(log(max_batch) * log(max_pages)) program variants.
    """
    if "layers" in pools:
        block_size = int(pools["layers"][0]["k_pool"].shape[1])
    else:
        block_size = int(pools["k_pool"].shape[2])
    n = len(prompts)
    if n == 0:
        raise ValueError("no prompts")
    pages = []
    for i, (p, ids) in enumerate(zip(prompts, rows_block_ids)):
        if len(p) == 0:
            raise ValueError("empty prompt")
        np_i = required_blocks(len(p), block_size)
        if np_i != len(ids):
            raise ValueError(
                f"prompt {i} of {len(p)} tokens needs exactly {np_i} pages; "
                f"got {len(ids)} block ids"
            )
        pages.append(np_i)
    import numpy as np

    bucket_rows = 1 << (n - 1).bit_length()
    bucket_pages = 1 << (max(pages) - 1).bit_length()
    p_bucket = bucket_pages * block_size
    prompt_arr = np.zeros((bucket_rows, p_bucket), np.int32)
    lens = np.ones((bucket_rows,), np.int32)
    ids_arr = np.zeros((bucket_rows, bucket_pages), np.int32)
    for i, (p, ids) in enumerate(zip(prompts, rows_block_ids)):
        prompt_arr[i, : len(p)] = p
        lens[i] = len(p)
        ids_arr[i, : len(ids)] = ids
    toks, pools = _prefill_scatter_sample(
        params, pools, jnp.asarray(prompt_arr), jnp.asarray(lens),
        jnp.asarray(ids_arr), key, cfg, p_bucket, bucket_pages,
        temperature, top_k, top_p, min_p, mesh,
    )
    return toks[:n], pools


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "t_bucket", "temperature", "top_k", "top_p", "min_p", "mesh",
    ),
    donate_argnums=(1,),
)
def _suffix_prefill_sample(
    params: Any,
    pools: transformer.KVCache,
    suffix: jax.Array,  # (N, t_bucket) int32, zero-padded rows
    suffix_lens: jax.Array,  # (N,) int32 — true suffix lengths (>= 1)
    block_tables: jax.Array,  # (N, max_blocks) int32 — shared + private ids
    cached_lens: jax.Array,  # (N,) int32 — resident prefix length per row
    key: jax.Array,
    cfg: ModelConfig,
    t_bucket: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """Prefix-cache hit admission: ONE multi-token paged forward over each
    row's uncached suffix. Token j of row i writes its K/V at slot
    cached_lens[i] + j through the row's table (landing only in the row's
    PRIVATE suffix blocks — the hit cap guarantees cached_len is block-
    aligned and strictly below the prompt), while attention gathers the
    shared prefix pages read-only (the model's paged tq>1 branch masks
    lin <= pos per query). The first output token samples from the last
    real suffix position.

    Pad tokens (rows shorter than the bucket) write slots >= the prompt
    length — private pages above the frontier, overwritten by decode
    before the mask ever exposes them, or scratch-redirected past the
    table (the established slot-reuse discipline). Pad ROWS carry all-
    zero tables and cached_len 0, so every write scatters to the reserved
    scratch block 0.
    """
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    n_rows = suffix.shape[0]
    with activation_mesh(mesh):
        # q_lens rides along for the kernel attention path only (ragged
        # per-row DMA elision on TPU); the gather path ignores it, so CPU
        # outputs are bit-identical with or without it.
        logits, pools = transformer.forward(
            params, suffix, cfg, kv_cache=pools,
            paged=PagedInfo(block_tables, cached_lens, q_lens=suffix_lens),
        )
        idx = jnp.clip(suffix_lens - 1, 0, t_bucket - 1).astype(jnp.int32)
        last = jnp.take_along_axis(
            logits,
            jnp.broadcast_to(idx[:, None, None], (n_rows, 1, logits.shape[-1])),
            axis=1,
        )[:, 0]
        toks = sample_logits(
            last, key, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p,
        ).astype(jnp.int32)
        return toks, pools


def prefill_suffix_into_pool_batched(
    params: Any,
    cfg: ModelConfig,
    pools: transformer.KVCache,
    suffixes: Sequence[Sequence[int]],
    tables_rows: Any,  # (N, max_blocks) int array — engine table rows
    cached_lens: Sequence[int],
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
    t_bucket: Optional[int] = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """Prefill ONLY the uncached suffixes of N prefix-cache-hit prompts in
    one device program; returns (first sampled token per row — a DEVICE
    (N,) int32 array, no host sync — and the updated pools).

    ``tables_rows[i]`` is row i's full block-table row (shared prefix
    blocks followed by private suffix blocks, zero-padded);
    ``cached_lens[i]`` its block-aligned resident prefix length. Rows and
    suffix lengths bucket to powers of two, mirroring
    ``prefill_into_pool_batched``'s jit-cache discipline.

    ``t_bucket`` pins the token-axis shape instead (chunked prefill: the
    engine feeds fixed-size chunks, so EVERY group — full chunks and the
    final tail alike — compiles ONE program per row bucket, where pow2
    length bucketing would recompile per novel prompt-length residue;
    see ServingEngine._dispatch_prefill_chunks).
    """
    import numpy as np

    n = len(suffixes)
    if n == 0:
        raise ValueError("no suffixes")
    if len(cached_lens) != n:
        raise ValueError(f"{n} suffixes but {len(cached_lens)} cached_lens")
    for i, s in enumerate(suffixes):
        if len(s) == 0:
            # The hit cap ((p-1)//bs blocks) makes this unreachable from
            # the engine; guard it for direct callers.
            raise ValueError(f"suffix {i} is empty (hit must be capped)")
    tables_np = np.asarray(tables_rows, np.int32)
    if tables_np.ndim != 2 or tables_np.shape[0] != n:
        raise ValueError(
            f"tables_rows must be (n={n}, max_blocks); got {tables_np.shape}"
        )
    max_t = max(len(s) for s in suffixes)
    bucket_rows = 1 << (n - 1).bit_length()
    if t_bucket is None:
        t_bucket = 1 << (max_t - 1).bit_length()
    elif t_bucket < max_t:
        raise ValueError(
            f"t_bucket={t_bucket} cannot hold a {max_t}-token suffix"
        )
    suf_arr = np.zeros((bucket_rows, t_bucket), np.int32)
    lens = np.ones((bucket_rows,), np.int32)
    tab_arr = np.zeros((bucket_rows, tables_np.shape[1]), np.int32)
    cl_arr = np.zeros((bucket_rows,), np.int32)
    for i, s in enumerate(suffixes):
        suf_arr[i, : len(s)] = s
        lens[i] = len(s)
        tab_arr[i] = tables_np[i]
        cl_arr[i] = int(cached_lens[i])
    toks, pools = _suffix_prefill_sample(
        params, pools, jnp.asarray(suf_arr), jnp.asarray(lens),
        jnp.asarray(tab_arr), jnp.asarray(cl_arr), key, cfg, t_bucket,
        temperature, top_k, top_p, min_p, mesh,
    )
    return toks[:n], pools


def _forward_sample_one(
    params, pools, tokens, block_tables, seq_lens, key, cfg,
    temperature, top_k, top_p, min_p, mesh=None, logprobs_k=0,
):
    """The single decode step both jitted entry points trace: forward one
    token per row through the paged cache, sample the next. Kept as ONE
    definition so the sps=1 and windowed paths can never diverge.

    Returns ``(next_token (B,), logprobs, pools)`` — ``logprobs`` is
    ``None`` unless ``logprobs_k > 0``, in which case it is the
    ``(values (B, k), ids (B, k))`` top-k log-softmax of the raw logits
    (the decode-fused host payload; see `sample_logits_fused`)."""
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    with activation_mesh(mesh):
        logits, pools = transformer.forward(
            params,
            tokens[:, None],
            cfg,
            kv_cache=pools,
            paged=PagedInfo(block_tables, seq_lens),
        )
        nxt, lp = sample_logits_fused(
            logits[:, 0], key, temperature=temperature, top_k=top_k,
            top_p=top_p, min_p=min_p, logprobs_k=logprobs_k,
        )
        return nxt.astype(jnp.int32), lp, pools


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "min_p", "mesh"),
    donate_argnums=(1,),
)
def paged_decode_step(
    params: Any,
    pools: transformer.KVCache,
    tokens: jax.Array,  # (B,) int32 — each row's previously sampled token
    block_tables: jax.Array,  # (B, max_blocks) int32
    seq_lens: jax.Array,  # (B,) int32
    key: jax.Array,
    cfg: ModelConfig,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """One lockstep decode step for every batch row (active or idle).

    Writes each row's token at its slot seq_len, attends over its blocks,
    samples the next token. Idle rows (table row all zeros, seq_len 0)
    scribble on the reserved scratch block and their sampled token is
    ignored by the engine. Donated pools: in-place scatter, no copy.
    (Kept as its own jit rather than paged_decode_steps(n=1): the raw
    ``key`` preserves the existing sps=1 sampling stream, where the scan
    would consume split(key, 1)[0].)
    """
    nxt, _, pools = _forward_sample_one(
        params, pools, tokens, block_tables, seq_lens, key, cfg,
        temperature, top_k, top_p, min_p, mesh,
    )
    return nxt, pools


@functools.partial(
    jax.jit,
    static_argnames=("cfg_t", "cfg_d", "k", "temperature", "mesh"),
    donate_argnums=(1, 2),
)
def paged_spec_round(
    params_t: Any,
    t_pools: transformer.KVCache,
    d_pools: transformer.KVCache,
    params_d: Any,
    tokens: jax.Array,  # (B,) int32 — each row's newest accepted token
    block_tables: jax.Array,  # (B, max_blocks) int32 — SHARED by both pools
    seq_lens: jax.Array,  # (B,) int32
    key: jax.Array,
    cfg_t: ModelConfig,
    cfg_d: ModelConfig,
    k: int,
    temperature: float = 0.0,
    mesh: Any = None,
) -> Tuple[jax.Array, jax.Array, transformer.KVCache, transformer.KVCache]:
    """One speculative round for every batch row over the paged pools:
    k single-token DRAFT steps propose, then the target VERIFIES all k in
    one (k+1)-token multi-token paged forward (models/transformer.py's
    tq>1 paged branch). Returns (emit (B, k+1), n_emit (B,), t_pools,
    d_pools): row b's valid output is emit[b, :n_emit[b]], between 1 and
    k+1 tokens (the accepted prefix + the target's correction/bonus).

    Both pools share ONE block table and frontier: page p of a request
    holds target K/V in the target pool and draft K/V in the draft pool
    (the allocator hands out ids once — the draft cache needs no second
    bookkeeping). Rejected slots hold garbage above the new frontier and
    are overwritten by the next round's writes, the same slot-reuse
    discipline as the contiguous speculative path
    (generation/speculative.py).

    Greedy (temperature=0) output equals target-only paged decoding row
    for row; sampling uses the Leviathan accept/reject rule vectorized
    over rows.
    """
    from pretraining_llm_tpu.generation.speculative import _probs
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    b = tokens.shape[0]
    v = cfg_t.vocab_size

    with activation_mesh(mesh):
        # --- draft: k proposal steps (no extra write-only step needed —
        # paged writes land at seq+j each step, and the verify below
        # covers the same slots in the draft's NEXT round implicitly
        # because slot reuse overwrites garbage).
        def draft_step(carry, j):
            d_pools, tok, key = carry
            key, sub = jax.random.split(key)
            logits, d_pools = transformer.forward(
                params_d, tok[:, None], cfg_d, kv_cache=d_pools,
                paged=transformer.PagedInfo(block_tables, seq_lens + j),
            )
            q_dist = jax.vmap(lambda l: _probs(l, temperature))(
                logits[:, 0]
            )  # (B, V)
            if temperature == 0.0:
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(
                    sub, logits[:, 0].astype(jnp.float32) / temperature
                ).astype(jnp.int32)
            return (d_pools, nxt, key), (nxt, q_dist)

        (d_pools, d_last, key), (drafts, q_dists) = jax.lax.scan(
            draft_step, (d_pools, tokens, key), jnp.arange(k)
        )
        drafts = drafts.T  # (B, k)
        q_dists = jnp.moveaxis(q_dists, 0, 1)  # (B, k, V)

        # Write-only parking step (same as the contiguous path): the k-th
        # proposal's K/V must reach slot seq+k, or an all-accept round
        # leaves the next round's draft attending a stale slot — output
        # stays correct either way (acceptance always verifies against
        # the target), but the draft's hit rate would silently degrade.
        _, d_pools = transformer.forward(
            params_d, d_last[:, None], cfg_d, kv_cache=d_pools,
            paged=transformer.PagedInfo(block_tables, seq_lens + k),
        )

        # --- target: verify last + k drafts in ONE multi-token forward
        seq_tokens = jnp.concatenate(
            [tokens[:, None], drafts], axis=1
        )  # (B, k+1)
        t_logits, t_pools = transformer.forward(
            params_t, seq_tokens, cfg_t, kv_cache=t_pools,
            paged=transformer.PagedInfo(block_tables, seq_lens),
        )  # (B, k+1, V)
        p_dists = jax.vmap(
            jax.vmap(lambda l: _probs(l, temperature))
        )(t_logits)  # (B, k+1, V)

        # --- accept / reject (vectorized over rows) -------------------
        key, sub_u, sub_r = jax.random.split(key, 3)
        rows = jnp.arange(b)[:, None]
        cols = jnp.arange(k)[None, :]
        p_at = p_dists[rows, cols, drafts]  # (B, k)
        q_at = q_dists[rows, cols, drafts]
        if temperature == 0.0:
            accepts = p_at > 0.0
        else:
            u = jax.random.uniform(sub_u, (b, k))
            accepts = u < jnp.minimum(1.0, p_at / jnp.maximum(q_at, 1e-30))
        n_acc = jnp.sum(
            jnp.cumprod(accepts.astype(jnp.int32), axis=1), axis=1
        ).astype(jnp.int32)  # (B,)

        p_final = p_dists[jnp.arange(b), n_acc]  # (B, V)
        if temperature == 0.0:
            final = jnp.argmax(p_final, axis=-1).astype(jnp.int32)
        else:
            q_pad = jnp.concatenate(
                [q_dists, jnp.zeros((b, 1, v), jnp.float32)], axis=1
            )
            resid = jnp.maximum(p_final - q_pad[jnp.arange(b), n_acc], 0.0)
            resid = resid / jnp.maximum(
                jnp.sum(resid, axis=-1, keepdims=True), 1e-30
            )
            final = jax.random.categorical(
                sub_r, jnp.log(resid + 1e-30)
            ).astype(jnp.int32)

        emit = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1
        )  # (B, k+1)
        emit = emit.at[jnp.arange(b), n_acc].set(final)
        return emit, n_acc + 1, t_pools, d_pools


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "temperature", "top_k", "top_p",
                     "min_p", "mesh"),
    donate_argnums=(1,),
)
def paged_decode_steps(
    params: Any,
    pools: transformer.KVCache,
    tokens: jax.Array,  # (B,) int32
    block_tables: jax.Array,  # (B, max_blocks) int32
    seq_lens: jax.Array,  # (B,) int32
    key: jax.Array,
    cfg: ModelConfig,
    n_steps: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """``n_steps`` lockstep decode steps in ONE device program.

    Multi-step scheduling: per-step host dispatch dominates a serving
    engine on a high-latency link (the tunneled backend pays ~ms per
    call), so the scheduler runs a fixed window of steps per dispatch and
    reaps/admits only at window boundaries. Rows that finish mid-window
    keep decoding into their own (pre-allocated, then freed) pages and
    the host discards the surplus tokens; rows that pass their table
    capacity redirect writes to the scratch block (see the overshoot
    guard in the model's paged branch). The scheduler must pre-allocate
    pages covering seq_len + n_steps writes per surviving row
    (ServingEngine._ensure_write_pages horizon).

    Returns ((B, n_steps) sampled tokens in order, updated pools).
    """

    def one(carry, sub):
        pools, tok, seq = carry
        nxt, _, pools = _forward_sample_one(
            params, pools, tok, block_tables, seq, sub, cfg,
            temperature, top_k, top_p, min_p, mesh,
        )
        return (pools, nxt, seq + 1), nxt

    subs = jax.random.split(key, n_steps)
    (pools, _, _), toks = jax.lax.scan(
        one, (pools, tokens, seq_lens), subs
    )
    return toks.T, pools  # (B, n_steps)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "min_p",
                     "mesh", "logprobs_k"),
    donate_argnums=(1,),
)
def paged_decode_step_lp(
    params: Any,
    pools: transformer.KVCache,
    tokens: jax.Array,  # (B,) int32
    block_tables: jax.Array,  # (B, max_blocks) int32
    seq_lens: jax.Array,  # (B,) int32
    key: jax.Array,
    cfg: ModelConfig,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
    logprobs_k: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array, transformer.KVCache]:
    """`paged_decode_step` plus the top-k logprob payload (raw ``key``,
    preserving the sps=1 sampling stream exactly like its twin).
    Returns ``(tokens (B,), lp_values (B, k), lp_ids (B, k), pools)``."""
    nxt, lp, pools = _forward_sample_one(
        params, pools, tokens, block_tables, seq_lens, key, cfg,
        temperature, top_k, top_p, min_p, mesh, logprobs_k=logprobs_k,
    )
    return nxt, lp[0], lp[1], pools


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "temperature", "top_k", "top_p",
                     "min_p", "mesh", "logprobs_k"),
    donate_argnums=(1,),
)
def paged_decode_steps_lp(
    params: Any,
    pools: transformer.KVCache,
    tokens: jax.Array,  # (B,) int32
    block_tables: jax.Array,  # (B, max_blocks) int32
    seq_lens: jax.Array,  # (B,) int32
    key: jax.Array,
    cfg: ModelConfig,
    n_steps: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    mesh: Any = None,
    logprobs_k: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array, transformer.KVCache]:
    """`paged_decode_steps` with the top-k logprob payload.

    Same scan, same key stream (split(key, n_steps)), same token
    numerics — the ONLY addition is the per-step (values, ids) top-k
    log-softmax of each step's raw logits, computed inside the same
    device program so the host still receives token ids + a (B, n, k)
    sliver instead of (B, n, V) logits.

    Returns ``(tokens (B, n_steps), lp_values (B, n_steps, k) f32,
    lp_ids (B, n_steps, k) int32, pools)``.
    """

    def one(carry, sub):
        pools, tok, seq = carry
        nxt, lp, pools = _forward_sample_one(
            params, pools, tok, block_tables, seq, sub, cfg,
            temperature, top_k, top_p, min_p, mesh,
            logprobs_k=logprobs_k,
        )
        return (pools, nxt, seq + 1), (nxt, lp[0], lp[1])

    subs = jax.random.split(key, n_steps)
    (pools, _, _), (toks, lp_vals, lp_ids) = jax.lax.scan(
        one, (pools, tokens, seq_lens), subs
    )
    return (
        toks.T,  # (B, n_steps)
        lp_vals.transpose(1, 0, 2),  # (B, n_steps, k)
        lp_ids.transpose(1, 0, 2),
        pools,
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "mesh"),
    donate_argnums=(1,),
)
def paged_decode_logits(
    params: Any,
    pools: transformer.KVCache,
    tokens: jax.Array,  # (B,) int32
    block_tables: jax.Array,  # (B, max_blocks) int32
    seq_lens: jax.Array,  # (B,) int32
    cfg: ModelConfig,
    mesh: Any = None,
) -> Tuple[jax.Array, transformer.KVCache]:
    """UNFUSED decode forward: one step, raw (B, V) last-position logits.

    The measurement/fallback lane for decode-fused sampling: forward
    only, with token selection left to a SEPARATE `sample_tokens`
    dispatch — exactly the extra device→host logits round-trip the fused
    path (`paged_decode_step[s]` / `_lp`) eliminates. The serving engine
    keeps this lane wired (``fused_sampling=False``) so fused-vs-unfused
    greedy bit-identity stays testable and the transfer win stays
    benchable.
    """
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    with activation_mesh(mesh):
        logits, pools = transformer.forward(
            params,
            tokens[:, None],
            cfg,
            kv_cache=pools,
            paged=PagedInfo(block_tables, seq_lens),
        )
    return logits[:, 0].astype(jnp.float32), pools


@functools.partial(
    jax.jit,
    static_argnames=("temperature", "top_k", "top_p", "min_p"),
)
def sample_tokens(
    logits: jax.Array,  # (B, V) f32
    key: jax.Array,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
) -> jax.Array:
    """The unfused lane's second dispatch: `sample_logits` as its own
    jitted program over host-visible logits. Same math as the fused
    in-program sampling (JAX PRNG is jit-boundary invariant), so fused
    vs unfused token streams are bit-identical given identical logits."""
    return sample_logits(
        logits, key, temperature=temperature, top_k=top_k, top_p=top_p,
        min_p=min_p,
    )
