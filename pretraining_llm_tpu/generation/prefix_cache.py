"""Cross-request prefix cache: content-addressed, copy-on-write paged-KV reuse.

At serving scale most traffic shares long common prefixes (system
prompts, few-shot templates), yet every admission prefills its whole
prompt from scratch. The paged pool already gives block-granular KV
(generation/paged.py) — this module shares those blocks ACROSS requests:

  identity    every FULL block of a finished request's committed history
              gets a chained content hash (blake2b over the block's token
              ids + the parent block's digest), so a block's identity
              encodes its entire prefix — two requests agree on block j
              iff they agree on every token up to and including it;
  reuse       admission walks the new prompt's block chain through the
              index and maps the longest cached run READ-ONLY into the
              row's block table; only the uncached suffix is prefilled
              (ServingEngine._admit / paged.prefill_suffix_into_pool_batched);
  copy-on-write
              the hit is capped so at least the prompt's final token is
              prefilled privately: decode writes slot seq_len, so the
              divergence point always lands in a FRESH private block —
              a shared page is never written in place;
  lifecycle   shared blocks carry a live-row refcount; at release the
              row's refs drop and its own full committed blocks are
              PUBLISHED into the index. Refcount-0 blocks stay resident
              in an LRU ("cold") list — still owned in the allocator's
              ``_live`` set, so speculative ``alloc_upto`` grants can
              never cannibalize them — and are evicted back to the free
              list only under pool pressure, BEFORE any live request is
              preempted.

Correctness story: greedy outputs with the cache on are bit-identical to
cache off (the survivor-identity pattern; tests/test_prefix_cache.py).
Publishing is safe under deep pipelining because a finished row's
surplus in-flight windows only write slots at or above its committed
content frontier, and only blocks wholly BELOW that frontier are ever
published.

All host-side. ``peek`` is called from gateway threads (the admission
discount hint) while the engine thread mutates — one lock guards every
public method.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pretraining_llm_tpu.generation.paged import BlockAllocator

# Engine-stats keys this cache maintains (mirrored as typed counters when
# bind() attaches a MetricsRegistry).
STAT_KEYS = (
    "prefix_cache_hits",
    "prefix_cache_misses",
    "prefix_cache_hit_tokens",
    "prefix_cache_evicted_blocks",
)


class PrefixCache:
    """Content-addressed index + refcount layer over a ``BlockAllocator``.

    The cache never allocates blocks itself; it only (a) answers "which
    resident blocks already hold this prompt's prefix", (b) tracks who
    references them, and (c) hands cold blocks back to the allocator on
    demand (``evict``). Cached-but-unreferenced blocks remain ``_live``
    in the allocator — the free list never contains a cached block, so
    every existing allocation path stays oblivious and structurally
    unable to reuse a page the LRU has not released.
    """

    def __init__(
        self,
        alloc: BlockAllocator,
        block_size: int,
        *,
        min_blocks: int = 1,
        stats: Optional[Dict[str, Any]] = None,
    ) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if min_blocks < 1:
            raise ValueError(f"min_blocks must be >= 1, got {min_blocks}")
        self.alloc = alloc
        self.block_size = int(block_size)
        self.min_blocks = int(min_blocks)
        self._lock = threading.Lock()
        self._index: Dict[bytes, int] = {}     # chain digest -> block id
        self._hash_of: Dict[int, bytes] = {}   # block id -> chain digest
        self._ref: Dict[int, int] = {}         # block id -> live-row refcount
        # Refcount-0 cached blocks, LRU order (oldest first — evict from
        # the front, re-publish/release at the back).
        self._cold: "OrderedDict[int, bytes]" = OrderedDict()
        # Optional content checksums (``kv_checksum``): block id -> digest
        # of the block's POOL BYTES at publish time (the engine computes
        # them; the cache only stores/serves them). Verified on acquire;
        # a mismatch drops the block via ``drop_block``.
        self._checksums: Dict[int, str] = {}
        # Blocks dropped for corruption while still referenced by live
        # rows: unreachable from the index already; the final deref frees
        # them to the allocator instead of re-coldlisting a known-bad page.
        self._doomed: set = set()
        # Tallies live in the caller's dict (the engine's ``stats``) so
        # serve.py/bench.py records and EngineLoop.metrics() see them for
        # free; typed counters attach via bind().
        self.stats: Dict[str, Any] = stats if stats is not None else {}
        for k in STAT_KEYS:
            self.stats.setdefault(k, 0)
        self._c_hits = self._c_misses = None
        self._c_hit_tokens = self._c_evicted = None
        self._g_cached = None

    # -- observability -----------------------------------------------------

    def bind(self, registry: Any) -> None:
        """Attach typed metrics (observability.metrics.MetricsRegistry):
        hit/miss/hit-token/eviction counters + a cached-blocks gauge.
        Counters advance alongside the untyped ``stats`` tallies."""
        self._c_hits = registry.counter(
            "prefix_cache_hits_total", "admissions that reused cached prefix blocks")
        self._c_misses = registry.counter(
            "prefix_cache_misses_total", "admissions with no cached prefix")
        self._c_hit_tokens = registry.counter(
            "prefix_cache_hit_tokens_total",
            "prompt tokens served from cache instead of prefill")
        self._c_evicted = registry.counter(
            "prefix_cache_evicted_blocks_total",
            "cold cached blocks returned to the pool under pressure")
        self._g_cached = registry.gauge(
            "prefix_cache_cached_blocks", "pool blocks resident in the prefix cache")
        self._sync_gauge()

    def _sync_gauge(self) -> None:
        if self._g_cached is not None:
            self._g_cached.set(len(self._index))

    def note_hit(self, cached_tokens: int) -> None:
        """Count one COMMITTED hit admission (the engine calls this only
        after the watermark passed and the row is claimed, so a stalled
        head retried every boundary does not inflate the hit rate)."""
        self.stats["prefix_cache_hits"] += 1
        self.stats["prefix_cache_hit_tokens"] += int(cached_tokens)
        if self._c_hits is not None:
            self._c_hits.inc()
            self._c_hit_tokens.inc(int(cached_tokens))

    def note_miss(self) -> None:
        self.stats["prefix_cache_misses"] += 1
        if self._c_misses is not None:
            self._c_misses.inc()

    # -- queries -----------------------------------------------------------

    @property
    def evictable(self) -> int:
        """Cold (refcount-0) cached blocks the pool can reclaim on demand."""
        with self._lock:
            return len(self._cold)

    @property
    def cached_blocks(self) -> int:
        """All indexed blocks: cold + shared by live rows."""
        with self._lock:
            return len(self._index)

    def peek(self, prompt: Sequence[int]) -> int:
        """Longest cached block-aligned prefix of ``prompt``, in TOKENS —
        no side effects, no refcounts. The frontend's admission-discount
        hint; safe from any thread."""
        with self._lock:
            return len(self._hit_blocks(prompt)) * self.block_size

    def debug_snapshot(self) -> Dict[str, int]:
        """Block accounting for /debug/engine: all indexed blocks, the
        cold (evictable) subset, and the live-shared remainder — one lock
        acquisition so the three numbers are mutually consistent."""
        with self._lock:
            cached = len(self._index)
            cold = len(self._cold)
        return {"cached": cached, "cold": cold, "shared": cached - cold}

    # -- admission-side lifecycle ------------------------------------------

    def acquire(self, prompt: Sequence[int]) -> Tuple[int, List[int]]:
        """Retain the longest cached block-aligned prefix of ``prompt``.
        Returns ``(cached_tokens, block_ids)``; each returned block's
        refcount is bumped (cold blocks leave the LRU). The caller maps
        the ids read-only into the row's table — or hands them back via
        ``release_shared`` if admission stalls after all."""
        with self._lock:
            ids = self._hit_blocks(prompt)
            for b in ids:
                n = self._ref.get(b, 0)
                if n == 0:
                    self._cold.pop(b, None)
                self._ref[b] = n + 1
            return len(ids) * self.block_size, ids

    def release_shared(self, block_ids: Sequence[int]) -> None:
        """Drop one reference per block (the un-acquire path for a stalled
        admission). Refcount-0 blocks rejoin the cold LRU as most recent."""
        with self._lock:
            for b in block_ids:
                self._deref(b)

    def release_row(
        self,
        history: Sequence[int],
        blocks: Sequence[int],
        n_shared: int,
        publish_len: int,
    ) -> List[int]:
        """Release a finished/preempted/cancelled row's blocks.

        ``history`` is the row's prompt + generated tokens; ``blocks`` its
        table entries in order (the first ``n_shared`` are shared prefix
        blocks); ``publish_len`` the count of LEADING slots whose pool
        content is committed (the engine passes p + g - 1: the last
        sampled token's K/V may never have been written, and surplus
        in-flight windows only write at or above that frontier).

        Shared blocks are deref'd. Private blocks wholly below
        ``publish_len`` are published into the index (duplicates of an
        already-indexed chain go back to the allocator instead — first
        writer wins, content is identical by construction). Everything
        else — the partial tail block and speculative over-grants — is
        freed. Returns the NEWLY published block ids, so a checksumming
        engine knows exactly which pages to digest."""
        with self._lock:
            for b in blocks[:n_shared]:
                self._deref(b)
            bs = self.block_size
            n_pub = min(max(publish_len, 0) // bs, len(blocks))
            to_free: List[int] = list(blocks[max(n_shared, n_pub):])
            published: List[int] = []
            digest = b""
            for j in range(n_pub):
                digest = self._chain(digest, history[j * bs:(j + 1) * bs])
                if j < n_shared:
                    continue  # already indexed (we matched it on acquire)
                b = blocks[j]
                if digest in self._index:
                    to_free.append(b)
                else:
                    self._index[digest] = b
                    self._hash_of[b] = digest
                    self._cold[b] = digest  # ref 0, most-recently-used
                    published.append(b)
            if to_free:
                self.alloc.free(to_free)
            self._sync_gauge()
            return published

    # -- integrity (resilience/integrity.py; ``kv_checksum``) --------------

    def set_checksum(self, block: int, digest: str) -> None:
        """Record a published block's pool-content digest (engine-computed
        at publish; see ServingEngine._release_row). Ignored for blocks
        that already left the index — publish and eviction can race only
        in the sense that eviction wins."""
        with self._lock:
            if block in self._hash_of:
                self._checksums[block] = digest

    def checksum_of(self, block: int) -> Optional[str]:
        """The digest recorded at publish, or None (checksumming off when
        it was published, or the block is gone)."""
        with self._lock:
            return self._checksums.get(block)

    def cached_block_ids(self) -> List[int]:
        """All indexed block ids, sorted (deterministic corruption-drill
        targeting + integrity sweeps)."""
        with self._lock:
            return sorted(self._hash_of)

    def drop_block(self, block: int) -> None:
        """Remove one block from the cache because its CONTENT failed
        verification. Unlike ``evict`` this takes a block in any state:
        a cold block is freed to the allocator immediately; a block still
        referenced by live rows just becomes unreachable (no future hit
        can map it) and is freed — not re-coldlisted — on its final
        deref. Idempotent for already-dropped blocks."""
        with self._lock:
            digest = self._hash_of.pop(block, None)
            if digest is None:
                return
            self._index.pop(digest, None)
            self._checksums.pop(block, None)
            if block in self._cold:
                del self._cold[block]
                self.alloc.free([block])
            else:
                self._doomed.add(block)
            self._sync_gauge()

    # -- pressure ----------------------------------------------------------

    def evict(self, n: int) -> int:
        """Return up to ``n`` cold blocks to the allocator, least recently
        used first. Returns how many were evicted (0 = nothing cold:
        the caller escalates to preemption)."""
        freed: List[int] = []
        with self._lock:
            while len(freed) < n and self._cold:
                b, digest = self._cold.popitem(last=False)
                del self._index[digest]
                del self._hash_of[b]
                self._checksums.pop(b, None)
                freed.append(b)
            if freed:
                self.alloc.free(freed)
                self.stats["prefix_cache_evicted_blocks"] += len(freed)
                self._sync_gauge()
        if freed and self._c_evicted is not None:
            self._c_evicted.inc(len(freed))
        return len(freed)

    def flush(self) -> int:
        """Evict EVERYTHING cold (tests / drain checks). Live-shared
        blocks are untouched; returns the number evicted."""
        return self.evict(len(self._cold))

    # -- internals (call under self._lock) ---------------------------------

    @staticmethod
    def _chain(parent: bytes, block_tokens: Sequence[int]) -> bytes:
        """Chained block digest: parent digest + this block's token ids.
        Position falls out of the chain — block j's digest commits to the
        whole prefix, so a flat dict lookup IS longest-prefix matching."""
        h = hashlib.blake2b(parent, digest_size=16)
        h.update(np.asarray(block_tokens, dtype=np.int64).tobytes())
        return h.digest()

    def _hit_blocks(self, prompt: Sequence[int]) -> List[int]:
        """Resident block ids covering the longest cached prefix. Capped
        at (len(prompt) - 1) // block_size FULL blocks so at least one
        prompt token always prefills privately (the first-token logits
        must come from a real forward, and the block containing the first
        decode write stays copy-on-write private); hits shorter than
        ``min_blocks`` don't count."""
        bs = self.block_size
        cap = (len(prompt) - 1) // bs
        ids: List[int] = []
        digest = b""
        for j in range(cap):
            digest = self._chain(digest, prompt[j * bs:(j + 1) * bs])
            b = self._index.get(digest)
            if b is None:
                break
            ids.append(b)
        if len(ids) < self.min_blocks:
            return []
        return ids

    def _deref(self, b: int) -> None:
        n = self._ref.get(b)
        if n is None:
            raise ValueError(f"release of unreferenced block {b}")
        if n == 1:
            del self._ref[b]
            if b in self._doomed:
                # Dropped for corruption while shared: the last holder is
                # gone, so the page finally leaves the pool.
                self._doomed.discard(b)
                self.alloc.free([b])
            else:
                self._cold[b] = self._hash_of[b]  # most-recently-used end
        else:
            self._ref[b] = n - 1
