"""Token sampling: temperature / top-k / top-p / min-p, pure and jittable.

The reference samples with temperature-1 multinomial only
(`/root/reference/src/models/transformer.py:110-113`). That remains the
default; top-k, nucleus (top-p), and min-p sampling are the standard
extensions (min-p keeps tokens with prob >= min_p * max_prob — the
support adapts to the distribution's confidence instead of a fixed mass
or count).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids from (B, V) logits. temperature=0 -> greedy."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    # Integrity guard (sampling path only — greedy argmax of corrupt
    # logits still lands in-vocab and the golden probes own that case):
    # corrupted state surfaces as NaN/+inf logits, and categorical over
    # them returns an arbitrary IN-RANGE id — silent garbage. Flag such
    # rows before masking (the top-k/top-p/min-p filters introduce
    # legitimate -inf) and return -1 for them: out of vocab range, so the
    # serving engine's reap-time sanity check fails the request loudly
    # instead of streaming it. Fused elementwise+reduce on the existing
    # program — no extra sync, no effect on finite logits.
    bad = jnp.any(jnp.isnan(logits) | (logits == jnp.inf), axis=-1)
    logits = logits / temperature
    if min_p is not None and 0.0 < min_p <= 1.0:
        # Keep tokens whose prob >= min_p * max prob. In logit space:
        # logit >= max_logit + log(min_p) — no softmax materialization.
        cutoff = jnp.max(logits, axis=-1, keepdims=True) + jnp.log(min_p)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    do_top_k = top_k is not None and top_k > 0
    do_top_p = top_p is not None and 0.0 < top_p < 1.0
    if do_top_k:
        # k > V is a no-op filter (the old clamped sort-index agreed);
        # lax.top_k would reject it, so clamp statically.
        top_k = min(top_k, logits.shape[-1])
    if do_top_p:
        # One descending "sort" (lax.top_k over V) serves BOTH filters:
        # the k-th-largest threshold reads straight off it, and masking
        # the sorted copy with the same threshold keeps it exactly the
        # descending sort of the post-top-k logits (monotone masking
        # preserves order and any ties AT the threshold — the old
        # second full jnp.sort, without the second sort).
        sorted_desc = jax.lax.top_k(logits, logits.shape[-1])[0]
        if do_top_k:
            kth = sorted_desc[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
            sorted_desc = jnp.where(sorted_desc < kth, -jnp.inf, sorted_desc)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative mass >= top_p (always >= 1 token).
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff_logit = jnp.take_along_axis(sorted_desc, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff_logit, -jnp.inf, logits)
    elif do_top_k:
        # top-k alone never needs the full sort: an O(V·log k) partial
        # top-k finds the k-th largest value (same value-threshold mask
        # as sorting, ties included).
        kth = jax.lax.top_k(logits, top_k)[0][:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    sampled = jax.random.categorical(key, logits, axis=-1)
    return jnp.where(bad, jnp.int32(-1), sampled.astype(jnp.int32))


def sample_logits_fused(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    logprobs_k: int = 0,
) -> tuple:
    """`sample_logits` plus the decode-fused host payload.

    The fused decode step ships token ids (and, when ``logprobs_k > 0``,
    the top-k logprobs of the MODEL distribution — raw logits before
    temperature/filtering, the standard logprobs contract) back to the
    host instead of the (B, V) logits array. Token choice is
    `sample_logits` verbatim, so fused-vs-unfused greedy decode is
    bit-identical by construction.

    Returns ``(tokens (B,) int32, logprobs)`` where ``logprobs`` is
    ``None`` when ``logprobs_k == 0`` and otherwise a
    ``(values (B, k) f32, token_ids (B, k) int32)`` pair, values sorted
    descending.
    """
    tokens = sample_logits(
        logits, key, temperature=temperature, top_k=top_k, top_p=top_p,
        min_p=min_p,
    )
    if logprobs_k <= 0:
        return tokens, None
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    vals, ids = jax.lax.top_k(lp, logprobs_k)
    return tokens, (vals, ids.astype(jnp.int32))
