"""Token sampling: temperature / top-k / top-p / min-p, pure and jittable.

The reference samples with temperature-1 multinomial only
(`/root/reference/src/models/transformer.py:110-113`). That remains the
default; top-k, nucleus (top-p), and min-p sampling are the standard
extensions (min-p keeps tokens with prob >= min_p * max_prob — the
support adapts to the distribution's confidence instead of a fixed mass
or count).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids from (B, V) logits. temperature=0 -> greedy."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    # Integrity guard (sampling path only — greedy argmax of corrupt
    # logits still lands in-vocab and the golden probes own that case):
    # corrupted state surfaces as NaN/+inf logits, and categorical over
    # them returns an arbitrary IN-RANGE id — silent garbage. Flag such
    # rows before masking (the top-k/top-p/min-p filters introduce
    # legitimate -inf) and return -1 for them: out of vocab range, so the
    # serving engine's reap-time sanity check fails the request loudly
    # instead of streaming it. Fused elementwise+reduce on the existing
    # program — no extra sync, no effect on finite logits.
    bad = jnp.any(jnp.isnan(logits) | (logits == jnp.inf), axis=-1)
    logits = logits / temperature
    if min_p is not None and 0.0 < min_p <= 1.0:
        # Keep tokens whose prob >= min_p * max prob. In logit space:
        # logit >= max_logit + log(min_p) — no softmax materialization.
        cutoff = jnp.max(logits, axis=-1, keepdims=True) + jnp.log(min_p)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative mass >= top_p (always >= 1 token).
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff_logit = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff_logit, -jnp.inf, logits)
    sampled = jax.random.categorical(key, logits, axis=-1)
    return jnp.where(bad, jnp.int32(-1), sampled.astype(jnp.int32))
