"""Continuous-batching serving engine over the paged KV cache.

Offline generation (`generation.generate`) compiles one program per
(batch, bucket) and every row enters and leaves together. A serving
workload is the opposite: requests arrive whenever, finish whenever, and
the device must never idle waiting for the longest row. This engine keeps
ONE compiled lockstep decode program (`paged.paged_decode_step`, shape
(max_batch, max_blocks) fixed at construction) and mutates only host-side
int32 state between steps:

  admission   — a waiting request claims a free batch row + pool blocks,
                prefills its prompt into its pages, joins the next step;
  growth      — a row crossing a block boundary gets one more block;
  eviction    — a finished row frees its blocks and the row slot;
  preemption  — when the pool runs dry, the youngest running request is
                evicted and requeued (recompute-on-resume: its prompt +
                generated-so-far become the new prompt), so the oldest
                requests always run to completion — no deadlock.

TPU-first shape discipline: idle rows keep decoding into the reserved
scratch block (block 0) with their outputs ignored — a masked no-op is
cheaper than a recompile, and XLA sees a static (max_batch,) program
forever. The reference has no serving stack (batch-1 fixed-count
generate, /root/reference/src/models/transformer.py:96-114).

Deep pipelining: the run() scheduler keeps a depth-``pipeline_depth``
queue of dispatched-but-unreaped decode windows. Window k+1's input
tokens chain from window k's last column ON DEVICE, host ``seq_lens``
advance speculatively at dispatch, and the host reap/consume/admission
work for windows k-1, k-2, ... overlaps the device's execution of
window k. Speculation is reconciled by FLUSHING the queue (a synchronous
drain back to committed host state) whenever a decision needs exact
state — preemption and page reclaim — and replaying from there; events
the lag contract already absorbs (a row finishing early, a
sampling-dependent admission landing mid-queue) need no flush because
surplus tokens are discarded at reap by the snapshot identity check.
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.generation import paged, speculative
from pretraining_llm_tpu.generation import prefix_cache as prefix_cache_mod
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability import spans as _spans


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new: int
    generated: List[int] = dataclasses.field(default_factory=list)
    # Tokens generated in earlier incarnations of a preempted request:
    # they were folded into `prompt` for recompute-on-resume, but they
    # belong to the OUTPUT (see _preempt/_finish).
    prefix: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    # Leading entries of ``blocks`` that are SHARED prefix-cache pages
    # (read-only; refcounted by the cache, never freed directly).
    n_shared: int = 0
    row: Optional[int] = None
    admit_order: int = -1  # monotonically increasing per admission
    preemptions: int = 0
    # Pipelined admission: the first sampled token stays ON DEVICE as
    # (batch_array, index) until the window it joined is reaped — the
    # engine never syncs just to learn it (see _resolve_first).
    pending_first: Optional[tuple] = None
    # Chunked prefill: the next prompt index to prefill. None = decode
    # phase (the whole prompt is resident — monolithic admission, or the
    # final chunk landed). While set, the row joins NO decode window/spec
    # round: its committed frontier is mid-prompt, and lockstep garbage
    # writes for it land at/above that frontier, overwritten by the next
    # chunk before any mask exposes them (slot-reuse discipline).
    prefill_pos: Optional[int] = None

    @property
    def n_generated(self) -> int:
        """Generated count INCLUDING a not-yet-materialized first token —
        the value scheduling math (max_new countdown, page horizons) must
        use so deferred resolution never changes allocation decisions."""
        return len(self.generated) + (1 if self.pending_first is not None else 0)


@dataclasses.dataclass
class _Window:
    """One dispatched-but-unreaped unit of device work in the in-flight
    queue. ``snapshot`` pins the (row, request) pairs the window was
    dispatched against: at reap, rows whose identity changed since (the
    request finished in an earlier reap, possibly re-admitted) are surplus
    by the lag contract and their tokens are discarded."""

    kind: str                       # "decode" | "spec"
    snapshot: List[tuple]           # [(row, _Request)] at dispatch time
    n: int                          # decode: window length; spec: k+1 bound
    toks: Any = None                # decode: (B, n) device tokens
    lp: Any = None                  # decode: ((B, n, k) values, ids) device
    emit: Any = None                # spec: (B, k+1) device emissions
    n_emit: Any = None              # spec: (B,) device per-row emit counts
    seq_dev: Any = None             # spec: (B,) device frontier at dispatch
    t_dispatch: float = 0.0         # perf_counter at dispatch (trace spans)


class ServingEngine:
    """Continuous-batching text generation over a shared paged KV pool.

    Usage::

        eng = ServingEngine(params, cfg, max_batch=4, n_blocks=128)
        rid = eng.submit(prompt_ids, max_new_tokens=64)
        outputs = eng.run()        # {rid: [token, ...]}

    ``temperature=0`` (default) decodes greedily; sampling parameters are
    engine-global (per-request values would either recompile or pay a
    (B,)-vector mask per knob — the global default matches the common
    single-model deployment).
    """

    def __init__(
        self,
        params: Any,
        cfg: ModelConfig,
        *,
        max_batch: int = 8,
        n_blocks: int = 256,
        block_size: int = 64,
        max_seq: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        stop_token: Optional[int] = None,
        seed: int = 0,
        steps_per_sched: int = 1,
        pipeline_depth: int = 2,
        admit_batch: int = 0,
        prefill_chunk_tokens: int = 0,
        prefix_cache: bool = False,
        prefix_cache_min_blocks: int = 1,
        kv_checksum: bool = False,
        quantize: str = "none",
        mesh: Any = None,
        draft_params: Any = None,
        draft_cfg: Optional[ModelConfig] = None,
        spec_k: int = 0,
        fused_sampling: bool = True,
        logprobs_k: int = 0,
    ):
        if cfg.n_experts:
            # Same restriction as ragged generate: pad slots inside a
            # prefill bucket would compete for expert capacity.
            raise ValueError("paged serving does not support MoE models yet")
        if cfg.doc_mask_token >= 0:
            # Decode sessions are single documents; forward() rejects the
            # combination with a cache (same sanitization as generate()).
            cfg = dataclasses.replace(cfg, doc_mask_token=-1)
        # Speculative serving: a draft model proposes spec_k tokens per
        # round, the target verifies them in ONE multi-token paged
        # forward (paged.paged_spec_round). Greedy output equals
        # target-only serving; decode dispatches drop ~(k+1)x at the
        # draft's acceptance rate.
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if (spec_k > 0) != (draft_params is not None and draft_cfg is not None):
            raise ValueError(
                "speculative serving needs all three of draft_params, "
                "draft_cfg and spec_k >= 1 (or none of them)"
            )
        # Decode-fused sampling (default): token selection runs INSIDE
        # the jitted decode window, so each window ships (B, n) token ids
        # (plus an optional (B, n, k) logprob sliver) back to the host
        # instead of per-step (B, V) logits. fused_sampling=False keeps
        # the unfused lane wired — forward-only program, a full logits
        # device->host round-trip, then a separate sampling dispatch per
        # step — as the measurement/bit-identity reference (greedy output
        # is identical by construction; tests pin it).
        self.fused_sampling = bool(fused_sampling)
        if logprobs_k < 0:
            raise ValueError(f"logprobs_k must be >= 0, got {logprobs_k}")
        if logprobs_k and not fused_sampling:
            raise ValueError(
                "logprobs_k requires fused_sampling (the logprob sliver "
                "rides the fused decode payload)"
            )
        if spec_k and (not fused_sampling or logprobs_k):
            raise ValueError(
                "speculative serving supports only the fused decode path "
                "without logprobs (spec rounds never materialize "
                "per-token logits host-side)"
            )
        self.logprobs_k = int(logprobs_k)
        # Per-request top-k logprobs, keyed by rid, one entry per OUTPUT
        # token in order: (values, token_ids) lists of length logprobs_k,
        # or None for tokens sampled inside prefill programs (each
        # request's first token, incl. post-preemption restarts) — those
        # programs don't compute the sliver. Populated only when
        # logprobs_k > 0; aligned with the finished[rid] token list.
        self.logprobs: Dict[int, List[Optional[tuple]]] = {}
        self.spec_k = int(spec_k)
        self.draft_params = draft_params
        self.draft_cfg: Optional[ModelConfig] = None
        if spec_k:
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab ({draft_cfg.vocab_size}) must equal "
                    f"target vocab ({cfg.vocab_size})"
                )
            if draft_cfg.n_experts:
                raise ValueError("draft model cannot be MoE (same rule)")
            if top_k or top_p or min_p:
                raise ValueError(
                    "speculative serving supports temperature-only "
                    "sampling (the accept/reject rule needs the raw "
                    "draft/target distributions)"
                )
            if draft_cfg.doc_mask_token >= 0:
                draft_cfg = dataclasses.replace(draft_cfg, doc_mask_token=-1)
            self.draft_cfg = draft_cfg
        # Quantized serving (models/quantize.py): "int8" quantizes the
        # block projections (per-channel symmetric, dequantized at each
        # use site); "int8-kv" ALSO flips the KV pool to int8 codes with
        # bf16 scale pages — per-slot bytes Dh+2 vs 2*Dh, ~1.94x the
        # blocks of a bf16 pool at equal HBM (Dh=64). Greedy outputs are
        # deterministic run-to-run within the quantized graph but differ
        # from bf16 serving; the sentinel pins probes per-graph.
        if quantize not in ("none", "int8", "int8-kv"):
            raise ValueError(
                f"quantize must be 'none', 'int8' or 'int8-kv', got "
                f"{quantize!r}"
            )
        self.quantize = quantize
        if quantize != "none":
            from pretraining_llm_tpu.models import quantize as quantize_mod

            if quantize == "int8-kv" and cfg.kv_cache_dtype != "int8":
                # int8-kv implies the int8 pool — flip the model knob here
                # so callers set ONE serving-level switch.
                cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
                if (
                    self.draft_cfg is not None
                    and self.draft_cfg.kv_cache_dtype != "int8"
                ):
                    self.draft_cfg = dataclasses.replace(
                        self.draft_cfg, kv_cache_dtype="int8"
                    )
            # Pre-quantized params (serve.py quantizes BEFORE sharding so
            # scale leaves ride shard_params_for_inference) pass through;
            # raw bf16/fp32 trees are quantized here for direct callers.
            if not quantize_mod.is_quantized(params):
                params = quantize_mod.quantize_params_for_serving(params, cfg)
            if self.spec_k and not quantize_mod.is_quantized(self.draft_params):
                self.draft_params = quantize_mod.quantize_params_for_serving(
                    self.draft_params, self.draft_cfg
                )
        self.params = params
        self.cfg = cfg
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        # Clamp max_seq so EVERY reachable prefill bucket fits the model
        # context: prefill pads prompts up to whole blocks, and a preempted
        # request can be readmitted with prompt+generated as its new prompt
        # — any p <= floor(ctx/bs)*bs then buckets within ctx, so
        # make_kv_cache can never blow up mid-serving on an accepted
        # request (block sizes that don't divide ctx are the trap).
        ctx_aligned = (cfg.context_length // self.block_size) * self.block_size
        self.max_seq = int(min(max_seq or cfg.context_length, ctx_aligned))
        # Table width: no row can ever hold more than the pool's usable
        # blocks, so clamping cuts the per-step gather/score width for
        # small pools (the attention kv_len is max_blocks * block_size).
        self.max_blocks = min(
            paged.required_blocks(self.max_seq, self.block_size), n_blocks - 1
        )
        self.temperature = temperature
        self.top_k, self.top_p, self.min_p = top_k, top_p, min_p
        self.stop_token = stop_token
        # Multi-step scheduling: decode windows of K steps per device
        # dispatch (one compiled scan), reaping/admitting only at window
        # boundaries — the lever against per-step host dispatch latency
        # on the tunneled backend. Rows finishing mid-window overrun into
        # their own pages (surplus discarded host-side).
        self.steps_per_sched = max(1, int(steps_per_sched))
        # Deep pipelining: how many dispatched-but-unreaped windows the
        # run() scheduler keeps queued before blocking on the oldest.
        # 1 = the classic double-buffered scheduler; 2 (default) hides a
        # full window of host reap/consume/admission work behind the
        # device. Purely host scheduling: greedy outputs are identical at
        # every depth (see run()).
        if pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1, got {pipeline_depth}")
        self.pipeline_depth = int(pipeline_depth)
        # Cross-window admission batching: defer waiting prefills until at
        # least this many could be admitted in ONE batched prefill (0/1 =
        # admit eagerly). Deferral only happens while rows are running —
        # an idle engine admits whatever fits, so no deadlock.
        if admit_batch < 0:
            raise ValueError(f"admit_batch must be >= 0, got {admit_batch}")
        self.admit_batch = int(admit_batch)
        # Chunked prefill: split each prompt into chunks of at most this
        # many tokens and interleave them between decode windows instead
        # of one monolithic prefill at admission — the token budget per
        # scheduler tick that protects decode TPOT while long prompts
        # stream in (0 = off, the historical monolithic behavior). The
        # budget is shared FCFS across all mid-prefill rows each tick;
        # rows past it wait (a `defer_prefill_chunk` decision). Greedy
        # outputs are bit-identical either way: chunks ride the SAME
        # multi-token paged forward as prefix-cache suffix prefill, and a
        # token's logits depend only on its own prompt prefix.
        if prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0, got {prefill_chunk_tokens}"
            )
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        # KV integrity checksums (resilience/integrity.py): record a
        # content digest of every pool block the prefix cache publishes,
        # and re-verify it when a later admission acquires the block — a
        # corrupted shared page is dropped and re-prefilled privately
        # instead of poisoning every future hit. Off by default: digests
        # pull page bytes to the host, so the knob buys detection at
        # publish/acquire boundaries only (never inside decode windows).
        self.kv_checksum = bool(kv_checksum)

        # Sharded serving: params arrive pre-sharded
        # (generate.shard_params_for_inference); the KV pools shard their
        # kv_heads dim over the mesh's 'tensor' axis (each TP shard holds
        # its own heads' pages — the same head split as training TP), and
        # decode activations follow via the in-forward constraints.
        self.mesh = mesh

        def _build_pool(pool_cfg: ModelConfig):
            pools = transformer.make_paged_kv_pool(
                pool_cfg, n_blocks, block_size,
                # bf16 scale pages are what carry int8-kv past the 1.9x
                # block-capacity target; legacy int8 pools (kv_cache_dtype
                # set directly, quantize='none') keep fp32 scales for
                # bit-compatibility with the dense int8 cache.
                scale_dtype="bfloat16" if self.quantize == "int8-kv" else None,
            )
            if mesh is None:
                return pools
            from jax.sharding import NamedSharding, PartitionSpec

            tp = mesh.shape.get("tensor", 1)
            head_ax = (
                "tensor" if (tp > 1 and pool_cfg.kv_heads % tp == 0) else None
            )
            if tp > 1 and head_ax is None:
                # Same loudness convention as the flash blockwise fallback:
                # silent replication here multiplies KV HBM by the tensor
                # axis size on every shard.
                warnings.warn(
                    f"serving KV pool: kv_heads={pool_cfg.kv_heads} not "
                    f"divisible by tensor={tp}; pool REPLICATED over the "
                    f"tensor axis ({tp}x KV HBM per shard). Choose tp "
                    f"dividing kv_heads.",
                    stacklevel=2,
                )
            # Every pool leaf carries kv_heads at axis -2 (scale pools have
            # a trailing 1); stacked leaves are 5-dim, unstacked 4-dim.
            return jax.tree.map(
                lambda leaf: jax.device_put(
                    leaf,
                    NamedSharding(
                        mesh,
                        PartitionSpec(
                            *([None] * (leaf.ndim - 2)), head_ax, None
                        ),
                    ),
                ),
                pools,
            )

        self.pools = _build_pool(cfg)
        # Draft pools mirror the block structure exactly: SAME table/ids,
        # draft-model dims per block (paged_spec_round's shared-frontier
        # contract).
        self.d_pools = _build_pool(self.draft_cfg) if self.spec_k else None
        self.n_blocks = int(n_blocks)
        self.alloc = paged.BlockAllocator(n_blocks)
        self.tables = np.zeros((self.max_batch, self.max_blocks), np.int32)
        self.seq_lens = np.zeros((self.max_batch,), np.int32)
        self.tokens = np.zeros((self.max_batch,), np.int32)
        self.rows: List[Optional[_Request]] = [None] * self.max_batch
        self.waiting: deque = deque()
        self.finished: Dict[int, List[int]] = {}
        # Requests aborted via cancel() — they never land in `finished`.
        self.cancelled: set = set()
        # Per-request lifecycle timestamps (monotonic seconds): submit_s,
        # admit_s (first row claim; preemption re-admits keep the first),
        # first_token_s (first COMMITTED output token), end_s. The online
        # frontend and the offline `serve.py --output` JSONL both read
        # these via timing_summary(); long-lived callers pop entries at
        # request end to bound growth.
        self.req_timing: Dict[int, Dict[str, float]] = {}
        self._now = time.monotonic
        # Streaming hooks (frontend/engine_loop.py): called synchronously
        # on the scheduling thread as tokens COMMIT (reap time in the
        # pipelined scheduler) and as requests finish. None = offline
        # batch mode.
        self.on_token: Optional[Callable[[int, int], None]] = None
        self.on_finish: Optional[Callable[[int, List[int]], None]] = None
        # Per-request traces (observability.tracing.RequestTrace), keyed
        # by rid — installed by the frontend via set_trace(). Empty when
        # tracing is off, and every recording site below guards on that
        # emptiness first, so the untraced hot path pays one dict truth
        # test. Recording itself is perf_counter reads + a list append:
        # no device syncs on any path.
        self.traces: Dict[int, Any] = {}
        # Optional latency histograms (observability.metrics.Histogram),
        # installed by the frontend: per-window wall duration and per-
        # window host-blocked readback seconds. Observed once per reaped
        # window — never per token.
        self.window_hist: Optional[Any] = None
        self.host_blocked_hist: Optional[Any] = None
        # Capacity observability (observability/capacity.py), installed by
        # the frontend like the histograms above: an occupancy sampler fed
        # once per reaped window (host ints the reap already holds — no
        # new device syncs), a scheduler decision log fed at the preempt/
        # evict/reclaim sites, and typed preemption counters. All None by
        # default; every producer site guards on that.
        self.capacity: Optional[Any] = None
        self.decisions: Optional[Any] = None
        self.preempt_counter: Optional[Any] = None
        self.preempt_tokens_counter: Optional[Any] = None
        # Chunked-prefill typed counters (bound by the frontend like the
        # preemption counters above): chunks dispatched, chunk tokens
        # prefilled, and ticks whose chunk program rode alongside a
        # decode window (interleaved) vs alone (dedicated).
        self.chunk_counter: Optional[Any] = None
        self.chunk_tokens_counter: Optional[Any] = None
        self.chunk_interleaved_counter: Optional[Any] = None
        self.chunk_dedicated_counter: Optional[Any] = None
        # Integrity typed counters (bound by the frontend like the rest):
        # out-of-vocab token ids caught at reap, and cached KV pages that
        # failed verify-on-acquire.
        self.invalid_token_counter: Optional[Any] = None
        self.kv_mismatch_counter: Optional[Any] = None
        self._key = jax.random.PRNGKey(seed)
        self._next_rid = 0
        self._admit_counter = 0
        # Pipelined scheduling state: the queue of in-flight windows
        # (tokens still on device, oldest first) and admission token
        # merges queued for the next dispatch — see _run_pipelined.
        self._inflight: deque = deque()
        self._pending_admit_merges: List[tuple] = []
        self.stats = {
            "steps": 0, "tokens": 0, "preemptions": 0, "admissions": 0,
            # Pipelined-scheduler telemetry: windows dispatched/reaped and
            # the host seconds spent blocked on a window's readback — the
            # quantity deep pipelining exists to shrink (host_blocked_s /
            # windows_reaped is the per-window counter bench.py reports).
            "windows": 0, "windows_reaped": 0, "host_blocked_s": 0.0,
            "flushes": 0,
            # Prompt tokens actually prefilled (suffix-only for cache
            # hits) — with prefix_cache_hit_tokens this yields the
            # prefill-reduction ratio bench.py's serving record reports.
            "prefill_tokens": 0,
            # Chunked-prefill telemetry: chunk programs dispatched, chunk
            # tokens prefilled through them, and scheduler ticks whose
            # chunk dispatch shared the tick with a decode window
            # (interleaved) vs ran alone (dedicated) — the TPOT-protection
            # signal (interleaved ≫ dedicated under decode load).
            "prefill_chunks": 0, "prefill_chunk_tokens": 0,
            "chunk_windows_interleaved": 0, "chunk_windows_dedicated": 0,
            # Unfused-lane telemetry: bytes of raw (B, V) logits pulled
            # to the host per decode step. Stays 0 with fused sampling
            # (the default) — the transfer the fused path deletes.
            "logits_bytes_host": 0,
        }
        # Cross-request prefix cache: content-addressed page reuse over
        # the allocator (generation/prefix_cache.py). Off by default —
        # when on, greedy outputs stay bit-identical to cache-off runs
        # (the survivor-identity contract; tests/test_prefix_cache.py).
        self.prefix_cache: Optional[prefix_cache_mod.PrefixCache] = None
        if prefix_cache:
            self.prefix_cache = prefix_cache_mod.PrefixCache(
                self.alloc, self.block_size,
                min_blocks=prefix_cache_min_blocks, stats=self.stats,
            )

    # -- public API --------------------------------------------------------

    def pool_info(self) -> Dict[str, Any]:
        """KV-pool layout facts for /debug/engine, the capacity snapshot
        and the `pllm_kv_pool_bytes` gauge: element dtypes, bytes per
        block and total pool bytes — summed over ALL pool leaves (scale
        pages included), host-side shape math only (no device sync).
        Draft pools (speculative serving) are reported separately."""
        pools = self.pools
        layer0 = pools["layers"][0] if "layers" in pools else pools
        total = int(
            sum(leaf.nbytes for leaf in jax.tree.leaves(pools))
        )
        info = {
            "quantize": self.quantize,
            "kv_dtype": str(layer0["k_pool"].dtype),
            "kv_scale_dtype": (
                str(layer0["k_scale_pool"].dtype)
                if "k_scale_pool" in layer0 else None
            ),
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "bytes_per_block": total // self.n_blocks,
            "pool_bytes": total,
        }
        if self.d_pools is not None:
            info["draft_pool_bytes"] = int(
                sum(leaf.nbytes for leaf in jax.tree.leaves(self.d_pools))
            )
        return info

    def health_gauges(self) -> Dict[str, Any]:
        """Point-in-time engine occupancy for the fleet health surface
        (worker ``health_pull`` replies and Router.fleet_health): row and
        KV-pool occupancy, queue depth, and the KV-migration counters.
        Host containers only — mutated between scheduler turns, each
        read an atomic snapshot — so gateway/worker threads may call it
        while the engine thread runs, at worst one turn stale. Block 0
        is reserved scratch, hence the ``- 1`` (same accounting as
        EngineLoop.debug_engine; the CI gate ties them out)."""
        pool_total = self.alloc.n_blocks - 1
        pool_free = self.alloc.available
        cache = self.prefix_cache
        pool_cold = cache.evictable if cache is not None else 0
        stats = dict(self.stats)
        return {
            "rows_active": sum(r is not None for r in list(self.rows)),
            "rows_capacity": self.max_batch,
            "waiting": len(self.waiting),
            "pool_total": pool_total,
            "pool_free": pool_free,
            "pool_cold": pool_cold,
            "pool_live": pool_total - pool_free - pool_cold,
            "kv_pages_adopted": int(stats.get("kv_pages_adopted", 0)),
            "kv_pages_rejected": int(stats.get("kv_pages_rejected", 0)),
            "preemptions": int(stats.get("preemptions", 0)),
        }

    def validate_request(
        self, prompt_ids: Sequence[int], max_new_tokens: Any
    ) -> int:
        """Everything submit() checks, without queueing anything — clear
        ``ValueError``s AT SUBMIT TIME (the gateway maps them to 400)
        instead of a shape/gather failure later inside dispatch. Reads
        only construction-time constants, so concurrent gateway threads
        may call it while the engine thread runs. Returns the normalized
        integer ``max_new_tokens``."""
        try:
            max_new = int(max_new_tokens)
        except (TypeError, ValueError):
            raise ValueError(
                f"max_new_tokens must be an integer, got "
                f"{type(max_new_tokens).__name__}"
            )
        if max_new != max_new_tokens:  # reject 2.5 -> 2 silent truncation
            raise ValueError(
                f"max_new_tokens must be an integer, got {max_new_tokens!r}"
            )
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt")
        ids = np.asarray(prompt_ids)
        if ids.ndim != 1:
            raise ValueError(
                f"prompt must be a flat list of token ids, got an array of "
                f"shape {ids.shape}"
            )
        if ids.dtype.kind not in "iu":
            raise ValueError(
                f"prompt must be integer token ids, got dtype {ids.dtype}"
            )
        lo, hi = int(ids.min()), int(ids.max())
        if lo < 0 or hi >= self.cfg.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, {self.cfg.vocab_size}); "
                f"got range [{lo}, {hi}]"
            )
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        total = p + max_new
        if total > self.max_seq:
            raise ValueError(
                f"prompt({p}) + max_new({max_new}) = {total} exceeds "
                f"max_seq={self.max_seq}"
            )
        if paged.required_blocks(total, self.block_size) > self.alloc.n_blocks - 1:
            raise ValueError(
                f"request needs {paged.required_blocks(total, self.block_size)} "
                f"blocks; the pool only has {self.alloc.n_blocks - 1}"
            )
        return max_new

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int) -> int:
        """Queue a request; returns its id. Fails fast if the request can
        never fit (prompt + generation must fit max_seq AND the pool)."""
        max_new = self.validate_request(prompt_ids, max_new_tokens)
        rid = self._next_rid
        self._next_rid += 1
        self.req_timing[rid] = {"submit_s": self._now()}
        self.waiting.append(_Request(rid, [int(t) for t in prompt_ids], max_new))
        return rid

    def set_trace(self, rid: int, trace: Any) -> None:
        """Attach a RequestTrace to a submitted request; the scheduler
        records queue/prefill/window spans into it. ``None`` is a no-op
        (the unsampled case), so callers need no guard."""
        if trace is not None:
            self.traces[rid] = trace

    def pop_trace(self, rid: int) -> Any:
        """Detach (and return) a request's trace at terminal time; the
        caller owns finishing it."""
        return self.traces.pop(rid, None)

    def cancel(self, rid: int) -> bool:
        """Abort a live request, releasing its row and pool blocks
        immediately. A waiting request unlinks with no device work; a
        running one first FLUSHES the in-flight window queue — windows
        already dispatched keep writing K/V into the victim's pages on
        device, so freeing those blocks before the drain would hand
        live-written pages to the next admission — then releases the row.
        Tokens the flush commits still stream through ``on_token``; the
        caller owns the terminal notification. Returns False when the
        request is unknown or already finished (cancellation lost the
        race — its output is in ``finished``)."""
        for req in self.waiting:
            if req.rid == rid:
                self.waiting.remove(req)
                self._mark_cancelled(rid)
                return True
        req = next(
            (r for r in self.rows if r is not None and r.rid == rid), None
        )
        if req is None:
            return False
        self._flush_inflight()
        # The drain may have finished the request (its surviving tokens
        # were committed and streamed) — then there is nothing to cancel.
        if req.row is None or self.rows[req.row] is not req:
            return False
        # A victim admitted this very boundary may still hold its first
        # token on device; resolving it can itself finish the request.
        self._resolve_first(req)
        if req.row is None:
            return False
        self._release_row(req)
        self._mark_cancelled(rid)
        return True

    def _mark_cancelled(self, rid: int) -> None:
        self.cancelled.add(rid)
        self.stats["cancelled"] = self.stats.get("cancelled", 0) + 1
        t = self.req_timing.get(rid)
        if t is not None:
            t["end_s"] = self._now()

    def timing_summary(self, rid: int) -> Dict[str, float]:
        """Lifecycle latencies (seconds) for a request: ``queue_wait_s``
        (submit -> first row claim), ``ttft_s`` (submit -> first committed
        output token), ``e2e_s`` (submit -> finish/cancel). Only phases
        the request actually reached appear."""
        t = self.req_timing.get(rid)
        if not t:
            return {}
        out: Dict[str, float] = {}
        sub = t["submit_s"]
        if "admit_s" in t:
            out["queue_wait_s"] = t["admit_s"] - sub
        if "first_token_s" in t:
            out["ttft_s"] = t["first_token_s"] - sub
        if "end_s" in t:
            out["e2e_s"] = t["end_s"] - sub
        if "cached_tokens" in t:
            # Prompt tokens served from the prefix cache instead of
            # prefill, summed across admissions (a preemption resume that
            # re-hits its own published pages counts its savings too).
            out["cached_tokens"] = int(t["cached_tokens"])
        return out

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self.rows)

    def has_work(self) -> bool:
        return bool(self.waiting) or self.n_active > 0

    def _window_len(self) -> int:
        """Effective decode-window length: ``steps_per_sched`` clamped by
        the active rows' token budget. When every row needs at most R more
        tokens, a full window wastes (sps - R) lockstep steps on rows that
        already finished — the tail-latency term at large windows. The
        clamp buckets UP to a power of two so the jit cache stays at
        log2(sps) window-program variants instead of one per residual
        length. (Pipelined mode sees n_generated one window stale: the
        clamp then OVERestimates the budget — never truncates a live
        row.)"""
        n = self.steps_per_sched
        if n <= 1:
            return max(1, n)
        rem = max(
            (req.max_new - req.n_generated for req in self.rows
             if req is not None),
            default=n,
        )
        if rem >= n:
            return n
        b = 1
        while b < max(1, rem):
            b <<= 1
        return min(b, n)

    def _n_decode_rows(self) -> int:
        """Rows eligible for decode windows/spec rounds: active AND past
        their prefill phase. Mid-chunk rows are excluded from dispatch
        snapshots — their frontier is mid-prompt."""
        return sum(
            1 for r in self.rows
            if r is not None and r.prefill_pos is None
        )

    def _note_chunk_window(self, decoded: bool) -> None:
        """Tick-level interleave accounting: a chunk program that shared
        its tick with a decode dispatch protected TPOT (interleaved);
        one that ran alone had the engine to itself (dedicated)."""
        if decoded:
            self.stats["chunk_windows_interleaved"] += 1
            if self.chunk_interleaved_counter is not None:
                self.chunk_interleaved_counter.inc()
        else:
            self.stats["chunk_windows_dedicated"] += 1
            if self.chunk_dedicated_counter is not None:
                self.chunk_dedicated_counter.inc()

    def step(self) -> None:
        """One scheduling round: admit -> prefill chunks (chunked mode)
        -> grow/preempt -> a window of ``steps_per_sched`` lockstep
        decode steps (clamped to the active rows' remaining-token
        budget, or ONE speculative round when spec_k is set) -> reap.
        A no-op when nothing is running or waiting."""
        self._admit()
        chunked = self._dispatch_prefill_chunks(defer=False)
        decoded = self._step_decode() if self._n_decode_rows() else False
        if chunked:
            self._note_chunk_window(decoded)

    def _step_decode(self) -> bool:
        """The synchronous decode arm of step(); True when a decode
        window (or spec round) actually ran."""
        if self.spec_k:
            return self._spec_step()
        n = self._window_len()
        self._ensure_write_pages(horizon=n)
        if self._n_decode_rows() == 0:  # everyone got preempted (tiny pool)
            return False
        # Backstop for the PagedInfo capacity invariant (submit() bounds
        # every request structurally; this keeps scheduler bugs loud).
        # Multi-step windows may overshoot capacity mid-window — that is
        # handled by the model's scratch-redirect guard; the invariant
        # here is on the WINDOW-START state only.
        paged.check_paged_bounds(self.tables, self.seq_lens, self.block_size)
        self._key, sub = jax.random.split(self._key)
        toks, lp = self._decode_window(
            jnp.asarray(self.tokens), jnp.asarray(self.tables),
            jnp.asarray(self.seq_lens), sub, n, raw_key_single=True,
        )
        window = np.asarray(toks)  # (B, n)
        lp_host = None
        if lp is not None:
            lp_host = (np.asarray(lp[0]), np.asarray(lp[1]))
        self.stats["steps"] += n
        for row, req in enumerate(self.rows):
            if req is None or req.prefill_pos is not None:
                continue
            self._consume_tokens(
                req, row, window[row], advance_seq=True,
                lp=None if lp_host is None
                else (lp_host[0][row], lp_host[1][row]),
            )
        return True

    def _decode_window(self, base, tables_dev, seq_dev, key, n,
                       raw_key_single=False):
        """ONE definition of the decode-window device dispatch for the
        synchronous and pipelined schedulers. Returns ``(toks, lp)``:
        ``toks`` a (B, n) DEVICE token array (the pipelined path chains
        its last column without a sync), ``lp`` None or the device
        ``((B, n, k) values, (B, n, k) ids)`` logprob sliver.

        Fused (default): sampling runs inside the jitted step program —
        the host payload per window is token ids (+ the optional
        sliver), never logits. Unfused: the measurement/reference lane —
        per step, a forward-only program returns full (B, V) logits,
        they cross device->host (counted in stats["logits_bytes_host"]),
        and a SEPARATE sampling dispatch picks the token. Greedy output
        is bit-identical between the two lanes by construction: same
        forward, same argmax, same key stream (``raw_key_single`` keeps
        the sync n==1 path on the raw key exactly like
        paged_decode_step)."""
        common = dict(
            cfg=self.cfg, temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, min_p=self.min_p, mesh=self.mesh,
        )
        single = n == 1 and raw_key_single
        if not self.fused_sampling:
            skeys = [key] if single else list(jax.random.split(key, n))
            sample_kw = dict(
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p, min_p=self.min_p,
            )
            tok, seq, cols = base, seq_dev, []
            for sub in skeys:
                logits, self.pools = paged.paged_decode_logits(
                    self.params, self.pools, tok, tables_dev, seq,
                    cfg=self.cfg, mesh=self.mesh,
                )
                # THE round-trip fused sampling deletes: every step pays
                # a (B, V) f32 device->host transfer + a second dispatch.
                logits_host = np.asarray(logits)
                self.stats["logits_bytes_host"] += logits_host.nbytes
                tok = paged.sample_tokens(
                    jnp.asarray(logits_host), sub, **sample_kw
                )
                cols.append(tok)
                seq = seq + 1
            return jnp.stack(cols, axis=1), None
        dev_args = (self.params, self.pools, base, tables_dev, seq_dev, key)
        if self.logprobs_k:
            if single:
                nxt, lpv, lpi, self.pools = paged.paged_decode_step_lp(
                    *dev_args, logprobs_k=self.logprobs_k, **common
                )
                return nxt[:, None], (lpv[:, None], lpi[:, None])
            toks, lpv, lpi, self.pools = paged.paged_decode_steps_lp(
                *dev_args, n_steps=n, logprobs_k=self.logprobs_k, **common
            )
            return toks, (lpv, lpi)
        if single:
            nxt, self.pools = paged.paged_decode_step(*dev_args, **common)
            return nxt[:, None], None
        toks, self.pools = paged.paged_decode_steps(
            *dev_args, n_steps=n, **common
        )
        return toks, None

    def _spec_step(self) -> bool:
        """One speculative round for every active row: k draft proposals,
        one multi-token target verify, per-row ragged acceptance (1 to
        k+1 tokens emitted per row). The round writes slots
        [seq, seq + k] in BOTH pools, so the page horizon is spec_k + 1;
        rejected slots hold garbage above each row's new frontier and are
        overwritten by the next round (slot-reuse discipline)."""
        k = self.spec_k
        self._ensure_write_pages(horizon=k + 1)
        if self._n_decode_rows() == 0:  # everyone preempted (tiny pool)
            return False
        paged.check_paged_bounds(self.tables, self.seq_lens, self.block_size)
        self._key, sub = jax.random.split(self._key)
        emit, n_emit, self.pools, self.d_pools = paged.paged_spec_round(
            self.params, self.pools, self.d_pools, self.draft_params,
            jnp.asarray(self.tokens), jnp.asarray(self.tables),
            jnp.asarray(self.seq_lens), sub, cfg_t=self.cfg,
            cfg_d=self.draft_cfg, k=k, temperature=self.temperature,
            mesh=self.mesh,
        )
        emit = np.asarray(emit)  # (B, k+1)
        n_emit = np.asarray(n_emit)  # (B,)
        self.stats["steps"] += 1
        self.stats["spec_rounds"] = self.stats.get("spec_rounds", 0) + 1
        self.stats["spec_proposed"] = (
            self.stats.get("spec_proposed", 0) + k * self._n_decode_rows()
        )
        for row, req in enumerate(self.rows):
            if req is None or req.prefill_pos is not None:
                continue
            self.stats["spec_accepted"] = (
                self.stats.get("spec_accepted", 0) + int(n_emit[row]) - 1
            )
            self._consume_tokens(
                req, row, emit[row, : int(n_emit[row])], advance_seq=True
            )
        return True

    def run(self, *, pipeline: bool = True) -> Dict[int, List[int]]:
        """Drive the engine until every submitted request has finished.

        ``pipeline=True`` (default) runs the deep-pipelined scheduler: a
        queue of up to ``pipeline_depth`` dispatched-but-unreaped windows.
        Window k+1's inputs chain from window k's last tokens ON DEVICE,
        so the host's reap/consume/admission work for older windows and
        their readback round trips overlap the device's execution instead
        of idling it — the device only drains when a decision needs exact
        host state (preemption, page reclaim), which flushes the queue
        and replays from committed state. The price is up to
        ``pipeline_depth`` windows of lag on finish detection (a finished
        row decodes surplus windows before its slot frees; surplus tokens
        are discarded at reap). Greedy outputs are IDENTICAL to
        pipeline=False at EVERY depth — per-row greedy decoding depends
        only on the row's own history, never on scheduling; with
        temperature > 0 the sampling key stream differs (window keys
        split in dispatch order, and deeper queues dispatch more surplus
        windows).

        Speculative serving (spec_k > 0) joins the same in-flight queue:
        round k+1 chains its seed tokens AND its frontier from round k's
        device-resident result (speculative.spec_next_inputs), so the
        data-dependent acceptance no longer forces a per-round host sync;
        the page horizon is pre-ensured for the worst-case (k+1) advance
        of every queued round. Committed host ``seq_lens`` advance at
        reap by the round's actual emit count.
        """
        if not pipeline:
            while self.has_work():
                self.step()
            return self.finished
        return self._run_pipelined()

    def _run_pipelined(self) -> Dict[int, List[int]]:
        assert not self._inflight, "re-entrant run()"
        while self.has_work() or self._inflight:
            self.pipeline_tick()
        return self.finished

    def pipeline_tick(self) -> bool:
        """One turn of the deep-pipelined scheduler: admit waiting
        requests, dispatch at most one window, reap windows beyond the
        queue depth. ``run(pipeline=True)`` is exactly this in a loop;
        the online frontend (frontend/engine_loop.py) calls it directly
        so submissions, cancellations and deadline checks can land
        BETWEEN scheduler turns of a long-lived engine. Returns True
        while device work remains dispatched or runnable (False = the
        engine is fully idle)."""
        depth = self.pipeline_depth
        self._admit(defer=True)
        # Chunked prefill rides BEFORE the decode dispatch: its writes
        # are committed prompt data (earlier in device program order than
        # this tick's window), and the token budget bounds the prefill
        # work a decode window ever waits behind — the TPOT protection.
        chunked = self._dispatch_prefill_chunks(defer=True)
        decoded = False
        if self._n_decode_rows():
            if self.spec_k:
                # Worst case every queued round and the new one
                # advance the device frontier by k+1 past the
                # committed seq_lens — pre-ensure the whole horizon
                # so no flush can land between dispatch and reap.
                k = self.spec_k
                self._ensure_write_pages(
                    horizon=(k + 1) * (len(self._inflight) + 1)
                )
                if self._n_decode_rows():
                    self._dispatch_spec_round()
                    decoded = True
            else:
                n = self._window_len()
                # ONE window length for both the page horizon and the
                # dispatch: ensure_write_pages may flush/preempt
                # (which only shrinks the remaining budget), and a
                # dispatch longer than the ensured horizon would
                # scratch-redirect live writes — computing n once
                # makes that impossible by construction. ``prealloc``
                # opportunistically extends rows toward the full
                # in-flight horizon (n * depth slots) from the free
                # list, so later dispatches rarely need new pages at
                # all — a page flush between an already-dispatched
                # window and its reap becomes the exception.
                self._ensure_write_pages(
                    horizon=n, prealloc=n * (depth - 1)
                )
                if self._n_decode_rows():
                    self._dispatch_window(n)
                    decoded = True
        if chunked:
            self._note_chunk_window(decoded)
        # Reap the oldest window once the queue exceeds its depth —
        # by then it has had `depth` windows of device time to finish,
        # so the readback rarely blocks — and drain outright when
        # nothing is running (end of stream, or everyone preempted).
        while (len(self._inflight) > depth
               or (self._inflight and not self.n_active)):
            self._reap_window(self._inflight.popleft())
        return bool(self._inflight) or self.has_work()

    def _dispatch_window(self, n: int) -> None:
        """Enqueue one ``steps_per_sched``-step decode window WITHOUT
        waiting for the queued ones: input tokens come from the youngest
        in-flight window's last column (still on device) merged with
        admission first-tokens (also on device); seq_lens advance
        host-side by the window length (every active row writes exactly
        that many slots, finished-or-not — surplus is discarded at reap).
        ``n`` is the window length the caller already ensured pages
        for."""
        capacity = self.max_blocks * self.block_size
        # Clamp: a finished-but-unreaped row may have written up to its
        # full allocation; feeding seq == capacity would trip the bounds
        # guard (and the model would clamp its page index onto a live
        # block). capacity-1 keeps its garbage writes inside its OWN last
        # block until it is reaped.
        seq_dispatch = np.minimum(self.seq_lens, capacity - 1)
        # Mid-prefill rows are NOT in the window: their lockstep writes
        # are garbage landing at/above their committed frontier (the next
        # chunk overwrites them before any mask exposes them), their seq
        # must not advance, and their tokens are never consumed.
        active = [
            i for i, r in enumerate(self.rows)
            if r is not None and r.prefill_pos is None
        ]
        paged.check_paged_bounds(
            self.tables[active], seq_dispatch[active], self.block_size
        )
        with _spans.span("serving.dispatch_window", steps=n):
            if self._inflight:
                base = self._inflight[-1].toks[:, -1]  # (B,) device, no sync
            else:
                base = jnp.asarray(self.tokens)
            base = self._merge_admitted(base)
            self._key, sub = jax.random.split(self._key)
            toks, lp = self._decode_window(
                base, jnp.asarray(self.tables), jnp.asarray(seq_dispatch),
                sub, n,
            )
        self.stats["steps"] += n
        self.stats["windows"] += 1
        snapshot = [(i, self.rows[i]) for i in active]
        for i in active:
            self.seq_lens[i] = min(int(self.seq_lens[i]) + n, capacity)
        self._inflight.append(
            _Window(kind="decode", snapshot=snapshot, n=n, toks=toks,
                    lp=lp, t_dispatch=time.perf_counter())
        )

    def _dispatch_spec_round(self) -> None:
        """Enqueue one speculative round against the device-resident
        frontier: seed tokens and seq_lens chain from the youngest queued
        round via spec_next_inputs (no host sync); rows admitted since
        the last dispatch are spliced in from committed host state. With
        an empty queue (start, or right after a reconciliation flush)
        both come from committed host state — the replay path."""
        k = self.spec_k
        capacity = self.max_blocks * self.block_size
        seq_committed = np.minimum(self.seq_lens, capacity - 1)
        # Same exclusion as _dispatch_window: mid-prefill rows ride no
        # spec round (their chained seq_dev is reset to the committed
        # frontier by the chunk dispatch's merge entry, so their garbage
        # writes stay at/above it).
        active = [
            i for i, r in enumerate(self.rows)
            if r is not None and r.prefill_pos is None
        ]
        # The bounds invariant is checked on COMMITTED state (a lower
        # bound on the device frontier); in-flight advances stay inside
        # the pre-ensured horizon by construction.
        paged.check_paged_bounds(
            self.tables[active], seq_committed[active], self.block_size
        )
        with _spans.span("serving.dispatch_window", steps=k + 1):
            if self._inflight:
                prev = self._inflight[-1]
                base, seq_dev = speculative.spec_next_inputs(
                    prev.emit, prev.n_emit, prev.seq_dev
                )
            else:
                base = jnp.asarray(self.tokens)
                seq_dev = jnp.asarray(self.seq_lens)
            base, seq_dev = self._merge_admitted(base, seq_dev)
            self._key, sub = jax.random.split(self._key)
            emit, n_emit, self.pools, self.d_pools = paged.paged_spec_round(
                self.params, self.pools, self.d_pools, self.draft_params,
                base, jnp.asarray(self.tables), seq_dev, sub,
                cfg_t=self.cfg, cfg_d=self.draft_cfg, k=k,
                temperature=self.temperature, mesh=self.mesh,
            )
        self.stats["steps"] += 1
        self.stats["windows"] += 1
        self.stats["spec_rounds"] = self.stats.get("spec_rounds", 0) + 1
        snapshot = [(i, self.rows[i]) for i in active]
        self._inflight.append(
            _Window(kind="spec", snapshot=snapshot, n=k + 1,
                    emit=emit, n_emit=n_emit, seq_dev=seq_dev,
                    t_dispatch=time.perf_counter())
        )

    def _merge_admitted(self, base, seq_dev=None):
        """Splice rows admitted since the last dispatch into the chained
        device inputs: their prefill-sampled first token, and (spec mode)
        their committed frontier — a released row's stale chain values
        are otherwise garbage by design (zero tables scratch its writes),
        but a RE-ADMITTED row must restart from committed host state."""
        for toks_dev, idxs, rows in self._pending_admit_merges:
            r = jnp.asarray(rows, jnp.int32)
            base = base.at[r].set(toks_dev[jnp.asarray(idxs, jnp.int32)])
            if seq_dev is not None:
                seq_dev = seq_dev.at[r].set(
                    jnp.asarray(self.seq_lens[np.asarray(rows)], jnp.int32)
                )
        self._pending_admit_merges = []
        return base if seq_dev is None else (base, seq_dev)

    def _reap_window(self, w: _Window) -> None:
        """Materialize a window's tokens and do the lagged bookkeeping:
        resolve deferred first tokens, extend outputs, finish rows that
        hit stop/max_new (their surplus in-window tokens are discarded,
        exactly as in the synchronous path). The readback wait is the
        host-blocked time deep pipelining exists to hide — measured per
        window into stats and the span's trace args."""
        widx = self.stats["windows_reaped"]
        with _spans.span("serving.reap_window", window=widx) as meta:
            t0 = time.perf_counter()
            with _spans.span("serving.host_blocked"):
                if w.kind == "spec":
                    emit = np.asarray(w.emit)      # (B, k+1) — THE sync point
                    n_emit = np.asarray(w.n_emit)  # (B,)
                else:
                    window = np.asarray(w.toks)    # (B, n) — THE sync point
                    lp_host = None
                    if w.lp is not None:
                        lp_host = (np.asarray(w.lp[0]), np.asarray(w.lp[1]))
            t_reaped = time.perf_counter()
            blocked = t_reaped - t0
            meta["host_blocked_s"] = round(blocked, 6)
            self.stats["host_blocked_s"] += blocked
            self.stats["windows_reaped"] += 1
            if self.window_hist is not None and w.t_dispatch:
                self.window_hist.observe(t_reaped - w.t_dispatch)
            if self.host_blocked_hist is not None:
                self.host_blocked_hist.observe(blocked)
            capacity = self.max_blocks * self.block_size
            toks_before = self.stats["tokens"]
            for row, req in w.snapshot:
                if req.row != row or self.rows[row] is not req:
                    # The row finished in an earlier reap and may have
                    # been re-admitted since; this window's tokens for it
                    # are surplus garbage by the lag contract. (Preemption
                    # can't land here: it flushes the queue first.)
                    continue
                if self.traces:
                    tr = self.traces.get(req.rid)
                    if tr is not None and not tr.finished:
                        # One span per (request, window) it rode: dispatch
                        # -> reap. Under deep pipelining these intervals
                        # OVERLAP across windows; the SLO decomposition
                        # unions them into decode time. host_blocked_s is
                        # the whole window's readback wait — per request
                        # it reads as "this much of my window was the
                        # host, not the device".
                        tr.span(
                            "req.window",
                            w.t_dispatch or t0, t_reaped,
                            kind=w.kind, steps=w.n, window=widx,
                            host_blocked_s=round(blocked, 6),
                        )
                self._resolve_first(req)
                if req.row is None:  # first token alone finished it
                    continue
                if w.kind == "spec":
                    # Commit the round's data-dependent advance. Proposal/
                    # acceptance telemetry counts here (not at dispatch)
                    # so surplus rounds for finished rows skew neither
                    # side of the hit rate.
                    ne = int(n_emit[row])
                    self.seq_lens[row] = min(
                        int(self.seq_lens[row]) + ne, capacity
                    )
                    self.stats["spec_proposed"] = (
                        self.stats.get("spec_proposed", 0) + self.spec_k
                    )
                    self.stats["spec_accepted"] = (
                        self.stats.get("spec_accepted", 0) + ne - 1
                    )
                    self._consume_tokens(
                        req, row, emit[row, :ne], advance_seq=False
                    )
                else:
                    self._consume_tokens(
                        req, row, window[row], advance_seq=False,
                        lp=None if lp_host is None
                        else (lp_host[0][row], lp_host[1][row]),
                    )
            if self.capacity is not None:
                # Occupancy sample AT the reap sync point: every value is
                # host state this method already touched (row snapshot,
                # committed-token delta, allocator free count, queue
                # depth) — no device access, so the asarray-spy contract
                # holds with sampling enabled.
                self.capacity.observe_window(
                    window=widx,
                    kind=w.kind,
                    t_dispatch_s=w.t_dispatch or t0,
                    t_reap_s=t_reaped,
                    steps=w.n,
                    rows=len(w.snapshot),
                    tokens_committed=self.stats["tokens"] - toks_before,
                    waiting=len(self.waiting),
                    pool_free=self.alloc.available,
                    pool_cold=(
                        self.prefix_cache.evictable
                        if self.prefix_cache is not None else 0
                    ),
                    host_blocked_s=blocked,
                    cum_tokens=self.stats["tokens"],
                    cum_prefill_tokens=self.stats["prefill_tokens"],
                    cum_rework_prefill_tokens=self.stats.get(
                        "preempted_tokens_recomputed", 0
                    ),
                    cum_preemptions=self.stats["preemptions"],
                )

    def _consume_tokens(self, req: _Request, row: int, toks,
                        advance_seq: bool, lp=None) -> None:
        """ONE definition of per-token reaping for all three schedulers
        (synchronous window, speculative round, pipelined reap): append
        to the output, update the row's pending token, finish on
        stop/max_new and DISCARD the surplus. ``advance_seq``: the
        synchronous and speculative paths advance the frontier here (the
        step that produced the token wrote its slot); the pipelined path
        already advanced it at dispatch. ``lp``: this row's
        ``((n, k) values, (n, k) ids)`` logprob slice — consumed in
        lockstep with the tokens, so surplus logprobs are discarded with
        their surplus tokens."""
        for i, tok in enumerate(int(t) for t in toks):
            if advance_seq:
                self.seq_lens[row] += 1
            self._check_token(req, tok)
            req.generated.append(tok)
            self._lp_append(
                req,
                None if lp is None
                else (lp[0][i].tolist(), lp[1][i].tolist()),
            )
            self._emit_token(req, tok)
            self.tokens[row] = tok
            self.stats["tokens"] += 1
            if tok == self.stop_token or len(req.generated) >= req.max_new:
                self._finish(req)
                break  # surplus tokens for this row are discarded

    def _lp_append(self, req: _Request, entry) -> None:
        """Record one output token's logprob entry (or its absence) —
        kept in lockstep with every ``generated.append`` so the per-rid
        list aligns with the final output across preemptions (prefix
        tokens keep the entries from their first incarnation)."""
        if not self.logprobs_k:
            return
        self.logprobs.setdefault(req.rid, []).append(entry)

    def _emit_token(self, req: _Request, tok: int) -> None:
        """Post-append commit hook: first-token timestamp + the streaming
        callback. The stop token is bookkeeping, not output (``_finish``
        strips it), so it is never streamed; across preemptions the
        concatenated stream equals the final ``prefix + generated``
        output exactly (preempted tokens streamed in their first
        incarnation, re-decoded ones arrive as prompt, not output)."""
        t = self.req_timing.get(req.rid)
        if t is not None and tok != self.stop_token:
            if "first_token_s" not in t:
                t["first_token_s"] = self._now()
                if self.traces:
                    tr = self.traces.get(req.rid)
                    if tr is not None:
                        # Zero-duration point on the waterfall; the TTFT
                        # histogram is observed at terminal time from
                        # req_timing, never here (per-token hot path).
                        tr.event("req.first_token")
        if self.on_token is not None and tok != self.stop_token:
            self.on_token(req.rid, tok)

    def _flush_inflight(self) -> None:
        """Reconciliation: synchronously drain EVERY in-flight window,
        oldest first, so host state is exact/committed — required before
        preemption decisions and speculative-page reclaim. The caller
        then replays from committed state (the next dispatch finds an
        empty queue and restarts the device chain from host tokens/
        seq_lens)."""
        if self._inflight:
            self.stats["flushes"] += 1
        while self._inflight:
            self._reap_window(self._inflight.popleft())

    def _check_token(self, req: _Request, tok: int) -> None:
        """In-band output sanity guard, applied to every token id at the
        moment it would COMMIT (the values are host ints the reap already
        materialized — no new device pulls). An out-of-vocab id can only
        come from corrupted state (weights, KV pages, a bad kernel —
        ``sample_logits`` maps non-finite sampling-path logits to -1 for
        exactly this reason), so the right move is to fail the engine
        loudly: the loop's failure path turns that into redrivable
        ``engine failure`` terminals instead of streaming garbage."""
        if 0 <= tok < self.cfg.vocab_size:
            return
        self.stats["invalid_tokens"] = self.stats.get("invalid_tokens", 0) + 1
        if self.invalid_token_counter is not None:
            self.invalid_token_counter.inc()
        from pretraining_llm_tpu.resilience.integrity import IntegrityError

        err = IntegrityError(
            f"invalid token id {tok} for rid {req.rid} (vocab size "
            f"{self.cfg.vocab_size}): refusing to stream corrupted output"
        )
        # Structured fields for the loop's integrity_invalid_token event.
        err.rid = req.rid
        err.token = int(tok)
        raise err

    def _verify_shared(
        self, req: _Request, cached_len: int, shared: List[int]
    ) -> Tuple[int, List[int]]:
        """Verify-on-acquire (``kv_checksum``): re-digest every shared
        block against the checksum recorded when it was published. On the
        first mismatch, keep only the verified prefix of the hit, release
        the rest, and DROP the corrupt block from the cache — this
        admission (and every future one) re-prefills those tokens
        privately, so one flipped page costs exactly one hit's worth of
        prefill instead of poisoning every request that shares it."""
        from pretraining_llm_tpu.resilience import integrity

        for j, b in enumerate(shared):
            expected = self.prefix_cache.checksum_of(b)
            if expected is None or (
                integrity.kv_block_digest(self.pools, b) == expected
            ):
                continue
            self.prefix_cache.release_shared(shared[j:])
            self.prefix_cache.drop_block(b)
            self.stats["kv_mismatches"] = (
                self.stats.get("kv_mismatches", 0) + 1
            )
            if self.kv_mismatch_counter is not None:
                self.kv_mismatch_counter.inc()
            if self.decisions is not None:
                tr = self.traces.get(req.rid)
                self.decisions.record(
                    "drop_corrupt_block",
                    rid=req.rid,
                    trace_id=getattr(tr, "trace_id", None),
                    block=b,
                    verified_blocks=j,
                )
                # The engine has no bus of its own; the loop's decision log
                # carries the (replica-labelled) one.
                if self.decisions.bus is not None:
                    self.decisions.bus.emit(
                        "integrity_kv_mismatch", rid=req.rid, block=b,
                        verified_blocks=j,
                    )
            keep = shared[:j]
            if len(keep) < self.prefix_cache.min_blocks:
                if keep:
                    self.prefix_cache.release_shared(keep)
                return 0, []
            return min(cached_len, len(keep) * self.block_size), keep
        return cached_len, shared

    def _resolve_first(self, req: _Request) -> None:
        """Materialize a deferred admission token (device is done with it
        by the time any caller needs the value)."""
        if req.pending_first is None:
            return
        arr, i = req.pending_first
        req.pending_first = None
        tok = int(np.asarray(arr)[i])
        self._check_token(req, tok)
        req.generated.append(tok)
        # Prefill programs sample but never compute the logprob sliver:
        # the first token's entry is an explicit None placeholder.
        self._lp_append(req, None)
        self._emit_token(req, tok)
        if req.row is not None:
            self.tokens[req.row] = tok
            if tok == self.stop_token or len(req.generated) >= req.max_new:
                self._finish(req)

    # -- scheduling internals ---------------------------------------------

    def _cache_available(self) -> int:
        """Blocks admission may count on: the free list plus cold cached
        blocks the LRU would hand back on demand."""
        avail = self.alloc.available
        if self.prefix_cache is not None:
            avail += self.prefix_cache.evictable
        return avail

    def _cache_alloc(self, n: int) -> Optional[List[int]]:
        """``alloc.alloc(n)``, evicting cold cached blocks first when the
        free list alone cannot cover the request."""
        if self.prefix_cache is not None and n > self.alloc.available:
            evicted = self.prefix_cache.evict(n - self.alloc.available)
            if evicted and self.decisions is not None:
                self.decisions.record(
                    "evict_cold", blocks=evicted, reason="admission",
                )
        return self.alloc.alloc(n)

    def reserve_migration_blocks(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` pool blocks for adopted (migrated-in) KV pages, or
        None when serving pressure says no. Same watermark as admission:
        never take the pool below one spare block per active request —
        a migration is an optimization and must lose to live decode.
        Loop-thread only (callers come through EngineLoop.run_on_loop);
        the blocks are expected to be published into the prefix cache
        (where they become cold, i.e. reclaimable) or freed by the
        caller — they must not leak as unowned live blocks."""
        if n < 1:
            return None
        if self._cache_available() - n < self.n_active:
            return None
        return self._cache_alloc(n)

    def _admission_capacity(self) -> int:
        """How many queue heads could be admitted RIGHT NOW under the
        free-row + watermark rules, without committing anything — the
        ``admit_batch`` gate's lookahead. (With the prefix cache on this
        is conservative: cold blocks count as available, but each head is
        charged its FULL block need, ignoring possible hits.)"""
        free_rows = sum(r is None for r in self.rows)
        avail = self._cache_available()
        active = self.n_active
        count = 0
        for req in self.waiting:
            if count >= free_rows:
                break
            need = paged.required_blocks(len(req.prompt) + 1, self.block_size)
            if avail - need < active:
                break
            avail -= need
            active += 1
            count += 1
        return count

    def _admit(self, defer: bool = False) -> None:
        """FCFS admission: every queue head that fits claims a free row,
        then ALL claimed prompts prefill in ONE device program (batched
        admission — N arrivals used to pay N serialized prefill programs
        + N host-synced first-token samples, the dominant term of the
        measured 8x serving/decode gap at the window boundary).

        ``defer=True`` (pipelined run loop) keeps the sampled first
        tokens on device: bookkeeping that needs their VALUES (stop
        tokens, output lists) lags until the window they join is reaped,
        while scheduling math uses ``n_generated`` which already counts
        them.

        Cross-window admission batching (``admit_batch`` > 1, pipelined
        only): while the device has work, waiting prefills accumulate
        until one batched admission can take ``admit_batch`` of them —
        turning per-boundary dribble admissions (one prefill program
        each) into one larger prefill at the boundary where rows/pages
        free up. Greedy outputs are unaffected: a request's tokens depend
        only on its own prompt, never on when it was admitted."""
        if defer and self.admit_batch > 1 and self.waiting and self.n_active:
            goal = min(self.admit_batch, len(self.waiting), self.max_batch)
            if self._admission_capacity() < goal:
                self.stats["admit_deferrals"] = (
                    self.stats.get("admit_deferrals", 0) + 1
                )
                return
            self.stats["admit_batches"] = (
                self.stats.get("admit_batches", 0) + 1
            )
        admits: List[_Request] = []
        while self.waiting:
            free_rows = [i for i, r in enumerate(self.rows) if r is None]
            if not free_rows:
                break
            req: _Request = self.waiting[0]
            p = len(req.prompt)
            # +1: the first decode step writes slot p — its page must exist.
            need = paged.required_blocks(p + 1, self.block_size)
            # Prefix-cache lookup: retain the longest cached block-aligned
            # prefix and charge admission only for the uncached remainder.
            cached_len = 0
            shared: List[int] = []
            t_lookup = t_hit = 0.0
            if self.prefix_cache is not None:
                t_lookup = time.perf_counter()
                cached_len, shared = self.prefix_cache.acquire(req.prompt)
                if self.kv_checksum and shared:
                    cached_len, shared = self._verify_shared(
                        req, cached_len, shared
                    )
                t_hit = time.perf_counter()
            need_new = need - len(shared)
            # Admission watermark — where head-of-line admission stalls:
            # keep one growth block of headroom per already-running row,
            # else a nearly-dry pool admits + pays a full prefill only for
            # the newcomer to be preempted at the next older-row block
            # boundary (prefill thrash). The stalled head waits for active
            # rows to finish and free blocks; preemption happens on growth.
            # Cold cached blocks count as available — the LRU hands them
            # back before any live request is preempted.
            if self._cache_available() - need_new < self.n_active:
                if shared:
                    self.prefix_cache.release_shared(shared)
                break
            blocks = self._cache_alloc(need_new)
            assert blocks is not None, "watermark guarantees coverage"
            self.waiting.popleft()
            row = free_rows[0]
            req.blocks = shared + blocks
            req.n_shared = len(shared)
            req.row = row
            if self.prefix_cache is not None:
                # Counted only for COMMITTED admissions, so a stalled head
                # retried at every boundary cannot inflate the hit rate.
                if cached_len:
                    self.prefix_cache.note_hit(cached_len)
                else:
                    self.prefix_cache.note_miss()
            req.admit_order = self._admit_counter
            self._admit_counter += 1
            self.stats["admissions"] += 1
            if not self.prefill_chunk_tokens:
                # Chunked mode counts prefill (and recompute rework) at
                # chunk DISPATCH — where the tokens are actually paid —
                # so a mid-prefill cancellation never inflates either.
                self.stats["prefill_tokens"] += p - cached_len
                if req.preemptions > 0:
                    # Recompute-on-resume rework, counted where it is
                    # actually PAID: the re-admission's prefill (a cache
                    # hit on the victim's own published pages shrinks it).
                    self.stats["preempted_tokens_recomputed"] = (
                        self.stats.get("preempted_tokens_recomputed", 0)
                        + p - cached_len
                    )
                    if self.preempt_tokens_counter is not None:
                        self.preempt_tokens_counter.inc(p - cached_len)
            t = self.req_timing.get(req.rid)
            if t is not None:
                # setdefault: a preempted request's re-admission must not
                # move its queue-wait mark.
                t.setdefault("admit_s", self._now())
                if self.prefix_cache is not None:
                    # Accumulates: a preemption-resume hit on just-published
                    # pages adds its savings on top of the first admission's.
                    # Cache off -> key absent, so timing summaries (and the
                    # JSONL/body schemas built from them) are unchanged.
                    t["cached_tokens"] = t.get("cached_tokens", 0) + cached_len
            if self.traces:
                tr = self.traces.get(req.rid)
                if tr is not None:
                    if self.prefix_cache is not None:
                        # Recorded only for COMMITTED admissions (stalled
                        # heads would otherwise stack duplicate spans).
                        tr.span(
                            "prefix_cache.lookup", t_lookup, t_hit,
                            cached_tokens=cached_len, blocks=len(shared),
                        )
                    if "admit" not in tr.marks:
                        # Same setdefault rule: the queue span is submit ->
                        # FIRST row claim; preemption re-admissions keep it.
                        now_p = time.perf_counter()
                        tr.span(
                            "req.queue", tr.marks.get("submit", tr.t0), now_p,
                            n_prompt=p,
                        )
                        tr.marks["admit"] = now_p
            self.rows[row] = req  # claim now: n_active sees earlier admits
            self.tables[row, :] = 0
            self.tables[row, : len(req.blocks)] = req.blocks
            if self.prefill_chunk_tokens:
                # Chunked admission: claim the row and ALL its blocks
                # (same watermark math — the allocation is identical),
                # but run NO prefill here. The committed frontier starts
                # at the cached prefix; _dispatch_prefill_chunks streams
                # the rest in budgeted chunks, cache hits riding the
                # same lane with a head start.
                req.prefill_pos = cached_len
                self.seq_lens[row] = cached_len
            else:
                self.seq_lens[row] = p
            admits.append(req)
        if not admits:
            return
        if self.prefill_chunk_tokens:
            return  # prompts stream in via _dispatch_prefill_chunks
        # Cache hits prefill ONLY their uncached suffix (shared pages are
        # already in the table; PagedInfo seq = cached length), misses run
        # the full prefill — one batched program per non-empty group.
        miss = [r for r in admits if r.n_shared == 0]
        hits = [r for r in admits if r.n_shared > 0]
        if miss and self.quantize == "int8-kv":
            # Quantized-pool bit-identity: the monolithic lane's dense
            # flash-prefill shortcut attends the UNQUANTIZED local k/v,
            # while the suffix lane attends dequantized pool pages — the
            # two would commit DIFFERENT quantized bytes for the same
            # prompt, breaking identity across prefix-cache/chunked
            # configurations. Route every admission through the suffix
            # lane (cached_len 0 = full prompt) so page bytes are always
            # the same pure function of the token's prompt prefix.
            hits = miss + hits
            miss = []
        t_prefill = time.perf_counter()
        groups: List[Tuple[List[_Request], jax.Array]] = []
        if miss:
            self._key, sub = jax.random.split(self._key)
            prompts = [r.prompt for r in miss]
            prefill_ids = [
                r.blocks[: paged.required_blocks(len(r.prompt), self.block_size)]
                for r in miss
            ]
            toks_dev, self.pools = paged.prefill_into_pool_batched(
                self.params, self.cfg, self.pools, prompts, prefill_ids,
                sub, temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p, min_p=self.min_p, mesh=self.mesh,
            )
            if self.spec_k:
                # The draft cache must cover the same pages (its sampled
                # tokens are discarded — the target's first token above is
                # the round seed either way).
                _, self.d_pools = paged.prefill_into_pool_batched(
                    self.draft_params, self.draft_cfg, self.d_pools, prompts,
                    prefill_ids, sub, temperature=self.temperature,
                    mesh=self.mesh,
                )
            groups.append((miss, toks_dev))
        if hits:
            self._key, sub = jax.random.split(self._key)
            bs = self.block_size
            suffixes = [r.prompt[r.n_shared * bs:] for r in hits]
            tables_rows = self.tables[np.asarray([r.row for r in hits])]
            cached_lens = [r.n_shared * bs for r in hits]
            toks_dev, self.pools = paged.prefill_suffix_into_pool_batched(
                self.params, self.cfg, self.pools, suffixes, tables_rows,
                cached_lens, sub, temperature=self.temperature,
                top_k=self.top_k, top_p=self.top_p, min_p=self.min_p,
                mesh=self.mesh,
            )
            if self.spec_k:
                # Shared block ids index BOTH pools, so the draft's prefix
                # KV is already resident too — suffix-only there as well.
                _, self.d_pools = paged.prefill_suffix_into_pool_batched(
                    self.draft_params, self.draft_cfg, self.d_pools,
                    suffixes, tables_rows, cached_lens, sub,
                    temperature=self.temperature, mesh=self.mesh,
                )
            groups.append((hits, toks_dev))
        if self.traces:
            # Host-side prefill span (dispatch + any compile; the async
            # device compute itself overlaps the next windows). Batched
            # admissions share one interval — the per-request cost of a
            # shared program IS the shared wall time.
            t_prefill_end = time.perf_counter()
            for req in admits:
                tr = self.traces.get(req.rid)
                if tr is not None:
                    tr.span(
                        "req.prefill", t_prefill, t_prefill_end,
                        n_prompt=len(req.prompt), batch=len(admits),
                    )
        self.stats["tokens"] += len(admits)  # the prefill-sampled firsts
        if defer:
            for group, toks_dev in groups:
                for i, req in enumerate(group):
                    req.pending_first = (toks_dev, i)
                # Next dispatch merges these device scalars into its input
                # tokens without a host round trip.
                self._pending_admit_merges.append(
                    (toks_dev, list(range(len(group))), [r.row for r in group])
                )
            return
        for group, toks_dev in groups:
            toks = np.asarray(toks_dev)
            for i, req in enumerate(group):
                tok = int(toks[i])
                req.generated.append(tok)
                self._lp_append(req, None)  # prefill-sampled: no sliver
                self._emit_token(req, tok)
                self.tokens[req.row] = tok
                if tok == self.stop_token or len(req.generated) >= req.max_new:
                    self._finish(req)

    def _dispatch_prefill_chunks(self, defer: bool) -> bool:
        """Stream mid-prefill rows' next prompt chunks in ONE multi-token
        paged forward (the prefix-cache suffix lane with a PINNED token
        bucket), token-budgeted to ``prefill_chunk_tokens`` per tick so
        the decode window dispatched right after never waits behind more
        than one budget of prefill compute. FCFS by admission order;
        rows past the budget wait (a ``defer_prefill_chunk`` decision).
        A row's FINAL chunk samples its first output token from the last
        prompt position — exactly the monolithic prefill's sample — and
        the row joins the very next decode window. Returns True when a
        chunk program was dispatched (the interleave accounting hook).

        Commit discipline: a chunk is committed AT DISPATCH — its
        content is deterministic prompt data, not speculation — so
        ``seq_lens``/``prefill_pos`` advance immediately and a
        reconciliation flush never needs to rewind chunk state. In spec
        mode every chunked row also queues a merge entry: the next
        round's chained ``seq_dev`` must be reset to the committed
        frontier so the excluded row's lockstep garbage lands at/above
        it, never below."""
        if not self.prefill_chunk_tokens:
            return False
        pending = sorted(
            (r for r in self.rows
             if r is not None and r.prefill_pos is not None),
            key=lambda r: r.admit_order,
        )
        if not pending:
            return False
        budget = self.prefill_chunk_tokens
        group: List[_Request] = []
        chunks: List[List[int]] = []
        offsets: List[int] = []
        finals: List[bool] = []
        for req in pending:
            if budget <= 0:
                self.stats["chunk_deferrals"] = (
                    self.stats.get("chunk_deferrals", 0) + 1
                )
                if self.decisions is not None:
                    self.decisions.record(
                        "defer_prefill_chunk",
                        rid=req.rid,
                        trace_id=getattr(
                            self.traces.get(req.rid), "trace_id", None
                        ),
                        budget=self.prefill_chunk_tokens,
                        tokens_left=len(req.prompt) - req.prefill_pos,
                    )
                continue
            start = req.prefill_pos
            take = min(budget, len(req.prompt) - start)
            group.append(req)
            chunks.append(req.prompt[start:start + take])
            offsets.append(start)
            finals.append(start + take == len(req.prompt))
            budget -= take
        t_chunk = time.perf_counter()
        with _spans.span(
            "serving.dispatch_chunks",
            rows=len(group), tokens=sum(len(c) for c in chunks),
        ):
            self._key, sub = jax.random.split(self._key)
            tables_rows = self.tables[np.asarray([r.row for r in group])]
            toks_dev, self.pools = paged.prefill_suffix_into_pool_batched(
                self.params, self.cfg, self.pools, chunks, tables_rows,
                offsets, sub, temperature=self.temperature,
                top_k=self.top_k, top_p=self.top_p, min_p=self.min_p,
                mesh=self.mesh, t_bucket=self.prefill_chunk_tokens,
            )
            if self.spec_k:
                # The draft pool must hold the same chunk K/V (shared
                # block ids index both pools); its sampled tokens are
                # discarded — the target's final-chunk token seeds the
                # round either way.
                _, self.d_pools = paged.prefill_suffix_into_pool_batched(
                    self.draft_params, self.draft_cfg, self.d_pools,
                    chunks, tables_rows, offsets, sub,
                    temperature=self.temperature, mesh=self.mesh,
                    t_bucket=self.prefill_chunk_tokens,
                )
        t_chunk_end = time.perf_counter()
        final_idxs: List[int] = []
        for i, req in enumerate(group):
            take = len(chunks[i])
            req.prefill_pos = None if finals[i] else offsets[i] + take
            self.seq_lens[req.row] = offsets[i] + take
            self.stats["prefill_chunks"] += 1
            self.stats["prefill_chunk_tokens"] += take
            self.stats["prefill_tokens"] += take
            if req.preemptions > 0:
                # Every chunk of a preemption resume is recompute rework
                # (its prompt IS the prior incarnation's prompt+output).
                self.stats["preempted_tokens_recomputed"] = (
                    self.stats.get("preempted_tokens_recomputed", 0) + take
                )
                if self.preempt_tokens_counter is not None:
                    self.preempt_tokens_counter.inc(take)
            if self.chunk_counter is not None:
                self.chunk_counter.inc()
            if self.chunk_tokens_counter is not None:
                self.chunk_tokens_counter.inc(take)
            if self.traces:
                tr = self.traces.get(req.rid)
                if tr is not None:
                    # One span per (request, chunk); batched groups share
                    # the host interval, like req.prefill. The request's
                    # decode windows all start after its final chunk, so
                    # these never overlap its req.window spans — the
                    # waterfall's sum-to-e2e invariant survives.
                    tr.span(
                        "req.prefill_chunk", t_chunk, t_chunk_end,
                        offset=offsets[i], chunk_tokens=take,
                        final=finals[i], batch=len(group),
                    )
            if finals[i]:
                final_idxs.append(i)
        if final_idxs:
            self.stats["tokens"] += len(final_idxs)  # prefill-sampled firsts
        if defer:
            for i in final_idxs:
                group[i].pending_first = (toks_dev, i)
            if self.spec_k:
                # ALL chunked rows merge: finals contribute their real
                # first token; non-finals just pin seq_dev back to the
                # committed frontier (their base token is garbage and
                # never consumed — the row is outside every snapshot).
                self._pending_admit_merges.append(
                    (toks_dev, list(range(len(group))),
                     [r.row for r in group])
                )
            elif final_idxs:
                self._pending_admit_merges.append(
                    (toks_dev, final_idxs,
                     [group[i].row for i in final_idxs])
                )
        else:
            toks = np.asarray(toks_dev)
            for i in final_idxs:
                req = group[i]
                tok = int(toks[i])
                req.generated.append(tok)
                self._lp_append(req, None)  # prefill-sampled: no sliver
                self._emit_token(req, tok)
                self.tokens[req.row] = tok
                if tok == self.stop_token or len(req.generated) >= req.max_new:
                    self._finish(req)
        return True

    def _ensure_write_pages(self, horizon: int = 1, prealloc: int = 0) -> None:
        """Every active row's next ``horizon`` write slots must have
        allocated pages (writes landing in a surviving row's unallocated
        page would silently fall through to the scratch block and LOSE
        that token's K/V); when the pool is dry, drain the in-flight
        queue, then roll back other rows' speculative page grants, and
        only then preempt youngest-first (recompute-on-resume) so the
        oldest admitted requests always make progress. Slots a row cannot
        reach before finishing (remaining < horizon) or that exceed table
        capacity don't need pages — those surplus writes are
        scratch-redirected and discarded by design.

        ``prealloc`` extends the target a further N slots
        OPPORTUNISTICALLY: extra pages come from the free list only
        (never a flush, never a preemption) and keep one headroom block
        per active row so admission's watermark is untouched. The
        pipelined scheduler uses it to cover the full in-flight horizon
        (window * depth), making a mid-queue page flush the exception;
        over-grants are speculative and rolled back at release,
        preemption, or by _reclaim_spec_pages under pressure."""
        capacity = self.max_blocks * self.block_size
        for row in range(self.max_batch):
            req = self.rows[row]
            if req is None:
                continue
            # n_generated may lag the device by the in-flight queue
            # (pipelined mode): remaining is then an OVERestimate, so the
            # horizon only ever covers extra slots — writes stay inside
            # allocated (or scratch-redirected) pages either way.
            remaining = req.max_new - req.n_generated
            last_write = min(
                int(self.seq_lens[row]) + min(horizon, remaining) - 1,
                capacity - 1,
            )
            need_pages = last_write // self.block_size + 1
            while len(req.blocks) < need_pages:
                got = self.alloc.alloc(1)
                if got is not None:
                    req.blocks.extend(got)
                    self.tables[row, len(req.blocks) - 1] = got[0]
                    continue
                if self._inflight:
                    # Pool dry with windows in flight: drain them first —
                    # their finished rows may free blocks, and preemption
                    # bookkeeping (prompt+generated) must be exact.
                    self._flush_inflight()
                    if self.rows[row] is not req:
                        break  # this row finished in the flush
                    continue  # retry allocation against the fresh state
                if self._reclaim_spec_pages(horizon):
                    continue  # speculative grants rolled back; retry
                if (
                    self.prefix_cache is not None
                    and self.prefix_cache.evict(1)
                ):
                    if self.decisions is not None:
                        self.decisions.record(
                            "evict_cold", blocks=1, reason="growth",
                            rid=req.rid,
                            trace_id=getattr(
                                self.traces.get(req.rid), "trace_id", None
                            ),
                        )
                    continue  # cold cache evicted BEFORE any preemption
                victim = max(
                    (r for r in self.rows if r is not None),
                    key=lambda r: r.admit_order,
                )
                self._preempt(victim)
                if victim is req or self.rows[row] is not req:
                    break  # this row is gone; nothing more to grow
        if prealloc > 0:
            self._prealloc_write_pages(horizon + prealloc)

    def _prealloc_write_pages(self, horizon: int) -> None:
        """Best-effort page growth toward ``horizon`` write slots per live
        row — free-list only, stopping at one headroom block per active
        row (the same constant admission's watermark protects)."""
        capacity = self.max_blocks * self.block_size
        for row in range(self.max_batch):
            req = self.rows[row]
            if req is None:
                continue
            remaining = req.max_new - req.n_generated
            last_write = min(
                int(self.seq_lens[row]) + min(horizon, remaining) - 1,
                capacity - 1,
            )
            need_pages = last_write // self.block_size + 1
            want = need_pages - len(req.blocks)
            spare = self.alloc.available - self.n_active
            if want <= 0 or spare <= 0:
                continue
            got = self.alloc.alloc_upto(min(want, spare))
            for b in got:
                req.blocks.append(b)
                self.tables[row, len(req.blocks) - 1] = b
            if got:
                self.stats["page_preallocs"] = (
                    self.stats.get("page_preallocs", 0) + len(got)
                )
            if len(got) < want:
                return  # pool has no spare pages this boundary

    def _reclaim_spec_pages(self, horizon: int) -> int:
        """Roll back speculative page grants: free every live row's
        blocks beyond its committed ``horizon`` coverage. Only legal with
        an empty in-flight queue (callers flush first) — then no write
        can target the reclaimed pages, and all live K/V sits below the
        committed frontier, which the kept coverage strictly contains.
        Returns the number of blocks returned to the pool."""
        assert not self._inflight, "reclaim needs committed state"
        capacity = self.max_blocks * self.block_size
        freed = 0
        for row in range(self.max_batch):
            req = self.rows[row]
            if req is None:
                continue
            remaining = req.max_new - req.n_generated
            last_write = min(
                int(self.seq_lens[row]) + min(horizon, remaining) - 1,
                capacity - 1,
            )
            need_pages = last_write // self.block_size + 1
            if len(req.blocks) > need_pages:
                surplus = req.blocks[need_pages:]
                del req.blocks[need_pages:]
                self.tables[row, need_pages:] = 0
                self.alloc.free(surplus)
                freed += len(surplus)
        if freed:
            self.stats["page_reclaims"] = (
                self.stats.get("page_reclaims", 0) + freed
            )
            if self.decisions is not None:
                self.decisions.record(
                    "reclaim_spec", blocks=freed, horizon=horizon,
                )
        return freed

    def _preempt(self, req: _Request) -> None:
        """Evict a running request: free its memory, requeue it at the
        FRONT with prompt+generated as the new prompt (vLLM-style recompute
        recovery — cheap for short generations, and the only option that
        frees ALL its blocks)."""
        # A victim admitted this very boundary may still hold its first
        # token on device; resolve it so the resumed prompt is exact.
        # Resolution can itself FINISH the request (stop token /
        # max_new=1) — then its blocks are already freed and there is
        # nothing to preempt.
        self._resolve_first(req)
        if req.row is None:
            return
        row = req.row
        self.stats["preemptions"] += 1
        if self.preempt_counter is not None:
            self.preempt_counter.inc()
        new_prompt = req.prompt + req.generated
        remaining = req.max_new - len(req.generated)
        assert remaining >= 1, "finished requests are reaped, not preempted"
        if self.decisions is not None:
            tr = self.traces.get(req.rid)
            self.decisions.record(
                "preempt",
                rid=req.rid,
                trace_id=getattr(tr, "trace_id", None),
                row=row,
                # Why this victim: youngest-first by admission order, so
                # the oldest admitted requests always make progress.
                victim_admit_order=req.admit_order,
                blocks_reclaimed=len(req.blocks),
                tokens_to_recompute=len(req.generated),
                preemption_n=req.preemptions + 1,
            )
        self._release_row(req)
        fresh = _Request(
            req.rid, new_prompt, remaining,
            prefix=req.prefix + req.generated,
            preemptions=req.preemptions + 1,
        )
        self.waiting.appendleft(fresh)

    def _finish(self, req: _Request) -> None:
        out = req.prefix + req.generated
        if self.stop_token is not None and out and out[-1] == self.stop_token:
            out = out[:-1]
        self.finished[req.rid] = out
        if self.logprobs_k:
            # Stop-token stripping above must strip its entry too: keep
            # the per-rid list exactly aligned with the output tokens.
            lps = self.logprobs.get(req.rid)
            if lps is not None and len(lps) > len(out):
                self.logprobs[req.rid] = lps[: len(out)]
        t = self.req_timing.get(req.rid)
        if t is not None:
            t["end_s"] = self._now()
        self._release_row(req)
        if self.on_finish is not None:
            self.on_finish(req.rid, out)

    def _release_row(self, req: _Request) -> None:
        row = req.row
        assert row is not None
        if self.prefix_cache is not None:
            # Publish the row's committed full blocks back to the cache
            # (and deref its shared ones). Only slots strictly below
            # p + g - 1 are guaranteed written — the LAST sampled token
            # may never have been fed — and any surplus in-flight window
            # writes at or above that frontier, so publishing below it is
            # safe even mid-pipeline.
            g = len(req.generated)
            p = len(req.prompt)
            if req.prefill_pos is not None:
                # Mid-prefill release (chunked cancellation/preemption):
                # only chunks below prefill_pos ever landed — publish
                # exactly those. A resume then re-acquires its OWN
                # partial prefix from the cache, so the rework shrinks
                # to the unprefilled remainder.
                publish_len = req.prefill_pos
            else:
                publish_len = p + g - 1 if g else p
            published = self.prefix_cache.release_row(
                req.prompt + req.generated, req.blocks, req.n_shared,
                publish_len,
            )
            if self.kv_checksum and published:
                # Record content digests AT publish — the pages below the
                # committed frontier are final (shared pages are read-only
                # and a row only ever writes ahead of it), so the digest
                # taken here is the truth every later acquire verifies.
                from pretraining_llm_tpu.resilience import integrity

                for b in published:
                    self.prefix_cache.set_checksum(
                        b, integrity.kv_block_digest(self.pools, b)
                    )
        else:
            self.alloc.free(req.blocks)
        req.blocks = []
        req.n_shared = 0
        req.row = None
        self.rows[row] = None
        self.tables[row, :] = 0
        self.seq_lens[row] = 0
        self.tokens[row] = 0
