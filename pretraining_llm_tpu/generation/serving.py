"""Continuous-batching serving engine over the paged KV cache.

Offline generation (`generation.generate`) compiles one program per
(batch, bucket) and every row enters and leaves together. A serving
workload is the opposite: requests arrive whenever, finish whenever, and
the device must never idle waiting for the longest row. This engine keeps
ONE compiled lockstep decode program (`paged.paged_decode_step`, shape
(max_batch, max_blocks) fixed at construction) and mutates only host-side
int32 state between steps:

  admission   — a waiting request claims a free batch row + pool blocks,
                prefills its prompt into its pages, joins the next step;
  growth      — a row crossing a block boundary gets one more block;
  eviction    — a finished row frees its blocks and the row slot;
  preemption  — when the pool runs dry, the youngest running request is
                evicted and requeued (recompute-on-resume: its prompt +
                generated-so-far become the new prompt), so the oldest
                requests always run to completion — no deadlock.

TPU-first shape discipline: idle rows keep decoding into the reserved
scratch block (block 0) with their outputs ignored — a masked no-op is
cheaper than a recompile, and XLA sees a static (max_batch,) program
forever. The reference has no serving stack (batch-1 fixed-count
generate, /root/reference/src/models/transformer.py:96-114).
"""

from __future__ import annotations

import dataclasses
import warnings
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.generation import paged
from pretraining_llm_tpu.generation.sampling import sample_logits
from pretraining_llm_tpu.models import transformer


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new: int
    generated: List[int] = dataclasses.field(default_factory=list)
    # Tokens generated in earlier incarnations of a preempted request:
    # they were folded into `prompt` for recompute-on-resume, but they
    # belong to the OUTPUT (see _preempt/_finish).
    prefix: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    row: Optional[int] = None
    admit_order: int = -1  # monotonically increasing per admission
    preemptions: int = 0


class ServingEngine:
    """Continuous-batching text generation over a shared paged KV pool.

    Usage::

        eng = ServingEngine(params, cfg, max_batch=4, n_blocks=128)
        rid = eng.submit(prompt_ids, max_new_tokens=64)
        outputs = eng.run()        # {rid: [token, ...]}

    ``temperature=0`` (default) decodes greedily; sampling parameters are
    engine-global (per-request values would either recompile or pay a
    (B,)-vector mask per knob — the global default matches the common
    single-model deployment).
    """

    def __init__(
        self,
        params: Any,
        cfg: ModelConfig,
        *,
        max_batch: int = 8,
        n_blocks: int = 256,
        block_size: int = 64,
        max_seq: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        stop_token: Optional[int] = None,
        seed: int = 0,
        steps_per_sched: int = 1,
        mesh: Any = None,
    ):
        if cfg.n_experts:
            # Same restriction as ragged generate: pad slots inside a
            # prefill bucket would compete for expert capacity.
            raise ValueError("paged serving does not support MoE models yet")
        if cfg.doc_mask_token >= 0:
            # Decode sessions are single documents; forward() rejects the
            # combination with a cache (same sanitization as generate()).
            cfg = dataclasses.replace(cfg, doc_mask_token=-1)
        self.params = params
        self.cfg = cfg
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        # Clamp max_seq so EVERY reachable prefill bucket fits the model
        # context: prefill pads prompts up to whole blocks, and a preempted
        # request can be readmitted with prompt+generated as its new prompt
        # — any p <= floor(ctx/bs)*bs then buckets within ctx, so
        # make_kv_cache can never blow up mid-serving on an accepted
        # request (block sizes that don't divide ctx are the trap).
        ctx_aligned = (cfg.context_length // self.block_size) * self.block_size
        self.max_seq = int(min(max_seq or cfg.context_length, ctx_aligned))
        # Table width: no row can ever hold more than the pool's usable
        # blocks, so clamping cuts the per-step gather/score width for
        # small pools (the attention kv_len is max_blocks * block_size).
        self.max_blocks = min(
            paged.required_blocks(self.max_seq, self.block_size), n_blocks - 1
        )
        self.temperature = temperature
        self.top_k, self.top_p, self.min_p = top_k, top_p, min_p
        self.stop_token = stop_token
        # Multi-step scheduling: decode windows of K steps per device
        # dispatch (one compiled scan), reaping/admitting only at window
        # boundaries — the lever against per-step host dispatch latency
        # on the tunneled backend. Rows finishing mid-window overrun into
        # their own pages (surplus discarded host-side).
        self.steps_per_sched = max(1, int(steps_per_sched))

        # Sharded serving: params arrive pre-sharded
        # (generate.shard_params_for_inference); the KV pools shard their
        # kv_heads dim over the mesh's 'tensor' axis (each TP shard holds
        # its own heads' pages — the same head split as training TP), and
        # decode activations follow via the in-forward constraints.
        self.mesh = mesh
        self.pools = transformer.make_paged_kv_pool(cfg, n_blocks, block_size)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            tp = mesh.shape.get("tensor", 1)
            head_ax = "tensor" if (tp > 1 and cfg.kv_heads % tp == 0) else None
            if tp > 1 and head_ax is None:
                # Same loudness convention as the flash blockwise fallback:
                # silent replication here multiplies KV HBM by the tensor
                # axis size on every shard.
                warnings.warn(
                    f"serving KV pool: kv_heads={cfg.kv_heads} not divisible "
                    f"by tensor={tp}; pool REPLICATED over the tensor axis "
                    f"({tp}x KV HBM per shard). Choose tp dividing kv_heads.",
                    stacklevel=2,
                )
            # Every pool leaf carries kv_heads at axis -2 (scale pools have
            # a trailing 1); stacked leaves are 5-dim, unstacked 4-dim.
            self.pools = jax.tree.map(
                lambda leaf: jax.device_put(
                    leaf,
                    NamedSharding(
                        mesh,
                        PartitionSpec(
                            *([None] * (leaf.ndim - 2)), head_ax, None
                        ),
                    ),
                ),
                self.pools,
            )
        self.alloc = paged.BlockAllocator(n_blocks)
        self.tables = np.zeros((self.max_batch, self.max_blocks), np.int32)
        self.seq_lens = np.zeros((self.max_batch,), np.int32)
        self.tokens = np.zeros((self.max_batch,), np.int32)
        self.rows: List[Optional[_Request]] = [None] * self.max_batch
        self.waiting: deque = deque()
        self.finished: Dict[int, List[int]] = {}
        self._key = jax.random.PRNGKey(seed)
        self._next_rid = 0
        self._admit_counter = 0
        self.stats = {"steps": 0, "tokens": 0, "preemptions": 0, "admissions": 0}

    # -- public API --------------------------------------------------------

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int) -> int:
        """Queue a request; returns its id. Fails fast if the request can
        never fit (prompt + generation must fit max_seq AND the pool)."""
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = p + max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"prompt({p}) + max_new({max_new_tokens}) = {total} exceeds "
                f"max_seq={self.max_seq}"
            )
        if paged.required_blocks(total, self.block_size) > self.alloc.n_blocks - 1:
            raise ValueError(
                f"request needs {paged.required_blocks(total, self.block_size)} "
                f"blocks; the pool only has {self.alloc.n_blocks - 1}"
            )
        rid = self._next_rid
        self._next_rid += 1
        self.waiting.append(_Request(rid, list(prompt_ids), int(max_new_tokens)))
        return rid

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self.rows)

    def has_work(self) -> bool:
        return bool(self.waiting) or self.n_active > 0

    def step(self) -> None:
        """One scheduling round: admit -> grow/preempt -> a window of
        ``steps_per_sched`` lockstep decode steps -> reap. A no-op when
        nothing is running or waiting."""
        self._admit()
        if self.n_active == 0:
            return
        n = self.steps_per_sched
        self._ensure_write_pages(horizon=n)
        if self.n_active == 0:  # everyone got preempted (tiny pool)
            return
        # Backstop for the PagedInfo capacity invariant (submit() bounds
        # every request structurally; this keeps scheduler bugs loud).
        # Multi-step windows may overshoot capacity mid-window — that is
        # handled by the model's scratch-redirect guard; the invariant
        # here is on the WINDOW-START state only.
        paged.check_paged_bounds(self.tables, self.seq_lens, self.block_size)
        self._key, sub = jax.random.split(self._key)
        common = dict(
            cfg=self.cfg, temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, min_p=self.min_p, mesh=self.mesh,
        )
        dev_args = (
            self.params, self.pools, jnp.asarray(self.tokens),
            jnp.asarray(self.tables), jnp.asarray(self.seq_lens), sub,
        )
        if n == 1:
            nxt, self.pools = paged.paged_decode_step(*dev_args, **common)
            window = np.asarray(nxt)[:, None]  # (B, 1)
        else:
            toks, self.pools = paged.paged_decode_steps(
                *dev_args, n_steps=n, **common
            )
            window = np.asarray(toks)  # (B, n)
        self.stats["steps"] += n
        for row, req in enumerate(self.rows):
            if req is None:
                continue
            for tok in (int(t) for t in window[row]):
                self.seq_lens[row] += 1  # this step wrote the pending token
                req.generated.append(tok)
                self.tokens[row] = tok
                self.stats["tokens"] += 1
                if tok == self.stop_token or len(req.generated) >= req.max_new:
                    self._finish(req)
                    break  # surplus window tokens for this row are discarded

    def run(self) -> Dict[int, List[int]]:
        """Drive step() until every submitted request has finished."""
        while self.has_work():
            self.step()
        return self.finished

    # -- scheduling internals ---------------------------------------------

    def _admit(self) -> None:
        """FCFS admission: the head of the queue claims a free row when the
        pool covers its prompt pages + the first decode write."""
        while self.waiting:
            free_rows = [i for i, r in enumerate(self.rows) if r is None]
            if not free_rows:
                return
            req: _Request = self.waiting[0]
            p = len(req.prompt)
            # +1: the first decode step writes slot p — its page must exist.
            need = paged.required_blocks(p + 1, self.block_size)
            # Admission watermark — where head-of-line admission stalls:
            # keep one growth block of headroom per already-running row,
            # else a nearly-dry pool admits + pays a full prefill only for
            # the newcomer to be preempted at the next older-row block
            # boundary (prefill thrash). The stalled head waits for active
            # rows to finish and free blocks; preemption happens on growth.
            if self.alloc.available - need < self.n_active:
                return
            blocks = self.alloc.alloc(need)
            assert blocks is not None, "watermark guarantees coverage"
            self.waiting.popleft()
            row = free_rows[0]
            prefill_pages = paged.required_blocks(p, self.block_size)
            last, self.pools = paged.prefill_into_pool(
                self.params, self.cfg, self.pools, req.prompt,
                blocks[:prefill_pages], mesh=self.mesh,
            )
            self._key, sub = jax.random.split(self._key)
            tok = int(
                sample_logits(
                    last[None], sub, temperature=self.temperature,
                    top_k=self.top_k, top_p=self.top_p, min_p=self.min_p,
                )[0]
            )
            req.blocks = blocks
            req.row = row
            req.admit_order = self._admit_counter
            self._admit_counter += 1
            self.stats["admissions"] += 1
            req.generated.append(tok)
            self.stats["tokens"] += 1  # the prefill-sampled first token
            self.rows[row] = req
            self.tables[row, :] = 0
            self.tables[row, : len(blocks)] = blocks
            self.seq_lens[row] = p
            self.tokens[row] = tok
            if tok == self.stop_token or len(req.generated) >= req.max_new:
                self._finish(req)

    def _ensure_write_pages(self, horizon: int = 1) -> None:
        """Every active row's next ``horizon`` write slots must have
        allocated pages (writes landing in a surviving row's unallocated
        page would silently fall through to the scratch block and LOSE
        that token's K/V); when the pool is dry, preempt youngest-first
        (recompute-on-resume) so the oldest admitted requests always make
        progress. Slots a row cannot reach before finishing (remaining <
        horizon) or that exceed table capacity don't need pages — those
        surplus writes are scratch-redirected and discarded by design."""
        capacity = self.max_blocks * self.block_size
        for row in range(self.max_batch):
            req = self.rows[row]
            if req is None:
                continue
            remaining = req.max_new - len(req.generated)
            last_write = min(
                int(self.seq_lens[row]) + min(horizon, remaining) - 1,
                capacity - 1,
            )
            need_pages = last_write // self.block_size + 1
            while len(req.blocks) < need_pages:
                got = self.alloc.alloc(1)
                if got is not None:
                    req.blocks.extend(got)
                    self.tables[row, len(req.blocks) - 1] = got[0]
                    continue
                victim = max(
                    (r for r in self.rows if r is not None),
                    key=lambda r: r.admit_order,
                )
                self._preempt(victim)
                if victim is req:
                    break  # this row is gone; nothing more to grow

    def _preempt(self, req: _Request) -> None:
        """Evict a running request: free its memory, requeue it at the
        FRONT with prompt+generated as the new prompt (vLLM-style recompute
        recovery — cheap for short generations, and the only option that
        frees ALL its blocks)."""
        row = req.row
        assert row is not None
        self.stats["preemptions"] += 1
        new_prompt = req.prompt + req.generated
        remaining = req.max_new - len(req.generated)
        assert remaining >= 1, "finished requests are reaped, not preempted"
        self._release_row(req)
        fresh = _Request(
            req.rid, new_prompt, remaining,
            prefix=req.prefix + req.generated,
            preemptions=req.preemptions + 1,
        )
        self.waiting.appendleft(fresh)

    def _finish(self, req: _Request) -> None:
        out = req.prefix + req.generated
        if self.stop_token is not None and out and out[-1] == self.stop_token:
            out = out[:-1]
        self.finished[req.rid] = out
        self._release_row(req)

    def _release_row(self, req: _Request) -> None:
        row = req.row
        assert row is not None
        self.alloc.free(req.blocks)
        req.blocks = []
        req.row = None
        self.rows[row] = None
        self.tables[row, :] = 0
        self.seq_lens[row] = 0
        self.tokens[row] = 0
