"""Speculative decoding: a small draft model proposes k tokens, the target
verifies them in ONE forward pass.

Serving-latency feature beyond the reference (whose generation is a
cache-less batch-1 loop, generate_text.py:41-42; this framework's standard
path is `generation.generate`). Decode is memory-bound — each target step
streams the full weights for one token — so letting a cheap draft model
propose k tokens and the target verify all of them in a single (k+1)-token
forward multiplies tokens-per-weight-stream by the acceptance rate.

Correctness contract (tested):
  - GREEDY (temperature=0) speculative output equals target-only greedy
    decoding for ANY draft model — acceptance compares the target argmax
    against the proposal, and the correction token is the target argmax
    itself. Bit-identical at fp32 (pinned by test); under bf16 compute a
    NEAR-TIE argmax can differ, because the (k+1)-token verify forward and
    the 1-token decode forward reduce in different orders.
  - Sampling uses the standard accept/reject rule (Leviathan et al. 2023;
    Chen et al. 2023): accept d_i with prob min(1, p(d_i)/q(d_i)); on the
    first rejection resample from norm(max(p - q, 0)); if all k accepted,
    sample the bonus token from the target's (k+1)-th distribution. The
    output distribution equals target-only sampling.

Design (one jitted program, batch 1 — the latency-bound serving shape):
  - Both models keep KV caches over the SAME slot layout: after a round,
    slots [0, P+k] are written in both; the accepted frontier advances by
    n_acc + 1 and the garbage above it is masked by causality, then
    overwritten by the next round's writes (the cached-decode forward
    masks kv positions >= cache_index + Tq).
  - The draft phase runs k sampling steps plus one WRITE-ONLY step for the
    k-th proposal, so the draft cache always covers the same slots as the
    target cache regardless of how many proposals are accepted.
  - A `lax.while_loop` round emits between 1 and k+1 tokens into a fixed
    (max_new + k + 1) buffer; the loop stops once max_new tokens exist.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.models import transformer


def _sanitize(cfg: ModelConfig) -> ModelConfig:
    """Decode-time config hygiene (mirrors generate()): doc masking is a
    training-time structure; ring/ulysses fall back inside dispatch."""
    if cfg.doc_mask_token >= 0:
        cfg = dataclasses.replace(cfg, doc_mask_token=-1)
    return cfg


def _probs(logits: jax.Array, temperature: float) -> jax.Array:
    """(V,) float32 target/draft distribution at the round's temperature.
    temperature=0 -> one-hot argmax (greedy acceptance/correction)."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jax.nn.one_hot(jnp.argmax(logits), logits.shape[-1])
    return jax.nn.softmax(logits / temperature)


def _sample_from(probs: jax.Array, key: jax.Array, temperature: float) -> jax.Array:
    """ONE sampling rule for every site (seed, draft steps, correction):
    greedy argmax at temperature 0, categorical over the dist otherwise."""
    if temperature == 0.0:
        return jnp.argmax(probs).astype(jnp.int32)
    return jax.random.categorical(key, jnp.log(probs + 1e-30)).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("cfg_t", "cfg_d", "total", "max_new_tokens", "k",
                     "temperature"),
)
def _spec_jit(params_t, params_d, prompt, key, *, cfg_t, cfg_d, total,
              max_new_tokens, k, temperature):
    """Module-level jit so repeated calls with the same static config
    hit the compile cache (a per-call closure would recompile every
    invocation — the repo-wide _generate_jit pattern)."""
    v = cfg_t.vocab_size
    p_len = prompt.shape[1]
    t_cache = transformer.make_kv_cache(cfg_t, 1, total)
    d_cache = transformer.make_kv_cache(cfg_d, 1, total)

    # Prefill both models; the target's last position seeds token 0.
    t_logits, t_cache = transformer.forward(
        params_t, prompt, cfg_t, kv_cache=t_cache, cache_index=jnp.int32(0)
    )
    _, d_cache = transformer.forward(
        params_d, prompt, cfg_d, kv_cache=d_cache, cache_index=jnp.int32(0)
    )
    key, sub = jax.random.split(key)
    t0 = _sample_from(_probs(t_logits[0, -1], temperature), sub, temperature)

    out = jnp.zeros((max_new_tokens + k + 1,), jnp.int32)
    out = out.at[0].set(t0)

    def round_body(carry):
        t_cache, d_cache, out, count, last, idx, key, stats = carry
        # idx = slot of `last` (the newest accepted token, not yet in
        # either cache); this round writes slots [idx, idx + k].

        # --- draft: k sampling steps + 1 write-only step -------------
        def draft_step(c, _):
            d_cache, tok, key, j = c
            logits, d_cache = transformer.forward(
                params_d, tok[None, None], cfg_d, kv_cache=d_cache,
                cache_index=idx + j,
            )
            q = _probs(logits[0, 0], temperature)
            key, sub = jax.random.split(key)
            nxt = _sample_from(q, sub, temperature)
            return (d_cache, nxt, key, j + 1), (nxt, q)

        (d_cache, d_last, key, _), (drafts, q_dists) = jax.lax.scan(
            draft_step, (d_cache, last, key, jnp.int32(0)), None, length=k
        )
        # Write-only: park d_k's K/V so the draft cache covers slot
        # idx + k like the target's will (logits unused).
        _, d_cache = transformer.forward(
            params_d, d_last[None, None], cfg_d, kv_cache=d_cache,
            cache_index=idx + k,
        )

        # --- target: verify all k proposals in ONE forward -----------
        seq = jnp.concatenate([last[None], drafts])  # (k+1,)
        t_logits, t_cache = transformer.forward(
            params_t, seq[None], cfg_t, kv_cache=t_cache, cache_index=idx
        )
        p_dists = jax.vmap(lambda l: _probs(l, temperature))(
            t_logits[0]
        )  # (k+1, V): p_dists[i] is the target dist AFTER seq[i]

        # --- accept / reject -----------------------------------------
        key, sub_u, sub_r = jax.random.split(key, 3)
        p_at = p_dists[jnp.arange(k), drafts]  # p_i(d_i)
        q_at = q_dists[jnp.arange(k), drafts]  # q_i(d_i)
        if temperature == 0.0:
            accepts = p_at > 0.0  # one-hot: accepted iff argmax == d_i
        else:
            u = jax.random.uniform(sub_u, (k,))
            accepts = u < jnp.minimum(1.0, p_at / jnp.maximum(q_at, 1e-30))
        n_acc = jnp.sum(jnp.cumprod(accepts.astype(jnp.int32))).astype(jnp.int32)

        # Final token of the round: the target's correction at the
        # first rejected position, or the bonus after k acceptances.
        # (greedy: both reduce to the target argmax at position n_acc.)
        p_final = p_dists[n_acc]
        if temperature == 0.0:
            final = _sample_from(p_final, sub_r, temperature)
        else:
            q_pad = jnp.concatenate(
                [q_dists, jnp.zeros((1, v), jnp.float32)]
            )  # bonus position: residual vs q=0 == p itself
            resid = jnp.maximum(p_final - q_pad[n_acc], 0.0)
            resid = resid / jnp.maximum(jnp.sum(resid), 1e-30)
            final = _sample_from(resid, sub_r, temperature)

        emit = jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)])
        emit = emit.at[n_acc].set(final)  # (k+1,); valid prefix n_acc+1
        out = jax.lax.dynamic_update_slice(out, emit, (count,))
        n_emit = n_acc + 1
        stats = {
            "rounds": stats["rounds"] + 1,
            "proposed": stats["proposed"] + k,
            "accepted": stats["accepted"] + n_acc,
        }
        return (
            t_cache, d_cache, out, count + n_emit, emit[n_acc],
            idx + n_emit, key, stats,
        )

    def round_cond(carry):
        return carry[3] < max_new_tokens

    stats0 = {
        "rounds": jnp.int32(0), "proposed": jnp.int32(0),
        "accepted": jnp.int32(0),
    }
    (_, _, out, count, _, _, _, stats) = jax.lax.while_loop(
        round_cond,
        round_body,
        (t_cache, d_cache, out, jnp.int32(1), t0, jnp.int32(p_len), key,
         stats0),
    )
    return out[:max_new_tokens], stats


@jax.jit
def spec_next_inputs(
    emit: jax.Array,      # (B, k+1) int32 round emissions
    n_emit: jax.Array,    # (B,) int32 tokens emitted per row (>= 1)
    seq_lens: jax.Array,  # (B,) int32 frontier the round was dispatched at
) -> Tuple[jax.Array, jax.Array]:
    """Next round's (seed token, frontier) chained on-device from a
    ``paged_spec_round`` result, without a host sync. The last emitted
    token of row b is ``emit[b, n_emit[b]-1]`` — by construction the
    round's ``final`` token, i.e. exactly the token the synchronous
    scheduler would feed back after consuming the round on the host. This
    is what lets speculative rounds join the serving engine's in-flight
    window queue: the device chains round k+1 off round k while the host
    is still reaping round k-1."""
    b = emit.shape[0]
    nxt = emit[jnp.arange(b), jnp.maximum(n_emit, 1) - 1]
    return nxt, seq_lens + n_emit


def generate_speculative(
    params_target: Any,
    cfg_target: ModelConfig,
    params_draft: Any,
    cfg_draft: ModelConfig,
    prompt_tokens: jax.Array,  # (P,) or (1, P) int32
    max_new_tokens: int,
    key: jax.Array,
    *,
    k: int = 4,
    temperature: float = 0.0,
) -> Tuple[jax.Array, dict]:
    """Returns ((max_new_tokens,) sampled ids, stats dict).

    stats: {"rounds": int, "proposed": int, "accepted": int} — acceptance
    telemetry for tuning k (accepted/proposed is the draft's hit rate).
    """
    cfg_t = _sanitize(cfg_target)
    cfg_d = _sanitize(cfg_draft)
    if cfg_t.vocab_size != cfg_d.vocab_size:
        raise ValueError(
            f"draft vocab ({cfg_d.vocab_size}) must equal target vocab "
            f"({cfg_t.vocab_size})"
        )
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    prompt = jnp.atleast_2d(jnp.asarray(prompt_tokens, jnp.int32))
    if prompt.shape[0] != 1:
        raise ValueError(
            "speculative decoding is the batch-1 latency path; use "
            "generation.generate for batched throughput decoding"
        )
    p_len = int(prompt.shape[1])
    total = p_len + max_new_tokens + k + 1  # slack: a round may overshoot
    for cfg, name in ((cfg_t, "target"), (cfg_d, "draft")):
        if total > cfg.context_length:
            raise ValueError(
                f"prompt({p_len}) + max_new({max_new_tokens}) + k({k}) "
                f"exceeds the {name} context ({cfg.context_length})"
            )

    out, stats = _spec_jit(
        params_target, params_draft, prompt, key, cfg_t=cfg_t, cfg_d=cfg_d,
        total=total, max_new_tokens=max_new_tokens, k=k,
        temperature=temperature,
    )
    return out, {name: int(val) for name, val in stats.items()}
