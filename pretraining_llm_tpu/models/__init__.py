from pretraining_llm_tpu.models.transformer import (  # noqa: F401
    forward,
    init_params,
    loss_fn,
)
