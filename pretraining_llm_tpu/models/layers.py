"""Layer primitives: norms, activations, RoPE — pure functions on pytrees.

Capability superset of the reference's `src/models/{mlp,attention}.py` layer
zoo, redesigned functional: no module state, explicit params, fp32 norm math
with bf16 matmul inputs (TPU MXU native), and pluggable position encodings.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


# ---------------------------------------------------------------------------
# Normalization — computed in fp32, output cast back to the input dtype.
# ---------------------------------------------------------------------------


def layernorm(p: Params, x: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def rmsnorm(p: Params, x: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    y = y * p["scale"].astype(jnp.float32)
    return y.astype(x.dtype)


def apply_norm(kind: str, p: Params, x: jax.Array, eps: float) -> jax.Array:
    return layernorm(p, x, eps) if kind == "layernorm" else rmsnorm(p, x, eps)


def init_norm(kind: str, d: int, dtype: jnp.dtype) -> Params:
    p = {"scale": jnp.ones((d,), dtype)}
    if kind == "layernorm":
        p["bias"] = jnp.zeros((d,), dtype)
    return p


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------


def activation_fn(kind: str, x: jax.Array) -> jax.Array:
    if kind == "relu":
        return jax.nn.relu(x)
    if kind == "gelu":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"activation_fn does not handle {kind!r} (swiglu is fused in mlp)")


# ---------------------------------------------------------------------------
# Rotary position embeddings
# ---------------------------------------------------------------------------


def rope_table(context_length: int, head_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """(cos, sin) tables of shape (T, head_dim // 2), fp32."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = jnp.arange(context_length, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array,
    seq_axis: int = 1,
) -> jax.Array:
    """Rotate (B, T, H, Dh) — or (B, H, T, Dh) with ``seq_axis=2`` — by
    position.

    positions: (T,) int32 into the table — shared across the batch — or
    (B, T) for per-row positions (ragged left-padded decode, where row i's
    token at slot s has logical position s - pad_offset_i).

    ``seq_axis=2`` serves the HEADS-MAJOR training layout the flash path
    uses (q/k/v produced (B, H, T, Dh) straight from the projection
    einsum so the Pallas kernel's fold is a free reshape — no transpose
    copies in the step; see models.transformer._attention_block).
    """
    if seq_axis not in (1, 2):
        raise ValueError(f"seq_axis must be 1 or 2, got {seq_axis}")
    if positions.ndim == 2:
        cos_t = cos[positions]  # (B, T, Dh/2)
        sin_t = sin[positions]
        expand = (slice(None), slice(None), None) if seq_axis == 1 else (
            slice(None), None, slice(None))
        cos_t, sin_t = cos_t[expand], sin_t[expand]  # head dim broadcast
    else:
        cos_t = cos[positions]  # (T, Dh/2)
        sin_t = sin[positions]
        expand = (None, slice(None), None) if seq_axis == 1 else (
            None, None, slice(None))
        cos_t, sin_t = cos_t[expand], sin_t[expand]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos_t - x2 * sin_t, x2 * cos_t + x1 * sin_t], axis=-1)
    return rotated.astype(x.dtype)
