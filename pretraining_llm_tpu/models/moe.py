"""Mixture-of-experts MLP with expert parallelism over the 'expert' mesh axis.

Beyond-parity component: the reference has only a dense MLP
(`/root/reference/src/models/mlp.py:24-26`); SURVEY §2.2 lists EP as the one
parallelism strategy left open. This is the TPU-native design:

  - **Dense einsum dispatch** (Switch/Mixtral-style token choice with a static
    per-expert capacity): routing is expressed as two big einsums against
    one-hot dispatch/combine tensors, so every shape is static, everything
    lands on the MXU, and under `pjit` the dispatch contraction over the token
    dim *is* the all-to-all — XLA inserts the collective from the shardings
    (tokens sharded over 'data', experts over 'expert'), no hand-written
    routing tables or ragged buffers.
  - Top-k gating with renormalized weights, slot-major capacity priority
    (every token's 1st choice is placed before any token's 2nd choice),
    dropped tokens fall back to the residual stream (their MoE output is 0).
  - Switch-style load-balance auxiliary loss in fp32, threaded through the
    block scan and added to the task loss as `router_aux_coef * aux`.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


def init_moe_params(
    cfg: ModelConfig, key: jax.Array, resid_std: float, dtype: jnp.dtype
) -> Params:
    """Per-block MoE params: router (D, E) + stacked expert FFNs (E, ...)."""
    d, f, e = cfg.d_model, cfg.d_ff, cfg.n_experts
    k_router, k_w1, k_w2 = jax.random.split(key, 3)

    def normal(k: jax.Array, shape: Tuple[int, ...], s: float = 0.02) -> jax.Array:
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dtype)

    if cfg.activation == "swiglu":
        experts: Params = {
            "w1": normal(k_w1, (e, d, 2, f)),
            "w2": normal(k_w2, (e, f, d), resid_std),
        }
        if cfg.mlp_bias:
            experts["b1"] = jnp.zeros((e, 2, f), dtype)
            experts["b2"] = jnp.zeros((e, d), dtype)
    else:
        experts = {
            "w1": normal(k_w1, (e, d, f)),
            "w2": normal(k_w2, (e, f, d), resid_std),
        }
        if cfg.mlp_bias:
            experts["b1"] = jnp.zeros((e, f), dtype)
            experts["b2"] = jnp.zeros((e, d), dtype)
    return {"router": normal(k_router, (d, e)), "experts": experts}


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    """Static per-expert slot count for a batch of n_tokens."""
    cap = int(cfg.expert_capacity_factor * cfg.experts_per_token * n_tokens / cfg.n_experts)
    return max(1, min(cap, n_tokens))


def route(
    router_logits: jax.Array, cfg: ModelConfig, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token-choice top-k routing with capacity.

    router_logits: (S, E) fp32. Returns (dispatch (S, E, C) 0/1,
    combine (S, E, C) gate weights, aux scalar load-balance loss).
    """
    s, e = router_logits.shape
    k = cfg.experts_per_token
    probs = jax.nn.softmax(router_logits, axis=-1)  # (S, E) fp32
    gate, idx = jax.lax.top_k(probs, k)  # (S, K)
    gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (S, K, E)

    # Slot-major priority: flatten to (K*S, E) with the choice-rank major so
    # every token's 1st choice outranks any token's 2nd choice, then a cumsum
    # assigns each (token, choice) its position within the expert's capacity.
    slot_major = onehot.transpose(1, 0, 2).reshape(k * s, e)
    pos = jnp.cumsum(slot_major, axis=0) - slot_major  # positions from 0
    pos = jnp.sum(pos * slot_major, axis=-1).reshape(k, s).T  # (S, K)
    keep = (pos < capacity).astype(jnp.float32)  # dropped tokens contribute 0
    pos_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    pos_onehot = pos_onehot * keep[..., None]

    combine = jnp.einsum("sk,ske,skc->sec", gate * keep, onehot, pos_onehot)
    dispatch = jnp.einsum("ske,skc->sec", onehot, pos_onehot)

    # Switch-style balance loss: E * sum_e(assignment fraction * mean prob).
    frac = jnp.mean(jnp.sum(onehot, axis=1), axis=0) / k  # (E,)
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    aux = e * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_mlp(mlp: Params, h: jax.Array, cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN on normed input h (B, T, D) -> (output (B, T, D), aux loss)."""
    cdt = jnp.dtype(cfg.compute_dtype)
    b, t, d = h.shape
    s = b * t
    x = h.reshape(s, d)

    router_logits = jnp.einsum(
        "sd,de->se",
        x.astype(jnp.float32),
        mlp["router"].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    capacity = expert_capacity(cfg, s)
    dispatch, combine, aux = route(router_logits, cfg, capacity)

    # Contracting the (data-sharded) token dim against the dispatch mask IS
    # the all-to-all: XLA lowers it to collectives between the 'data' and
    # 'expert' mesh axes.
    xin = jnp.einsum(
        "sec,sd->ecd", dispatch.astype(cdt), x.astype(cdt), preferred_element_type=jnp.float32
    ).astype(cdt)
    xin = constrain(xin, "expert", None, None)

    ex = mlp["experts"]
    if cfg.activation == "swiglu":
        gates = jnp.einsum(
            "ecd,edgf->ecgf", xin, ex["w1"].astype(cdt), preferred_element_type=jnp.float32
        ).astype(cdt)
        if "b1" in ex:
            gates = gates + ex["b1"].astype(cdt)[:, None, :, :]
        hidden = jax.nn.silu(gates[..., 0, :]) * gates[..., 1, :]
    else:
        hidden = jnp.einsum(
            "ecd,edf->ecf", xin, ex["w1"].astype(cdt), preferred_element_type=jnp.float32
        ).astype(cdt)
        if "b1" in ex:
            hidden = hidden + ex["b1"].astype(cdt)[:, None, :]
        hidden = jax.nn.relu(hidden) if cfg.activation == "relu" else jax.nn.gelu(
            hidden, approximate=True
        )
    out = jnp.einsum(
        "ecf,efd->ecd", hidden, ex["w2"].astype(cdt), preferred_element_type=jnp.float32
    ).astype(cdt)
    if "b2" in ex:
        out = out + ex["b2"].astype(cdt)[:, None, :]
    out = constrain(out, "expert", None, None)

    y = jnp.einsum("sec,ecd->sd", combine.astype(cdt), out, preferred_element_type=jnp.float32)
    return y.astype(h.dtype).reshape(b, t, d), aux
