"""Mixture-of-experts MLP with expert parallelism over the 'expert' mesh axis.

Beyond-parity component: the reference has only a dense MLP
(`/root/reference/src/models/mlp.py:24-26`); SURVEY §2.2 lists EP as the one
parallelism strategy left open. This is the TPU-native design:

  - **Grouped dense einsum dispatch** (Switch/Mixtral-style token choice with
    a static per-expert capacity): routing is expressed as einsums against
    one-hot dispatch/combine tensors, so every shape is static, everything
    lands on the MXU, and under `pjit` the dispatch contraction over the token
    dim *is* the all-to-all — XLA inserts the collective from the shardings
    (tokens sharded over 'data', experts over 'expert'), no hand-written
    routing tables or ragged buffers.
  - Routing is computed per **group** of `cfg.moe_group_size` tokens
    (flaxformer-style), with capacity proportional to the group size, so the
    dispatch/combine tensors are O(S * k * C_group) — linear in the batch —
    instead of the O(S^2) a single global capacity pool costs. Group count
    depends only on the token count (never the mesh), so routing decisions
    are identical across mesh shapes (sharding-invariance holds); the group
    dim stays sharded over the data axes while experts shard over 'expert'.
  - Top-k gating with renormalized weights, slot-major capacity priority
    (every token's 1st choice is placed before any token's 2nd choice),
    dropped tokens fall back to the residual stream (their MoE output is 0).
  - Switch-style load-balance auxiliary loss in fp32, threaded through the
    block scan and added to the task loss as `router_aux_coef * aux`.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


def init_moe_params(
    cfg: ModelConfig, key: jax.Array, resid_std: float, dtype: jnp.dtype
) -> Params:
    """Per-block MoE params: router (D, E) + stacked expert FFNs (E, ...)."""
    d, f, e = cfg.d_model, cfg.d_ff, cfg.n_experts
    k_router, k_w1, k_w2 = jax.random.split(key, 3)

    def normal(k: jax.Array, shape: Tuple[int, ...], s: float = 0.02) -> jax.Array:
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dtype)

    if cfg.activation == "swiglu":
        experts: Params = {
            "w1": normal(k_w1, (e, d, 2, f)),
            "w2": normal(k_w2, (e, f, d), resid_std),
        }
        if cfg.mlp_bias:
            experts["b1"] = jnp.zeros((e, 2, f), dtype)
            experts["b2"] = jnp.zeros((e, d), dtype)
    else:
        experts = {
            "w1": normal(k_w1, (e, d, f)),
            "w2": normal(k_w2, (e, f, d), resid_std),
        }
        if cfg.mlp_bias:
            experts["b1"] = jnp.zeros((e, f), dtype)
            experts["b2"] = jnp.zeros((e, d), dtype)
    return {"router": normal(k_router, (d, e)), "experts": experts}


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    """Static per-expert slot count for a batch of n_tokens."""
    cap = int(cfg.expert_capacity_factor * cfg.experts_per_token * n_tokens / cfg.n_experts)
    return max(1, min(cap, n_tokens))


def route(
    router_logits: jax.Array, cfg: ModelConfig, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token-choice top-k routing with capacity.

    router_logits: (S, E) fp32. Returns (dispatch (S, E, C) 0/1,
    combine (S, E, C) gate weights, aux scalar load-balance loss).
    """
    s, e = router_logits.shape
    k = cfg.experts_per_token
    probs = jax.nn.softmax(router_logits, axis=-1)  # (S, E) fp32
    gate, idx = jax.lax.top_k(probs, k)  # (S, K)
    gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (S, K, E)

    # Slot-major priority: flatten to (K*S, E) with the choice-rank major so
    # every token's 1st choice outranks any token's 2nd choice, then a cumsum
    # assigns each (token, choice) its position within the expert's capacity.
    slot_major = onehot.transpose(1, 0, 2).reshape(k * s, e)
    pos = jnp.cumsum(slot_major, axis=0) - slot_major  # positions from 0
    pos = jnp.sum(pos * slot_major, axis=-1).reshape(k, s).T  # (S, K)
    keep = (pos < capacity).astype(jnp.float32)  # dropped tokens contribute 0
    pos_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    pos_onehot = pos_onehot * keep[..., None]

    combine = jnp.einsum("sk,ske,skc->sec", gate * keep, onehot, pos_onehot)
    dispatch = jnp.einsum("ske,skc->sec", onehot, pos_onehot)

    # Switch-style balance loss: E * sum_e(assignment fraction * mean prob).
    frac = jnp.mean(jnp.sum(onehot, axis=1), axis=0) / k  # (E,)
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    aux = e * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def _group_count(s: int, group_size: int) -> int:
    """Number of routing groups: S/group_size rounded to a divisor of S.

    Depends only on the token count (never the mesh) so routing is identical
    across mesh shapes.
    """
    if group_size <= 0 or s <= group_size:
        return 1
    g = s // group_size
    while s % g != 0:  # token counts are powers of two in practice; be safe
        g -= 1
    return g


def moe_mlp(
    mlp: Params, h: jax.Array, cfg: ModelConfig, *, decode: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN on normed input h (B, T, D) -> (output (B, T, D), aux loss).

    ``decode=True`` (KV-cached generation) routes without a capacity bound:
    per-step token counts are tiny and a capacity drop there would make a
    token's output depend on which other sequences are co-batched.
    """
    cdt = jnp.dtype(cfg.compute_dtype)
    b, t, d = h.shape
    s = b * t
    g = 1 if decode else _group_count(s, cfg.moe_group_size)
    sg = s // g
    x = h.reshape(g, sg, d)

    router_logits = jnp.einsum(
        "gsd,de->gse",
        x.astype(jnp.float32),
        mlp["router"].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    capacity = sg if decode else expert_capacity(cfg, sg)
    dispatch, combine, aux = jax.vmap(
        lambda lg: route(lg, cfg, capacity)
    )(router_logits)
    aux = jnp.mean(aux)

    # Contracting the (data-sharded) token dim against the dispatch mask IS
    # the all-to-all: XLA lowers it to collectives between the 'data' and
    # 'expert' mesh axes. The group dim rides the data axes.
    # Accumulation precision is a non-issue here (each (e, c) slot gathers
    # exactly one token), but the grouped form makes these genuinely batched
    # dots and the CPU backend has no batched-bf16 DotThunk — route them
    # through fp32 there. TPU keeps bf16 (MXU accumulates fp32 natively).
    ddt = jnp.float32 if jax.default_backend() == "cpu" else cdt
    xin = jnp.einsum("gsec,gsd->gecd", dispatch.astype(ddt), x.astype(ddt)).astype(cdt)
    # Fold (g, c) into one per-expert row dim: each expert runs ONE
    # (G*C, D) @ (D, F) matmul — bigger MXU tiles than G separate dots, and
    # the same non-batched lowering the CPU backend supports in bf16.
    gc = g * capacity
    xin = xin.transpose(1, 0, 2, 3).reshape(cfg.n_experts, gc, d)
    xin = constrain(xin, "expert", None, None)

    ex = mlp["experts"]
    if cfg.activation == "swiglu":
        gates = jnp.einsum(
            "ecd,edgf->ecgf", xin, ex["w1"].astype(cdt), preferred_element_type=jnp.float32
        ).astype(cdt)
        if "b1" in ex:
            gates = gates + ex["b1"].astype(cdt)[:, None, :, :]
        hidden = jax.nn.silu(gates[..., 0, :]) * gates[..., 1, :]
    else:
        hidden = jnp.einsum(
            "ecd,edf->ecf", xin, ex["w1"].astype(cdt), preferred_element_type=jnp.float32
        ).astype(cdt)
        if "b1" in ex:
            hidden = hidden + ex["b1"].astype(cdt)[:, None, :]
        hidden = jax.nn.relu(hidden) if cfg.activation == "relu" else jax.nn.gelu(
            hidden, approximate=True
        )
    # 'save_big' saves the expert hidden too (mirrors the dense MLP tag) —
    # without it the whole dispatch + expert FFN would recompute in backward.
    hidden = checkpoint_name(hidden, "mlp_hidden")
    out = jnp.einsum(
        "ecf,efd->ecd", hidden, ex["w2"].astype(cdt), preferred_element_type=jnp.float32
    ).astype(cdt)
    if "b2" in ex:
        out = out + ex["b2"].astype(cdt)[:, None, :]
    out = constrain(out, "expert", None, None)
    out = out.reshape(cfg.n_experts, g, capacity, d).transpose(1, 0, 2, 3)

    # Combine sums exactly experts_per_token (~2) terms per token: bf16
    # accumulation is exact enough; same CPU batched-dot dtype caveat as xin.
    y = jnp.einsum("gsec,gecd->gsd", combine.astype(ddt), out.astype(ddt))
    return y.astype(h.dtype).reshape(b, t, d), aux
