"""Post-load int8 weight quantization for serving.

Decode is HBM-bandwidth-bound: every decode window re-reads the whole
weight set, so bytes-per-weight — not FLOPs — is the lever. This module
implements the serving-prep pass behind ``serving.quantize``:

  - **per-channel symmetric int8** over the matmul projections of every
    transformer block (attention qkv/q/kv/o and FFN w1/w2), reducing over
    the *contracted* (input) axes of each einsum so every output channel
    keeps its own fp32 scale,
  - each quantized weight is replaced in-place by its int8 tensor plus a
    sibling ``{name}_scale`` fp32 leaf in the same subtree — the scale
    keeps the leading ``(n_layers,)`` dim, so the pair rides the existing
    depth ``lax.scan`` over ``params['blocks']`` unchanged, and
    ``generate.shard_params_for_inference`` shards both through the same
    name-keyed partition rules (scales are per-output-channel, so they
    follow their weight's output-axis sharding),
  - embeddings, lm_head, norms, biases and MoE experts stay in their
    original dtype: embeddings/lm_head dominate quality per bit at small
    vocab-heavy models, norm/bias math is deliberately fp32/bf16 in the
    forward, and expert matmuls route through capacity-gathered einsums
    this pass does not cover.

Dequantization happens at the use site (``transformer._weight``):
``w_int8.astype(f32) * scale`` then cast to the compute dtype, so the
matmul itself accumulates exactly like the bf16 path — the quantized
forward is a pure function of the int8 bytes + scales, which is what the
integrity sentinel's quantized-graph probe pinning relies on.

Symmetric scheme (no zero-points): ``scale = max(|w|, eps) / 127`` over
the reduce axes, ``q = clip(round(w / scale), -127, 127)``. 127 (not
128) keeps the code symmetric so ``-q`` is always representable.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

# Contracted (input) axes per quantized projection, for STACKED block
# leaves (leading n_layers axis at 0). Reducing over the contracted axes
# gives one scale per output channel — the per-channel symmetric scheme:
#   wqkv (L, d, 3, h, dh) -> scale (L, 1, 3, h, dh)
#   wq   (L, d, h, dh)    -> scale (L, 1, h, dh)
#   wkv  (L, d, 2, g, dh) -> scale (L, 1, 2, g, dh)
#   wo   (L, h, dh, d)    -> scale (L, 1, 1, d)
#   w1   (L, d, [2,] f)   -> scale (L, 1, [2,] f)
#   w2   (L, f, d)        -> scale (L, 1, d)
_REDUCE_AXES: Dict[str, Tuple[int, ...]] = {
    "wqkv": (1,),
    "wq": (1,),
    "wkv": (1,),
    "wo": (1, 2),
    "w1": (1,),
    "w2": (1,),
}

_EPS = 1e-8


def quantize_weight(
    w: jax.Array, axes: Tuple[int, ...]
) -> Tuple[jax.Array, jax.Array]:
    """(int8 codes, fp32 scale) for symmetric per-channel quantization of
    ``w`` reducing over ``axes``. Scale keeps singleton reduce dims so
    ``q.astype(f32) * scale`` broadcasts back to ``w``'s shape."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axes, keepdims=True)
    # eps floor: an all-zero channel quantizes to zeros with a tiny scale
    # instead of dividing by zero.
    scale = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_weight(q: jax.Array, scale: jax.Array, dtype: Any) -> jax.Array:
    """Inverse of `quantize_weight` (up to rounding): fp32 multiply, then
    one cast to the compute dtype — the same numerics transformer._weight
    applies at every use site."""
    return (q.astype(jnp.float32) * scale.astype(jnp.float32)).astype(dtype)


def quantize_params_for_serving(params: Any, cfg: Any) -> Any:
    """Serving-prep pass: per-channel int8 over the block projections.

    Call AFTER `generate.cast_params_for_inference` (the pass reads any
    float dtype) and BEFORE `generate.shard_params_for_inference` — the
    int8 leaves and their ``{name}_scale`` siblings flow through the
    name-keyed partition rules like any other block leaf.

    Returns a new tree; only ``params['blocks']['attn'|'mlp']`` changes.
    MoE models are rejected loudly (expert einsums are not covered).
    """
    if getattr(cfg, "n_experts", 0):
        raise ValueError(
            "int8 weight quantization does not cover MoE expert matmuls"
        )
    params = dict(params)
    blocks = dict(params["blocks"])
    for sub_name in ("attn", "mlp"):
        sub = dict(blocks[sub_name])
        for name, axes in _REDUCE_AXES.items():
            w = sub.get(name)
            if w is None or not jnp.issubdtype(w.dtype, jnp.floating):
                continue
            q, scale = quantize_weight(w, axes)
            sub[name] = q
            sub[name + "_scale"] = scale
        blocks[sub_name] = sub
    params["blocks"] = blocks
    return params


def is_quantized(params: Any) -> bool:
    """True if `quantize_params_for_serving` has run on this tree."""
    try:
        attn = params["blocks"]["attn"]
    except (KeyError, TypeError):
        return False
    return any(k.endswith("_scale") for k in attn)


def param_bytes(params: Any) -> int:
    """Total bytes across all leaves — the model-bytes estimate bench.py
    reports so HBM-bandwidth wins are attributable."""
    return int(
        sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(params)
        )
    )
