"""Decoder-only transformer: pure-functional init/forward over a param pytree.

Capability parity with `/root/reference/src/models/transformer.py` (forward with
optional targets -> (logits, loss); SURVEY §2.5 architecture spec) — redesigned
TPU-first instead of translated:

  - Blocks are *stacked* (leading n_layers dim on every block param) and the
    depth loop is a `jax.lax.scan`, so XLA traces/compiles one block regardless
    of depth (the reference Python-loops 64 modules: transformer.py:68-69).
  - One fused QKV projection per block feeding all heads at once (the
    reference runs 16 separate per-head Linears in a Python loop:
    attention.py:95) — the MXU wants one big matmul.
  - Causal masking is index arithmetic inside the attention op, not the
    reference's ~1 GB of per-head registered tril buffers (attention.py:33).
  - fp32 master params, bf16 compute, fp32 softmax/logits/loss: TPU-native
    mixed precision with no GradScaler (the reference's scaler is vestigial
    for bf16, SURVEY §A B8).
  - `reference_parity` shape (no output projection, untied biased lm_head,
    ReLU, learned positions) is reachable via ModelConfig flags — see the
    `reference-3b` preset.

The same forward serves training (kv_cache=None) and KV-cached decode
(kv_cache + cache_index given): caches are stacked per layer and scanned with
the blocks.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from pretraining_llm_tpu.utils import jax_compat

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.models import layers, moe
from pretraining_llm_tpu.ops import remat
from pretraining_llm_tpu.ops.attention import multihead_attention
from pretraining_llm_tpu.parallel.sharding import constrain, current_mesh

Params = Dict[str, Any]
KVCache = Dict[str, jax.Array]  # {'k','v'}: (L, B, Tmax, kv_heads, Dh)


class PagedInfo(NamedTuple):
    """Batch-level paged-decode state, shared by every layer.

    The per-layer block POOLS ride the kv_cache scan carry exactly like the
    contiguous cache (see make_paged_kv_pool); the int32 routing state here
    is what the serving engine mutates host-side between steps — admission,
    growth, and eviction never change a device-array shape, so the decode
    program compiles once and serves forever (vLLM's PagedAttention idea
    re-expressed for XLA's static-shape model: block tables are gather/
    scatter indices, not pointers).

    INVARIANT (caller-enforced, unchecked under jit): every row's
    seq_lens < max_blocks * block_size — a decode step WRITES slot
    seq_lens, so at capacity the page index would clamp onto the row's
    last table entry and silently overwrite a live block. Schedulers must
    bound-check host-side before dispatch (ServingEngine does; drive
    `generation.paged.check_paged_bounds` if you build tables yourself).
    """

    block_tables: jax.Array  # (B, max_blocks) int32 — pool block ids per row
    seq_lens: jax.Array  # (B,) int32 — tokens already in the cache per row
    # Ragged multi-token calls (chunked prefill): row b's TRUE query count
    # (<= T); queries past it are padding whose outputs the caller
    # discards. None = uniform (every row carries all T queries — decode
    # steps and the speculative verify). Only the kernel attention path
    # reads it (per-row DMA elision + pad-query masking); the gather path
    # computes pad queries and lets the caller discard them, so outputs
    # for REAL queries are bit-identical whether or not q_lens is passed.
    q_lens: Optional[jax.Array] = None  # (B,) int32 or None


def _lm_head_weights(params: Params, cfg: ModelConfig):
    """(w_out (D, V), bias (V,)|None) — single source of truth for the output
    head, shared by forward (sampling logits) and loss_fn (chunked CE)."""
    if cfg.tie_embeddings:
        return params["tok_embed"]["embedding"].T, None
    head = params["lm_head"]
    return head["kernel"], head.get("bias")


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Initialize the parameter pytree (fp32 masters by default).

    GPT-2 style init: N(0, 0.02) everywhere, residual-output projections
    (wo, w2) scaled by 1/sqrt(2*n_layers), zeros for biases.
    """
    dtype = jnp.dtype(cfg.param_dtype)
    d, h, dh, f, v, t, nl = (
        cfg.d_model,
        cfg.n_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.vocab_size,
        cfg.context_length,
        cfg.n_layers,
    )
    std = 0.02
    resid_std = std / (2 * nl) ** 0.5
    k_tok, k_pos, k_head, k_blocks = jax.random.split(key, 4)

    def normal(k: jax.Array, shape: Tuple[int, ...], s: float = std) -> jax.Array:
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dtype)

    g = cfg.kv_heads

    def init_block(k: jax.Array) -> Params:
        ks = jax.random.split(k, 5)
        if g == h:
            attn: Params = {"wqkv": normal(ks[0], (d, 3, h, dh))}
            if cfg.qkv_bias:
                attn["bqkv"] = jnp.zeros((3, h, dh), dtype)
        else:
            # GQA: separate q and (smaller) fused kv projections.
            attn = {
                "wq": normal(ks[0], (d, h, dh)),
                "wkv": normal(ks[4], (d, 2, g, dh)),
            }
            if cfg.qkv_bias:
                attn["bq"] = jnp.zeros((h, dh), dtype)
                attn["bkv"] = jnp.zeros((2, g, dh), dtype)
        if cfg.use_output_proj:
            attn["wo"] = normal(ks[1], (h, dh, d), resid_std)
            attn["bo"] = jnp.zeros((d,), dtype)
        if cfg.n_experts:
            mlp: Params = moe.init_moe_params(cfg, ks[2], resid_std, dtype)
        elif cfg.activation == "swiglu":
            mlp: Params = {"w1": normal(ks[2], (d, 2, f)), "w2": normal(ks[3], (f, d), resid_std)}
            if cfg.mlp_bias:
                mlp["b1"] = jnp.zeros((2, f), dtype)
                mlp["b2"] = jnp.zeros((d,), dtype)
        else:
            mlp = {"w1": normal(ks[2], (d, f)), "w2": normal(ks[3], (f, d), resid_std)}
            if cfg.mlp_bias:
                mlp["b1"] = jnp.zeros((f,), dtype)
                mlp["b2"] = jnp.zeros((d,), dtype)
        return {
            "ln1": layers.init_norm(cfg.norm, d, dtype),
            "attn": attn,
            "ln2": layers.init_norm(cfg.norm, d, dtype),
            "mlp": mlp,
        }

    # vmap over per-layer keys -> every block param gets a leading (n_layers,) dim
    blocks = jax.vmap(init_block)(jax.random.split(k_blocks, nl))

    params: Params = {
        "tok_embed": {"embedding": normal(k_tok, (v, d))},
        "blocks": blocks,
        "final_norm": layers.init_norm(cfg.norm, d, dtype),
    }
    if cfg.pos_embed == "learned":
        params["pos_embed"] = {"embedding": normal(k_pos, (t, d))}
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": normal(k_head, (d, v))}
        if cfg.lm_head_bias:
            params["lm_head"]["bias"] = jnp.zeros((v,), dtype)
    return params


# ---------------------------------------------------------------------------
# Block forward
# ---------------------------------------------------------------------------


def _weight(sub: Params, name: str, cdt: Any) -> jax.Array:
    """Matmul weight read in compute dtype — the single dequant point for
    int8 serving params (models/quantize.py). A quantized projection is an
    int8 leaf plus a sibling ``{name}_scale`` fp32 leaf (per-output-channel
    symmetric); dequant is one fp32 multiply, then the SAME compute-dtype
    cast the bf16 path takes, so the matmul accumulates identically."""
    w = sub[name]
    if w.dtype == jnp.int8:
        return (w.astype(jnp.float32) * sub[name + "_scale"]).astype(cdt)
    return w.astype(cdt)


def _attention_block(
    blk: Params,
    x: jax.Array,
    cfg: ModelConfig,
    rope: Optional[Tuple[jax.Array, jax.Array]],
    positions: jax.Array,
    kv: Optional[Params],
    cache_index: Optional[jax.Array],
    zigzag: bool = False,
    pad_offsets: Optional[jax.Array] = None,
    segments: Optional[jax.Array] = None,
    paged: Optional[PagedInfo] = None,
) -> Tuple[jax.Array, Optional[Params]]:
    """Pre-LN attention sub-block: x + attn(ln1(x)). Returns (x, new_kv).

    ``pad_offsets`` (B,) enables RAGGED cached decode: row i is left-padded
    by pad_offsets[i] slots, so its token at cache slot s has logical
    position s - pad_offsets[i]. Slot indices drive causality (equivalent
    to logical causality under a shared left-pad layout), RoPE uses the
    per-row logical positions, and the kv mask excludes each row's dead
    pad slots.
    """
    cdt = jnp.dtype(cfg.compute_dtype)
    h = layers.apply_norm(cfg.norm, blk["ln1"], x, cfg.norm_eps)
    # HEADS-MAJOR training layout for the flash kernel (opt-in probe knob,
    # measured ~1% slower on v5e despite removing the per-call relayout
    # copies — see ModelConfig.flash_heads_major for the numbers): q/k/v
    # produced (B, H, T, Dh) straight from the projection einsum, kernel
    # fold becomes a free reshape. Cached decode and the other impls keep
    # the (B, T, H, Dh) convention.
    hm = (
        kv is None
        and cfg.attention_impl == "flash"
        and cfg.flash_heads_major
    )
    if "wqkv" in blk["attn"]:
        qkv = jnp.einsum(
            "btd,dchn->bchtn" if hm else "btd,dchn->bcthn",
            h.astype(cdt), _weight(blk["attn"], "wqkv", cdt),
            preferred_element_type=jnp.float32,
        ).astype(cdt)
        if "bqkv" in blk["attn"]:
            bqkv = blk["attn"]["bqkv"].astype(cdt)  # (3, H, Dh)
            qkv = qkv + (
                bqkv[None, :, :, None, :] if hm else bqkv[None, :, None, :, :]
            )
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # hm: (B, H, T, Dh)
    else:
        # GQA: H query heads, kv_heads <= H key/value heads.
        q = jnp.einsum(
            "btd,dhn->bhtn" if hm else "btd,dhn->bthn",
            h.astype(cdt), _weight(blk["attn"], "wq", cdt),
            preferred_element_type=jnp.float32,
        ).astype(cdt)
        kvp = jnp.einsum(
            "btd,dcgn->bcgtn" if hm else "btd,dcgn->bctgn",
            h.astype(cdt), _weight(blk["attn"], "wkv", cdt),
            preferred_element_type=jnp.float32,
        ).astype(cdt)
        if "bq" in blk["attn"]:
            bq = blk["attn"]["bq"].astype(cdt)  # (H, Dh)
            bkv = blk["attn"]["bkv"].astype(cdt)  # (2, G, Dh)
            q = q + (bq[None, :, None, :] if hm else bq[None, None])
            kvp = kvp + (
                bkv[None, :, :, None, :] if hm else bkv[None, :, None]
            )
        k, v = kvp[:, 0], kvp[:, 1]  # hm: (B, G, T, Dh)

    if rope is not None:
        cos, sin = rope
        if paged is not None:
            # Paged decode: row i's j-th query token sits at logical
            # position seq_lens[i] + j (linear index within its own block
            # list; j > 0 only in the speculative verify).
            rope_pos = paged.seq_lens[:, None] + jnp.arange(
                k.shape[1], dtype=paged.seq_lens.dtype
            )[None, :]
        elif pad_offsets is not None:
            # Per-row logical positions: slot - left-pad offset. Pad slots
            # clip to 0; their K/V is masked out of every real attention.
            rope_pos = jnp.clip(positions[None, :] - pad_offsets[:, None], 0)
        else:
            rope_pos = positions
        q = layers.apply_rope(q, cos, sin, rope_pos, seq_axis=2 if hm else 1)
        k = layers.apply_rope(k, cos, sin, rope_pos, seq_axis=2 if hm else 1)

    # Remat tags for the 'save_qkv_attn'/'save_big' policies: with post-RoPE
    # q/k/v saved, the attention backward starts directly from its VJP inputs
    # instead of recomputing LN1 + the QKV projection (+RoPE).
    q = checkpoint_name(q, "qkv")
    k = checkpoint_name(k, "qkv")
    v = checkpoint_name(v, "qkv")

    # GQA: every attention path attends H query heads against G KV heads
    # directly when the layout allows it (no K/V expansion — the cache/HBM
    # bandwidth win; ring/ulysses additionally move G/H the KV bytes through
    # their collectives). Ring needs whole groups per tensor shard, ulysses
    # needs the KV heads to split over tensor x seq shards (see the
    # *_supports_grouped predicates); KV is repeated up front otherwise
    # (training-time only).
    n_rep = cfg.n_heads // cfg.kv_heads

    def rep(a: jax.Array) -> jax.Array:
        return jnp.repeat(a, n_rep, axis=2) if n_rep > 1 else a

    new_kv: Optional[Params] = None
    if kv is not None and "k_pool" in kv:
        # PAGED decode (serving): the cache is a POOL of fixed-size blocks
        # (n_blocks, block_size, G, Dh); each batch row owns an ordered list
        # of pool block ids (paged.block_tables) and a logical length
        # (paged.seq_lens). One step = scatter this token's K/V into the
        # row's slot seq_len, then attend over the row's gathered blocks
        # masked to <= seq_len. All shapes are static — the serving engine
        # admits/evicts requests by editing int32 tables host-side, never
        # recompiling. (The reference has no serving path at all; its
        # generate is batch-1 fixed-count, transformer.py:96-114.)
        if paged is None:
            raise ValueError(
                "a paged kv pool requires forward(..., paged=PagedInfo)"
            )
        bsz = q.shape[0]
        tq = k.shape[1]
        block_size = kv["k_pool"].shape[1]
        tables, seq = paged.block_tables, paged.seq_lens
        # Token i of this call writes logical slot seq + i. tq == 1 is the
        # serving decode step; tq > 1 is the speculative-decoding paged
        # VERIFY (k+1 draft tokens through the target in one program —
        # prompts still enter via generation.paged.prefill_into_pool).
        # Multi-step scheduling overshoot guard: inside a fixed-length
        # decode window a row can pass its capacity (it gets reaped right
        # after); redirect such writes to the reserved scratch block
        # instead of letting the page index clamp onto the row's LAST
        # block and corrupt a live slot. Single-step schedulers never hit
        # this (check_paged_bounds), multi-step ones hit it by design.
        capacity = tables.shape[1] * block_size
        pos = seq[:, None] + jnp.arange(tq, dtype=seq.dtype)[None, :]  # (B,T)
        in_range = pos < capacity
        pos_c = jnp.minimum(pos, capacity - 1)
        blk_ids = jnp.where(
            in_range, tables[jnp.arange(bsz)[:, None], pos_c // block_size], 0
        )  # (B, T)
        slots = jnp.where(in_range, pos_c % block_size, 0)  # (B, T)
        quantized = "k_scale_pool" in kv

        def scatter(pool, val):
            # One (B, T)-indexed scatter per pool: rows own disjoint
            # blocks and a row's T slots are distinct, so indices collide
            # only on the reserved scratch block (idle rows, overshoot
            # redirects) — whose content is never unmasked.
            return pool.at[blk_ids, slots].set(val.astype(pool.dtype))

        if quantized:
            k_q, k_sc = _kv_quantize(k)
            v_q, v_sc = _kv_quantize(v)
            new_kv = {
                "k_pool": scatter(kv["k_pool"], k_q),
                "v_pool": scatter(kv["v_pool"], v_q),
                "k_scale_pool": scatter(kv["k_scale_pool"], k_sc),
                "v_scale_pool": scatter(kv["v_scale_pool"], v_sc),
            }
        else:
            new_kv = {
                "k_pool": scatter(kv["k_pool"], k),
                "v_pool": scatter(kv["v_pool"], v),
            }

        if cfg.paged_attention_impl == "kernel" and quantized:
            # int8 pools through the kernel path: the ragged kernel fuses
            # the per-(slot, head) dequant into its page loop — int8 bytes
            # + scale pages are what crosses HBM, never a dequantized
            # (B, kv_len) copy. EVERY query shape routes the ragged form
            # (decode steps pass q_lens=1 per row, uniform multi-token
            # verifies pass q_lens=tq): one kernel owns quantized decode,
            # chunked prefill AND the speculative verify, so the quantized
            # graph has a single attention numerics path.
            from pretraining_llm_tpu.ops.pallas_ragged import (
                ragged_paged_attention,
            )

            if tq > 1 and paged.q_lens is not None:
                q_lens = paged.q_lens
            else:
                q_lens = jnp.full((bsz,), tq, dtype=seq.dtype)
            out = ragged_paged_attention(
                q.astype(cdt),
                new_kv["k_pool"],
                new_kv["v_pool"],
                tables, seq, q_lens,
                window=cfg.sliding_window,
                k_scale=new_kv["k_scale_pool"],
                v_scale=new_kv["v_scale_pool"],
                kv_splits=cfg.ragged_kv_splits or None,
                amla=cfg.ragged_amla,
            )
        elif cfg.paged_attention_impl == "kernel":
            # Gather-free: the Pallas kernel DMAs each row's pages straight
            # off the pool via the block table (ops/pallas_paged.py) — the
            # row's KV bytes are read once, no (B, kv_len) copy is ever
            # materialized. tq > 1 routes the multi-token form (the
            # speculative verify's per-query frontiers live inside the
            # kernel mask).
            if tq > 1 and paged.q_lens is not None:
                # Ragged multi-token form (chunked prefill): rows carry
                # heterogeneous true query counts; the ragged kernel
                # elides DMA past each row's OWN chunk end instead of
                # scanning every row to the longest row's frontier.
                from pretraining_llm_tpu.ops.pallas_ragged import (
                    ragged_paged_attention,
                )

                out = ragged_paged_attention(
                    q.astype(cdt),
                    new_kv["k_pool"].astype(cdt),
                    new_kv["v_pool"].astype(cdt),
                    tables, seq, paged.q_lens,
                    window=cfg.sliding_window,
                    kv_splits=cfg.ragged_kv_splits or None,
                    amla=cfg.ragged_amla,
                )
            else:
                from pretraining_llm_tpu.ops.pallas_paged import (
                    paged_decode_attention,
                )

                qin = q[:, 0] if tq == 1 else q
                out = paged_decode_attention(
                    qin.astype(cdt),
                    new_kv["k_pool"].astype(cdt),
                    new_kv["v_pool"].astype(cdt),
                    tables, seq, window=cfg.sliding_window,
                )
                if tq == 1:
                    out = out[:, None]
        else:
            max_blocks = tables.shape[1]
            kv_len = max_blocks * block_size

            def gather(pool):
                # (B, max_blocks, block_size, ...) -> (B, kv_len, ...): each
                # row's logical KV sequence, assembled from its pool blocks.
                return pool[tables].reshape((bsz, kv_len) + pool.shape[2:])

            if quantized:
                ck = _kv_dequantize(
                    gather(new_kv["k_pool"]), gather(new_kv["k_scale_pool"]), cdt
                )
                cv = _kv_dequantize(
                    gather(new_kv["v_pool"]), gather(new_kv["v_scale_pool"]), cdt
                )
            else:
                ck = gather(new_kv["k_pool"]).astype(cdt)
                cv = gather(new_kv["v_pool"]).astype(cdt)
            lin = jnp.arange(kv_len)
            # Causality is the length mask, per query token: token i (at
            # logical slot seq+i) sees slots <= seq+i — its own just-
            # written K/V and everything before it. Unallocated table tail
            # entries point at arbitrary blocks but sit at linear indices
            # beyond the frontier — always masked.
            kv_mask = lin[None, None, :] <= pos[:, :, None]  # (B, T, kv_len)
            if cfg.sliding_window:
                kv_mask = kv_mask & (
                    lin[None, None, :] > pos[:, :, None] - cfg.sliding_window
                )
            out = multihead_attention(
                q, ck, cv, impl="naive", causal=False, kv_mask=kv_mask
            )
    elif kv is not None:
        # Decode: write this step's K/V into the cache at cache_index, attend
        # over the whole (masked) cache. The cache is a per-layer dict
        # {'k','v'} (+ {'k_scale','v_scale'} when kv_cache_dtype='int8').
        tq = k.shape[1]
        quantized = "k_scale" in kv

        def write(buf, val):
            return jax.lax.dynamic_update_slice_in_dim(
                buf, val.astype(buf.dtype), cache_index, axis=1
            )

        if quantized:
            k_q, k_sc = _kv_quantize(k)
            v_q, v_sc = _kv_quantize(v)
            new_kv = {
                "k": write(kv["k"], k_q),
                "v": write(kv["v"], v_q),
                "k_scale": write(kv["k_scale"], k_sc),
                "v_scale": write(kv["v_scale"], v_sc),
            }
        else:
            new_kv = {"k": write(kv["k"], k), "v": write(kv["v"], v)}
        tmax = new_kv["k"].shape[1]
        # The flash-prefill shortcut is only valid when the write offset is
        # PROVABLY zero at trace time (a concrete 0, as the generate prefill
        # passes). A traced or nonzero offset — chunked prefill continuing
        # at index>0 — must attend the cached prefix too, so it keeps the
        # masked-einsum path; the contract is enforced here, not advisory.
        prefill_at_zero = cache_index is None or (
            not isinstance(cache_index, jax.core.Tracer) and int(cache_index) == 0
        )
        if (
            tq > 1
            and prefill_at_zero
            and pad_offsets is None  # ragged rows need the per-row kv mask
            and cfg.attention_impl in ("flash", "ring", "ulysses")
        ):
            # PREFILL (kv_cache set, Tq>1, cache_index==0): attending over
            # the written cache prefix [0, Tq) is exactly causal
            # self-attention over this block's local q/k/v, so it routes
            # through the flash kernel — O(block) memory instead of
            # materialized (Tq, Tmax) masked scores against the whole
            # cache, which re-acquired the O(T^2) wall at 8k prompts
            # (VERDICT r2 next #6). Single-token decode steps keep the
            # masked einsum below (per-step shapes are tiny). Ring/ulysses
            # are training-time layouts; their decode prefill uses flash
            # (the dispatch inside falls back safely under exotic meshes).
            out = multihead_attention(
                q, k, v, impl="flash",
                block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv,
                window=cfg.sliding_window,
            )
        elif (
            tq > 1
            and pad_offsets is None
            and cfg.attention_impl in ("flash", "ring", "ulysses")
        ):
            # CHUNKED prefill (traced or nonzero offset): rectangular
            # blockwise attention of this chunk's queries (positions
            # [cache_index, cache_index+tq)) against the cache —
            # O(block) transient memory instead of materialized
            # (Tq, Tmax) masked scores, GQA-native (grouped cache, never
            # expanded). No explicit length mask needed: slots at/above
            # the write frontier sit at positions > every query position,
            # so causality alone excludes them, and slots below hold the
            # valid prefix written by earlier chunks.
            from pretraining_llm_tpu.ops.flash_attention import blockwise_attention

            kv_view = new_kv
            k_lo = 0
            if not isinstance(cache_index, jax.core.Tracer):
                # Concrete offset (host-side chunk loops): slice off the
                # key blocks that lie entirely beyond the frontier before
                # dequant/attention — they would contribute only masked
                # scores (~2x the needed FLOPs on a mid-cache chunk).
                # Round up to the configured KV tile so the slice never
                # shrinks the block _pick_block would choose. With a
                # sliding window, ALSO slice off the below-window prefix
                # (tile-aligned down) — otherwise chunked windowed prefill
                # pays O(T^2) scanning keys that are entirely masked;
                # k_offset keeps the sliced keys' positions absolute.
                tile = cfg.flash_block_kv or 512
                hi = min(tmax, -(-(int(cache_index) + tq) // tile) * tile)
                if cfg.sliding_window:
                    k_lo = max(
                        0,
                        (int(cache_index) - cfg.sliding_window + 1)
                        // tile * tile,
                    )
                kv_view = {
                    name: buf[:, k_lo:hi] for name, buf in new_kv.items()
                }
            ck, cv = _materialize_cache(kv_view, quantized, cdt)
            out = blockwise_attention(
                q, ck, cv, causal=True,
                block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv,
                q_offset=cache_index, k_offset=k_lo,
                window=cfg.sliding_window,
            )
        else:
            kv_positions = jnp.arange(tmax)
            kv_mask = (kv_positions < cache_index + tq)[None, :]
            if pad_offsets is not None:
                # Ragged rows: slots below each row's left-pad offset are
                # dead (never written with real tokens) — mask them out.
                kv_mask = kv_mask & (kv_positions[None, :] >= pad_offsets[:, None])
            cache_k, cache_v = _materialize_cache(new_kv, quantized, cdt)
            out = multihead_attention(
                q,
                cache_k,
                cache_v,
                impl="naive",
                q_positions=positions,
                kv_positions=kv_positions,
                kv_mask=kv_mask,
                window=cfg.sliding_window,
            )
    else:
        grouped_ok = cfg.attention_impl in ("naive", "flash")
        if cfg.attention_impl == "ring":
            from pretraining_llm_tpu.parallel.ring_attention import ring_supports_grouped

            grouped_ok = ring_supports_grouped(
                current_mesh(), cfg.n_heads, cfg.kv_heads
            )
        elif cfg.attention_impl == "ulysses":
            from pretraining_llm_tpu.parallel.ulysses import ulysses_supports_grouped

            grouped_ok = ulysses_supports_grouped(
                current_mesh(), cfg.n_heads, cfg.kv_heads
            )
        out = multihead_attention(
            q,
            k if grouped_ok else rep(k),
            v if grouped_ok else rep(v),
            impl=cfg.attention_impl,
            block_q=cfg.flash_block_q,
            block_kv=cfg.flash_block_kv,
            ring_layout="zigzag" if zigzag else "contiguous",
            segments=segments,
            window=cfg.sliding_window,
            heads_major=hm,
        )

    # Tag for the 'save_attn' remat policy: keep the (cheap-to-store,
    # expensive-to-recompute) attention output, recompute everything else.
    # (Heads-major path saves (B, H, T, Dh) — consumers below match.)
    out = checkpoint_name(out, "attn_out")

    if cfg.use_output_proj:
        out = jnp.einsum(
            "bhtn,hnd->btd" if hm else "bthn,hnd->btd",
            out, _weight(blk["attn"], "wo", cdt),
            preferred_element_type=jnp.float32,
        ).astype(cdt) + blk["attn"]["bo"].astype(cdt)
    else:
        # Reference shape (attention.py:95): concat heads is the output.
        if hm:
            out = out.transpose(0, 2, 1, 3)
        b, t = out.shape[:2]
        out = out.reshape(b, t, cfg.n_heads * cfg.head_dim)
    return x + out.astype(x.dtype), new_kv


def _mlp_block(
    blk: Params, x: jax.Array, cfg: ModelConfig, decode: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Pre-LN MLP sub-block: x + mlp(ln2(x)). Returns (x, router aux loss)."""
    cdt = jnp.dtype(cfg.compute_dtype)
    h = layers.apply_norm(cfg.norm, blk["ln2"], x, cfg.norm_eps).astype(cdt)
    mlp = blk["mlp"]
    if cfg.n_experts:
        out, aux = moe.moe_mlp(mlp, h, cfg, decode=decode)
        return x + out.astype(x.dtype), aux
    if cfg.activation == "swiglu":
        gates = jnp.einsum(
            "btd,dcf->bctf", h, _weight(mlp, "w1", cdt), preferred_element_type=jnp.float32
        ).astype(cdt)
        if "b1" in mlp:
            gates = gates + mlp["b1"].astype(cdt)[None, :, None, :]
        hidden = jax.nn.silu(gates[:, 0]) * gates[:, 1]
    else:
        hidden = jnp.einsum(
            "btd,df->btf", h, _weight(mlp, "w1", cdt), preferred_element_type=jnp.float32
        ).astype(cdt)
        if "b1" in mlp:
            hidden = hidden + mlp["b1"].astype(cdt)
        hidden = layers.activation_fn(cfg.activation, hidden)
    hidden = checkpoint_name(hidden, "mlp_hidden")
    out = jnp.einsum(
        "btf,fd->btd", hidden, _weight(mlp, "w2", cdt), preferred_element_type=jnp.float32
    ).astype(cdt)
    if "b2" in mlp:
        out = out + mlp["b2"].astype(cdt)
    return x + out.astype(x.dtype), jnp.zeros((), jnp.float32)


def _block(
    blk: Params,
    x: jax.Array,
    cfg: ModelConfig,
    rope: Optional[Tuple[jax.Array, jax.Array]],
    positions: jax.Array,
    kv: Optional[Params],
    cache_index: Optional[jax.Array],
    zigzag: bool = False,
    pad_offsets: Optional[jax.Array] = None,
    segments: Optional[jax.Array] = None,
    paged: Optional[PagedInfo] = None,
) -> Tuple[jax.Array, Optional[Params], jax.Array]:
    x, new_kv = _attention_block(
        blk, x, cfg, rope, positions, kv, cache_index, zigzag, pad_offsets,
        segments=segments, paged=paged,
    )
    x = constrain(
        x, ("data", "fsdp"), "seq" if cfg.sequence_parallel else None, None
    )
    # Uncapacitated MoE routing only for single-token decode steps: prefill
    # processes whole prompts, where capacity = token count would rebuild the
    # O(S^2) dispatch the grouped path exists to avoid.
    x, aux = _mlp_block(blk, x, cfg, decode=kv is not None and x.shape[1] == 1)
    x = constrain(
        x, ("data", "fsdp"), "seq" if cfg.sequence_parallel else None, None
    )
    return x, new_kv, aux


# ---------------------------------------------------------------------------
# Full forward
# ---------------------------------------------------------------------------


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: ModelConfig,
    *,
    positions: Optional[jax.Array] = None,
    kv_cache: Optional[KVCache] = None,
    cache_index: Optional[jax.Array] = None,
    return_hidden: bool = False,
    return_aux: bool = False,
    return_pre_logits: bool = False,
    zigzag: bool = False,
    blocks_baked: bool = False,
    pad_offsets: Optional[jax.Array] = None,
    paged: Optional[PagedInfo] = None,
) -> Tuple[jax.Array, Optional[KVCache]]:
    """Compute logits. tokens: (B, T) int32 -> logits (B, T, V) fp32.

    ``paged`` + a pool-layout ``kv_cache`` (make_paged_kv_pool) selects
    PAGED single-token decode for continuous-batching serving: block
    tables route each row's reads/writes through a shared block pool (see
    PagedInfo / generation.serving.ServingEngine).

    Training/eval: kv_cache=None. Decode: pass a stacked cache
    {'k','v'}: (L, B, Tmax, kv_heads, Dh) — plus {'k_scale','v_scale'}
    when ``kv_cache_dtype='int8'`` — and the integer write offset
    ``cache_index``; the updated cache is returned. Cached calls with T>1
    and a provably-zero ``cache_index`` (a concrete 0, as the generate
    prefill passes) take the flash-prefill shortcut under
    ``attention_impl != 'naive'``; a traced or nonzero offset (CHUNKED
    prefill) routes through rectangular blockwise attention against the
    cache — O(block) transient memory at any offset. impl='naive' keeps
    the masked einsum everywhere.

    ``return_hidden=True`` additionally returns intermediate activations
    {'block_outputs': (L, B, T, D), 'final_hidden': (B, T, D)} — the
    feature-extraction hook replacing the reference's bespoke
    ``forward_embedding`` methods (transformer.py:80-94, SURVEY §A Q3).

    ``return_aux=True`` additionally returns the summed MoE router
    load-balance loss (zero for dense models).

    ``zigzag=True`` declares that the caller permuted the sequence dim with
    `parallel.zigzag.zigzag_perm` (and passed the matching ``positions``);
    ring attention then uses the balanced zigzag chunk layout. loss_fn
    manages this automatically — set it manually only if you permute inputs
    yourself.

    ``blocks_baked=True`` declares that ``params['blocks']`` is stored in the
    interleaved-pipeline rank-major layout (parallel.pipeline
    .interleave_layout, baked by train_step.shard_train_state) — only valid
    when the pipelined path is active, and required for correctness with a
    baked state.

    ``pad_offsets`` (B,) int32 enables RAGGED cached decode (decode-only;
    requires ``kv_cache``): each row is left-padded by pad_offsets[i] dead
    slots, so a batch of different-length prompts decodes in lockstep —
    `generation.generate(..., prompt_lengths=...)` builds this layout. Row
    i's token at cache slot s has logical position s - pad_offsets[i]
    (RoPE / learned positions use logical; causality + cache writes use
    slots; the kv mask hides each row's pad slots).
    """
    cdt = jnp.dtype(cfg.compute_dtype)
    b, t = tokens.shape
    if pad_offsets is not None and kv_cache is None:
        raise ValueError(
            "pad_offsets (ragged left-padded rows) is a cached-decode "
            "layout; training/eval calls must not pass it"
        )
    if paged is not None:
        if not _is_pool_cache(kv_cache):
            raise ValueError(
                "paged=PagedInfo requires a pool-layout kv_cache "
                "(make_paged_kv_pool)"
            )
        # t == 1: serving decode; small t > 1: speculative paged verify.
        # PROMPTS still enter via generation.paged.prefill_into_pool —
        # the in-forward path scatters tokens one slot past the frontier.
        if pad_offsets is not None:
            raise ValueError(
                "pad_offsets is the contiguous ragged layout; paged rows "
                "are ragged natively via seq_lens"
            )
    elif _is_pool_cache(kv_cache):
        raise ValueError(
            "a pool-layout kv_cache requires forward(..., paged=PagedInfo)"
        )
    if positions is None:
        start = cache_index if cache_index is not None else 0
        positions = start + jnp.arange(t)

    # Packed-document masking: derive per-token document ids from the
    # separator token IN-MODEL (no data-pipeline change — the uint16 token
    # stream already contains the per-document EOT appended at preprocess
    # time). Token i belongs to document #(separators strictly before i),
    # so the separator itself is the LAST token of its document; attention
    # never crosses a boundary. Training/eval only — generation of a
    # packed stream is meaningless, and validation forbids the combination.
    segments = None
    if cfg.doc_mask_token >= 0:
        if kv_cache is not None:
            raise ValueError(
                "doc_mask_token is a training/eval feature; cached decode "
                "must run with doc masking disabled"
            )
        is_sep = (tokens == cfg.doc_mask_token).astype(jnp.int32)
        segments = jnp.cumsum(is_sep, axis=1) - is_sep  # exclusive cumsum

    # Replicate the (vocab x fsdp)-sharded table explicitly before the
    # lookup: the gather's output sharding then propagates from the
    # batch-sharded token indices. Left implicit, XLA propagates the TABLE's
    # sharding onto the (B, T, D) output and then cannot reach the
    # batch-sharded constraint efficiently — the "[SPMD] involuntary full
    # rematerialization" replicate-then-reshard of the activations seen in
    # the multichip dryrun (XLA all-gathers the table either way).
    emb_table = constrain(params["tok_embed"]["embedding"], None, None)
    x = emb_table[tokens].astype(cdt)
    if cfg.pos_embed == "learned":
        pos_table = constrain(params["pos_embed"]["embedding"], None, None)
        if paged is not None:
            # Each row's query tokens sit at their own logical positions
            # (seq + i); clip keeps overshoot rows (scratch-redirected
            # garbage by contract) inside the table.
            ppos = jnp.clip(
                paged.seq_lens[:, None]
                + jnp.arange(t, dtype=paged.seq_lens.dtype)[None, :],
                0, cfg.context_length - 1,
            )
            x = x + pos_table[ppos].astype(cdt)
        elif pad_offsets is not None:
            logical = jnp.clip(positions[None, :] - pad_offsets[:, None], 0)
            x = x + pos_table[logical].astype(cdt)  # (B, T, D) per-row gather
        else:
            x = x + pos_table[positions].astype(cdt)[None]
        rope = None
    else:
        rope = layers.rope_table(cfg.context_length, cfg.head_dim, cfg.rope_theta)
    x = constrain(x, ("data", "fsdp"), "seq" if cfg.sequence_parallel else None, None)

    def scan_body(carry, layer_inputs):
        x, aux_sum = carry
        if kv_cache is None:
            blk = layer_inputs
            x, _, aux = _block(
                blk, x, cfg, rope, positions, None, None, zigzag,
                segments=segments,
            )
            return (x, aux_sum + aux), (x if return_hidden else None)
        blk, cache_layer = layer_inputs
        x, new_kv, aux = _block(
            blk, x, cfg, rope, positions, cache_layer, cache_index,
            pad_offsets=pad_offsets, paged=paged,
        )
        return (x, aux_sum + aux), new_kv

    body = remat.checkpoint_wrap(scan_body, cfg.remat)

    mesh = current_mesh()
    use_pipeline = (
        kv_cache is None
        and cfg.pipeline_stages > 1
        and mesh is not None
        and mesh.shape.get("pipe", 1) > 1
    )

    block_outputs = None
    aux0 = jnp.zeros((), jnp.float32)
    if blocks_baked and not use_pipeline:
        raise ValueError(
            "blocks_baked=True but the pipelined path is inactive (no pipe "
            "mesh installed, or pipeline_stages<=1): a rank-major baked "
            "layer stack would be scanned in the wrong depth order. "
            "De-interleave with parallel.pipeline.deinterleave_layout first."
        )
    if use_pipeline:
        if return_hidden:
            raise ValueError("return_hidden is not supported with pipeline parallelism")
        from pretraining_llm_tpu.parallel import pipeline

        def pipe_block(blk, h):
            h, _, aux = _block(blk, h, cfg, rope, positions, None, None, zigzag)
            return h, aux

        x, aux_total = pipeline.pipeline_apply(
            params["blocks"], x, mesh, pipe_block,
            n_micro=cfg.pipeline_microbatches, remat=cfg.remat,
            interleave=cfg.pipeline_interleave, baked=blocks_baked,
        )
        new_cache = None
    elif kv_cache is None:
        (x, aux_total), block_outputs = jax.lax.scan(
            body, (x, aux0), params["blocks"], unroll=cfg.scan_unroll
        )
        new_cache = None
    elif "layers" in kv_cache:
        # UNSTACKED decode cache (decode_cache_layout='unstacked'):
        # trace-time python loop over layers, each layer's (B, T, G, Dh)
        # cache leaves updated by ONE dynamic-update-slice directly on the
        # token-scan carry — the aliasable pattern, eliminating both the
        # stacked layout's whole-cache carry copies and its per-layer
        # slice/update-slice relayouts (together ~50% of the profiled v5e
        # decode step). Layer weights come from static slices of the
        # stacked block params (fold into their consumers, no copies).
        if t > cfg.decode_loop_max_tokens:
            # PREFILL: the carry-copy pathology is per decode STEP; a
            # python layer loop here would only scale the prefill program
            # (and its compile time) by n_layers. Re-stack, run the rolled
            # scan once, unstack the result — two whole-cache copies per
            # prefill, amortized over the entire generation. Small multi-
            # token calls (speculative-decoding verify rounds, Tq=k+1)
            # keep the in-place layer loop below: they repeat every few
            # tokens, so per-round re-stack copies would claw back the
            # unstacked layout's win (boundary: decode_loop_max_tokens).
            stacked_cache = {
                name: jnp.stack([lyr[name] for lyr in kv_cache["layers"]])
                for name in kv_cache["layers"][0]
            }
            (x, aux_total), new_stacked = jax.lax.scan(
                body, (x, aux0), (params["blocks"], stacked_cache),
                unroll=cfg.scan_unroll,
            )
            new_cache = {
                "layers": tuple(
                    {name: buf[layer] for name, buf in new_stacked.items()}
                    for layer in range(cfg.n_layers)
                )
            }
        else:
            aux_total = aux0
            new_layers = []
            for layer in range(cfg.n_layers):
                blk = jax.tree.map(
                    lambda a, _l=layer: jax.lax.index_in_dim(
                        a, _l, 0, keepdims=False
                    ),
                    params["blocks"],
                )
                x, new_kv, aux = _block(
                    blk, x, cfg, rope, positions, kv_cache["layers"][layer],
                    cache_index, pad_offsets=pad_offsets, paged=paged,
                )
                aux_total = aux_total + aux
                new_layers.append(new_kv)
            new_cache = {"layers": tuple(new_layers)}
    else:
        # Single-token decode steps may fully unroll the depth scan: the
        # rolled inner while forces XLA to copy the whole cache at the
        # token-scan loop boundary every step (see ModelConfig.
        # decode_unroll_layers). Tq is a static shape, so this is a
        # trace-time choice; prefill (Tq>1) keeps the rolled scan.
        # (On-chip 2026-08-01: unroll measured SLOWER than the rolled scan
        # — the unstacked cache layout above is the measured fix for the
        # carry-copy problem instead.)
        unroll = (
            cfg.n_layers
            if cfg.decode_unroll_layers and x.shape[1] == 1
            else cfg.scan_unroll
        )
        (x, aux_total), new_cache = jax.lax.scan(
            body, (x, aux0), (params["blocks"], kv_cache),
            unroll=unroll,
        )

    x = layers.apply_norm(cfg.norm, params["final_norm"], x, cfg.norm_eps)
    if return_pre_logits:
        # Loss path: the chunked-CE head computes logits itself (see
        # _chunked_ce); hand back the final-norm hidden states.
        logits = x
    else:
        w_out, head_bias = _lm_head_weights(params, cfg)
        logits = jnp.einsum(
            "btd,dv->btv", x.astype(cdt), w_out.astype(cdt), preferred_element_type=jnp.float32
        )
        if head_bias is not None:
            logits = logits + head_bias.astype(jnp.float32)
    extras: Tuple[Any, ...] = ()
    if return_hidden:
        extras += ({"block_outputs": block_outputs, "final_hidden": x},)
    if return_aux:
        extras += (aux_total,)
    if extras:
        return (logits, new_cache) + extras
    return logits, new_cache


def _chunked_ce(
    hidden: jax.Array,
    w_out: jax.Array,
    bias: Optional[jax.Array],
    targets: jax.Array,
    cfg: ModelConfig,
    z: float = 0.0,
) -> jax.Array:
    """Mean cross-entropy head dispatcher (chunked | fused | dense).

    chunked (default): no full (B*T, V) logits buffer. The fp32 logits for
    GPT-2-sized vocabs dwarf every other activation (B=12, T=1024,
    V=50304 -> 2.5 GB); computing them whole, saving them for backward, and
    re-reading them is pure HBM traffic. Instead scan over token chunks:
    each chunk's logits live only transiently, and the backward recomputes
    them chunk-by-chunk (one extra small matmul per chunk for a ~3x cut in
    head memory traffic). fused: Pallas kernel (see ops/pallas_ce).
    dense: the OPPOSITE trade — deliberately materializes and SAVES the
    compute-dtype (S, V) logits so backward recomputes nothing (see
    _dense_lse_ce); head memory is S*V*2 bytes.
    """
    cdt = jnp.dtype(cfg.compute_dtype)
    b, t, d = hidden.shape
    s = b * t
    if cfg.ce_impl == "fused":
        from pretraining_llm_tpu.ops.pallas_ce import fused_cross_entropy

        mesh = current_mesh()
        # GSPMD can't partition a pallas_call: without handling it would
        # REPLICATE the kernel (all-gathering the global batch onto every
        # device). Batch-sharded meshes get an explicit shard_map over the
        # batch axes (W replicated, per-shard kernel); vocab-sharded (tensor)
        # and seq/pipe-sharded hidden layouts fall back to chunked CE.
        nontrivial = lambda ax: mesh.shape.get(ax, 1) > 1 if mesh is not None else False
        fused_ok = bias is None and not any(
            nontrivial(ax) for ax in ("tensor", "seq", "pipe")
        )
        if not fused_ok:
            # Loud degradation (VERDICT r2 #9): the user asked for the fused
            # kernel; tell them they aren't getting it instead of silently
            # training slower. Fires once per trace (warnings dedupe).
            import warnings

            why = (
                "the lm_head has a bias"
                if bias is not None
                else "the mesh shards tensor/seq/pipe axes the kernel can't express"
            )
            warnings.warn(
                f"ce_impl='fused' degraded to chunked CE: {why}. "
                "Drop lm_head_bias / use a data+fsdp-only mesh to get the "
                "fused kernel.",
                stacklevel=3,
            )
        if fused_ok:
            hidden_c = hidden.astype(cdt)
            w_c = w_out.astype(cdt)
            if mesh is not None and (nontrivial("data") or nontrivial("fsdp")):
                from jax.sharding import PartitionSpec as P

                batch_axes = ("data", "fsdp")

                def local_ce(h_l, w_l, t_l):
                    bl, tl, dl = h_l.shape
                    return fused_cross_entropy(
                        h_l.reshape(bl * tl, dl), w_l, t_l.reshape(bl * tl)
                    ).reshape(bl, tl)

                losses = jax_compat.shard_map(
                    local_ce,
                    mesh=mesh,
                    in_specs=(P(batch_axes, None, None), P(None, None), P(batch_axes, None)),
                    out_specs=P(batch_axes, None),
                    check_vma=False,
                )(hidden_c, w_c, targets)
            else:
                losses = fused_cross_entropy(
                    hidden_c.reshape(s, d), w_c, targets.reshape(s)
                )
            return jnp.mean(losses)
    if cfg.ce_impl == "dense":
        # ZERO-recompute head: the backward of the chunked path re-runs the
        # (S, V) logits matmul (2*S*d*V FLOPs — ~10% of the whole step's
        # analytic FLOPs at gpt2-124m/b16, pure unaccounted wall time),
        # while this path SAVES compute-dtype logits (+ the f32 lse) and
        # backward is just softmax + the two unavoidable grad matmuls.
        # Cost: S*V*2 bytes of saved residual (824 MB at b8/T1024/V50304)
        # — affordable exactly when remat pressure is low (small batch or
        # remat=none), which is when the recompute charge dominates. Also
        # removes the chunk scan's serialization. Numerics: backward's
        # softmax is exp(bf16-rounded logits - lse) vs the chunked path's
        # freshly recomputed f32-accum logits; grads agree to bf16 rounding
        # (tested) — the forward LOSS value is computed from f32-accum
        # logits either way and matches exactly.
        return _dense_lse_ce(
            hidden.reshape(s, d), w_out, bias, targets.reshape(s), cdt, z=z
        ) / s
    # Chunk only when the fp32 logits buffer is big enough to matter (XLA
    # already fuses the small-head case well — measured neutral-to-slower to
    # chunk at GPT-2 batch sizes). Target <= ~512 MB per chunk.
    logits_bytes = s * cfg.vocab_size * 4
    want = max(1, -(-logits_bytes // (512 * 1024 * 1024)))
    n_chunks = 1
    if want > 1:
        # Any divisor of S with chunk >= 512 keeps the memory bound; prefer
        # the smallest chunk count >= want, else the largest available (an
        # awkward S loses granularity, not the whole saving).
        divisors = [c for c in range(2, s // 512 + 1) if s % c == 0]
        at_least = [c for c in divisors if c >= want]
        if at_least:
            n_chunks = min(at_least)
        elif divisors:
            n_chunks = max(divisors)
        if n_chunks < want:
            import warnings

            warnings.warn(
                f"chunked CE head: batch*seq={s} has no divisor >= {want} with "
                f"chunk >= 512; using {n_chunks} chunks — logits memory "
                f"{logits_bytes / n_chunks / 2**20:.0f} MB/chunk exceeds the "
                "512 MB target. Prefer power-of-two batch*context products.",
                stacklevel=2,
            )
    xs = hidden.reshape(n_chunks, s // n_chunks, d)
    ts_ = targets.reshape(n_chunks, s // n_chunks)
    return _lse_saved_ce(xs, w_out, bias, ts_, cdt, z=z) / s


def _subtract_onehot(p: jax.Array, targets: jax.Array) -> jax.Array:
    """softmax-grad core: p - onehot(targets), WITHOUT a scatter.

    The obvious ``p.at[arange, t].add(-1)`` lowers to a TPU scatter, which
    linearizes the whole (S, V) fp32 block to scatter layout and back —
    profiled at ~8% of the entire gpt2-124m train step (the top two
    data-formatting ops in the 2026-08-01 hlo_stats capture, ~15 ms/step
    of pure relayout at b16). The iota-compare-subtract form fuses into
    the same elementwise pass that builds p: zero extra memory traffic.

    Contract: targets must lie in [0, vocab_size). The scatter form wrapped
    negative indices (``.at[t].add`` subtracts at column V+t); this form is a
    NO-OP for out-of-range ids, so the two differ if an ignore-index
    convention is ever added — route ignored positions through a loss MASK
    (as loss_fn's docmask path does), never a sentinel target id.
    """
    if __debug__ and not isinstance(targets, jax.core.Tracer):
        assert int(targets.min()) >= 0 and int(targets.max()) < p.shape[1], (
            "_subtract_onehot: targets outside [0, vocab) — use a loss mask, "
            "not a sentinel id"
        )
    cols = jax.lax.broadcasted_iota(jnp.int32, p.shape, dimension=1)
    return p - (cols == targets[:, None]).astype(p.dtype)


def _head_logits32(xc, wc, bias, cdt):
    """The ONE definition of head logits for both custom-VJP CE heads:
    compute-dtype operands, f32 accumulation, f32 bias add. The chunked and
    dense backward paths must stay numerically in lockstep — any change to
    this formula applies to both."""
    logits = jnp.einsum(
        "sd,dv->sv", xc.astype(cdt), wc, preferred_element_type=jnp.float32
    )
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    return logits


def _lse_saved_ce(xs, w_out, bias, ts_, cdt, z=0.0):
    """Sum of per-token CE over chunked logits, custom VJP.

    vs `lax.scan(jax.checkpoint(chunk))`: the checkpointed backward re-runs
    the whole forward per chunk — logits matmul, then max + exp + sum for
    logsumexp, then ANOTHER exp for its VJP — four elementwise passes over
    the (S, V) block that exists only to rebuild what one saved (S,) vector
    already knows. Saving lse (4 bytes/token) lets the backward form
    softmax = exp(logits - lse) in ONE pass after the (unavoidable) logits
    matmul recompute. Matmul count and the fp32 dW scan carry are identical
    to the autodiff version — this strictly removes VPU reduction passes.

    Gradients match the checkpointed path to float-associativity: dlogits
    stays fp32 into the dX/dW matmuls exactly as autodiff would keep it.
    """
    def logits_of(xc, wc, bias):
        return _head_logits32(xc, wc, bias, cdt)

    @jax.custom_vjp
    def ce(xs, w_out, bias):
        return _fwd(xs, w_out, bias)[0]

    def _fwd(xs, w_out, bias):
        wc = w_out.astype(cdt)

        def chunk(carry, inp):
            xc, tc = inp
            logits = logits_of(xc, wc, bias)
            lse = jax.nn.logsumexp(logits, axis=-1)
            label_logit = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
            total = jnp.sum(lse - label_logit)
            if z:
                # z-loss (PaLM/ST-MoE): z * lse^2 keeps softmax logits from
                # drifting (lse ~ 0 means calibrated normalizers; also
                # guards bf16 logit overflow at scale).
                total = total + z * jnp.sum(jnp.square(lse))
            return carry + total, lse

        total, lses = jax.lax.scan(chunk, jnp.zeros((), jnp.float32), (xs, ts_))
        return total, (xs, w_out, bias, lses)

    def _bwd(res, g):
        xs, w_out, bias, lses = res
        wc = w_out.astype(cdt)
        dw0 = jnp.zeros(w_out.shape, jnp.float32)
        db0 = None if bias is None else jnp.zeros(bias.shape, jnp.float32)

        def chunk(carry, inp):
            dw_acc, db_acc = carry
            xc, tc, lse = inp
            logits = logits_of(xc, wc, bias)
            p = jnp.exp(logits - lse[:, None])  # softmax, one pass
            if z:
                # d(lse^2)/dlogits = 2*lse*softmax -> fold into p's scale.
                p = p * (1.0 + 2.0 * z * lse[:, None])
            dlogits = _subtract_onehot(p, tc) * g  # fp32
            dx = jnp.einsum(
                "sv,dv->sd", dlogits, wc, preferred_element_type=jnp.float32
            )
            dw_acc = dw_acc + jnp.einsum(
                "sd,sv->dv", xc.astype(cdt), dlogits,
                preferred_element_type=jnp.float32,
            )
            if db_acc is not None:
                db_acc = db_acc + jnp.sum(dlogits, axis=0)
            return (dw_acc, db_acc), dx.astype(xs.dtype)

        (dw, db), dxs = jax.lax.scan(chunk, (dw0, db0), (xs, ts_, lses))
        return (
            dxs,
            dw.astype(w_out.dtype),
            None if bias is None else db.astype(bias.dtype),
        )

    ce.defvjp(_fwd, _bwd)
    return ce(xs, w_out, bias)


def _dense_lse_ce(x, w_out, bias, ts_, cdt, z=0.0):
    """Sum of per-token CE with SAVED logits — no backward recompute.

    Custom VJP saving (compute-dtype logits, f32 lse): forward computes the
    (S, V) logits once with f32 accumulation (loss value identical to the
    chunked path), backward rebuilds softmax in one elementwise pass from
    the saved block and goes straight to the dX/dW matmuls. The matmul the
    chunked backward re-runs simply never happens again.
    """
    @jax.custom_vjp
    def ce(x, w_out, bias):
        return _fwd(x, w_out, bias)[0]

    def _fwd(x, w_out, bias):
        logits = _head_logits32(x, w_out.astype(cdt), bias, cdt)
        lse = jax.nn.logsumexp(logits, axis=-1)
        label_logit = jnp.take_along_axis(logits, ts_[:, None], axis=-1)[:, 0]
        total = jnp.sum(lse - label_logit)
        if z:
            total = total + z * jnp.sum(jnp.square(lse))  # see _lse_saved_ce
        # Save in compute dtype: halves the residual vs f32 at bf16-rounding
        # cost in backward only (the fp32 loss above is already computed).
        return total, (x, w_out, bias, logits.astype(cdt), lse)

    def _bwd(res, g):
        x, w_out, bias, logits_c, lse = res
        p = jnp.exp(logits_c.astype(jnp.float32) - lse[:, None])
        if z:
            p = p * (1.0 + 2.0 * z * lse[:, None])  # see _lse_saved_ce
        dlogits = _subtract_onehot(p, ts_) * g  # fp32
        dx = jnp.einsum(
            "sv,dv->sd", dlogits, w_out.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        dw = jnp.einsum(
            "sd,sv->dv", x.astype(cdt), dlogits,
            preferred_element_type=jnp.float32,
        )
        db = None if bias is None else jnp.sum(dlogits, axis=0)
        return (
            dx.astype(x.dtype),
            dw.astype(w_out.dtype),
            None if bias is None else db.astype(bias.dtype),
        )

    ce.defvjp(_fwd, _bwd)
    return ce(x, w_out, bias)


def loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: ModelConfig,
    *,
    include_aux: bool = True,
    blocks_baked: bool = False,
) -> jax.Array:
    """Mean next-token cross-entropy in fp32 (reference: transformer.py:73-77).

    Computed via the chunked head (see _chunked_ce) — numerically identical
    to logsumexp over full logits, but O(1/n_chunks) head memory. For MoE
    models the Switch-style router load-balance loss is added with weight
    ``cfg.router_aux_coef`` when ``include_aux`` (training objective); eval
    passes include_aux=False so reported val_loss stays pure cross-entropy,
    comparable across dense and MoE models.

    With zigzag ring attention active (attention_impl='ring',
    ring_layout='zigzag', a seq>1 mesh), tokens/targets/positions are
    permuted here into the balanced chunk-pair layout — mean CE is
    permutation invariant, so the loss value is identical to the dense
    computation (tested) while causal ring work balances across devices.
    """
    positions = None
    zigzag = False
    if cfg.attention_impl == "ring" and cfg.ring_layout == "zigzag":
        mesh = current_mesh()
        n_seq = mesh.shape.get("seq", 1) if mesh is not None else 1
        if n_seq > 1:
            if tokens.shape[1] % (2 * n_seq) == 0:
                from pretraining_llm_tpu.parallel.zigzag import zigzag_perm

                perm = zigzag_perm(tokens.shape[1], n_seq)
                # Re-pin the batch/seq sharding after the permutation: the
                # gather's output sharding is otherwise ambiguous to XLA's
                # propagation, which falls back to replicate-then-reshard on
                # the embedding lookup downstream ("[SPMD] involuntary full
                # rematerialization" warnings). Constrained here, the zigzag
                # shuffle is one explicit (B, T) int32 collective permute and
                # the embedding gather stays shard-local.
                tokens = constrain(tokens[:, perm], ("data", "fsdp"), "seq")
                targets = constrain(targets[:, perm], ("data", "fsdp"), "seq")
                positions = jnp.asarray(perm)
                zigzag = True
            else:
                import warnings

                warnings.warn(
                    f"ring_layout='zigzag' configured but seq_len="
                    f"{tokens.shape[1]} is not divisible by 2*seq_axis="
                    f"{2 * n_seq}; falling back to the imbalanced contiguous "
                    "ring layout (utilization ~(n+1)/2n).",
                    stacklevel=2,
                )
    hidden, _, aux = forward(
        params, tokens, cfg, positions=positions, zigzag=zigzag,
        return_aux=True, return_pre_logits=True, blocks_baked=blocks_baked,
    )
    w_out, bias = _lm_head_weights(params, cfg)
    # z-loss is part of the TRAINING objective only — include_aux=False
    # (eval) keeps reported val_loss pure cross-entropy, exactly like the
    # MoE router aux term.
    loss = _chunked_ce(
        hidden, w_out, bias, targets, cfg,
        z=cfg.z_loss_coef if include_aux else 0.0,
    )
    if cfg.n_experts and include_aux:
        loss = loss + cfg.router_aux_coef * aux
    return loss


def _is_pool_cache(kv_cache: Optional[KVCache]) -> bool:
    """True for a paged POOL container (stacked or unstacked layout)."""
    return kv_cache is not None and (
        "k_pool" in kv_cache
        or ("layers" in kv_cache and "k_pool" in kv_cache["layers"][0])
    )


def _unstack_fields(n_layers: int, fields: Dict[str, Tuple[Tuple[int, ...], Any]]) -> KVCache:
    """{'layers': per-layer dicts of fresh zero arrays} from {name:
    (stacked_shape, dtype)} specs — allocated per layer DIRECTLY (never
    materializing the stacked array first: pools are sized toward HBM
    capacity, and a transient 2x would OOM engines that otherwise fit).
    Each layer gets its own buffers (sharing one zeros across carry
    leaves would alias donated updates)."""
    return {
        "layers": tuple(
            {
                name: jnp.zeros(shape[1:], dt)
                for name, (shape, dt) in fields.items()
            }
            for _ in range(n_layers)
        )
    }


def make_kv_cache(
    cfg: ModelConfig, batch_size: int, max_length: int, dtype: Any = None
) -> KVCache:
    """Decode cache in the layout ``cfg.decode_cache_layout`` selects:
    stacked {(L, B, T, G, Dh)} fields, or {'layers': (per-layer dicts of
    (B, T, G, Dh) fields,)} — see the config field for the v5e profile
    evidence behind the unstacked option."""
    if max_length > cfg.context_length:
        # Position tables (learned or RoPE) are sized by context_length; JAX
        # gather would silently clamp out-of-range positions — fail fast here.
        raise ValueError(
            f"kv cache max_length={max_length} exceeds context_length={cfg.context_length}"
        )
    # GQA caches only kv_heads heads — the memory win that motivates GQA.
    shape = (cfg.n_layers, batch_size, max_length, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        if dtype is not None:
            # An explicit element dtype contradicts the quantized layout;
            # dropping it silently would hand back an int8 cache to a
            # caller that asked for an exact fp baseline.
            raise ValueError(
                f"make_kv_cache(dtype={dtype!r}) conflicts with "
                "kv_cache_dtype='int8'; use kv_cache_dtype='compute' for an "
                "exact cache"
            )
        # Per-(token, head) symmetric int8: values + an fp32 amax scale.
        # Persistent cache bytes per element: 1 + 4/Dh vs 2 (bf16) — ~1.9x
        # smaller at Dh=64; the transient dequant is per-layer, per-step.
        sshape = shape[:-1] + (1,)
        fields = {
            "k": (shape, jnp.int8),
            "v": (shape, jnp.int8),
            "k_scale": (sshape, jnp.float32),
            "v_scale": (sshape, jnp.float32),
        }
    else:
        dtype = jnp.dtype(dtype or cfg.compute_dtype)
        fields = {"k": (shape, dtype), "v": (shape, dtype)}
    if cfg.decode_cache_layout == "unstacked":
        return _unstack_fields(cfg.n_layers, fields)
    return {name: jnp.zeros(s, dt) for name, (s, dt) in fields.items()}


def make_paged_kv_pool(
    cfg: ModelConfig, n_blocks: int, block_size: int, dtype: Any = None,
    *, scale_dtype: Any = None,
) -> KVCache:
    """Block POOL layout for paged serving decode (see PagedInfo).

    Pools are stacked over layers like the contiguous cache and ride the
    same depth-scan carry: {'k_pool','v_pool'}: (L, n_blocks, block_size,
    kv_heads, Dh), plus scale pools when ``kv_cache_dtype='int8'``.
    Block 0 is reserved by convention as the idle-row scratch target (the
    serving engine parks inactive batch rows on it); allocators hand out
    ids from 1.

    ``scale_dtype`` (int8 pools only) picks the per-(slot, head) scale
    element type: fp32 by default (historical layout, bit-compatible with
    the dense int8 cache), bfloat16 for the ``serving.quantize=int8-kv``
    mode — per-slot bytes drop from Dh+4 to Dh+2, so an int8-kv pool
    holds 2*Dh/(Dh+2) ≈ 1.94x (Dh=64) the blocks of a bf16 pool at equal
    HBM budget (fp32 scales stall at 1.88x, under the 1.9x capacity
    target). The quantize scatter casts the fp32 amax to bf16 at write
    and every dequant upcasts back to fp32, so page bytes stay a pure
    function of the token's hidden state (the bit-identity contract).
    """
    if n_blocks < 2:
        raise ValueError("need n_blocks >= 2 (block 0 is the idle scratch)")
    if block_size % 8:
        # TPU sublane granularity; also keeps page gathers tile-aligned.
        raise ValueError(f"block_size must be a multiple of 8, got {block_size}")
    shape = (cfg.n_layers, n_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        if dtype is not None:
            raise ValueError(
                f"make_paged_kv_pool(dtype={dtype!r}) conflicts with "
                "kv_cache_dtype='int8'"
            )
        sdt = jnp.dtype(scale_dtype or jnp.float32)
        if sdt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
            raise ValueError(
                f"int8 pool scale_dtype must be float32 or bfloat16, got {sdt}"
            )
        sshape = shape[:-1] + (1,)
        fields = {
            "k_pool": (shape, jnp.int8),
            "v_pool": (shape, jnp.int8),
            "k_scale_pool": (sshape, sdt),
            "v_scale_pool": (sshape, sdt),
        }
    else:
        if scale_dtype is not None:
            raise ValueError(
                f"make_paged_kv_pool(scale_dtype={scale_dtype!r}) needs "
                "kv_cache_dtype='int8' (exact pools carry no scale pages)"
            )
        dtype = jnp.dtype(dtype or cfg.compute_dtype)
        fields = {"k_pool": (shape, dtype), "v_pool": (shape, dtype)}
    if cfg.decode_cache_layout == "unstacked":
        # Same carry-aliasing rationale as the dense unstacked cache
        # (see decode_cache_layout): per-layer pools update in place on
        # the serving window's token-scan carry.
        return _unstack_fields(cfg.n_layers, fields)
    return {name: jnp.zeros(s, dt) for name, (s, dt) in fields.items()}


def _kv_quantize(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-(token, head) over the channel dim."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1, keepdims=True), 1e-8)
    q = jnp.round(x32 / scale * 127.0).astype(jnp.int8)
    return q, scale


def _kv_dequantize(q: jax.Array, scale: jax.Array, dtype: Any) -> jax.Array:
    # Scale upcast FIRST: bf16 scale pools (int8-kv serving) must multiply
    # in fp32 like the historical fp32 scales do — JAX weak typing would
    # otherwise compute `scale * (1/127)` in bf16. Bit-wise a no-op for
    # fp32 scales.
    scale32 = scale.astype(jnp.float32)
    return (q.astype(jnp.float32) * (scale32 * (1.0 / 127.0))).astype(dtype)


def _materialize_cache(kv: Params, quantized: bool, dtype: Any):
    """(k, v) in compute dtype from a (possibly int8-quantized, possibly
    sliced) cache view — the single dequant point for every cached-attention
    read path."""
    if quantized:
        return (
            _kv_dequantize(kv["k"], kv["k_scale"], dtype),
            _kv_dequantize(kv["v"], kv["v_scale"], dtype),
        )
    return kv["k"].astype(dtype), kv["v"].astype(dtype)
