"""Run-wide observability: events, spans, goodput, device/compile telemetry.

The resilience subsystem (resilience/) made multi-day runs *survive* faults;
this package makes the cost of surviving them visible. The flat metrics JSONL
records loss and windowed MFU, but restarts, rollbacks, eval, checkpoint
saves and recompiles are invisible in it — a 43.8%-MFU run and a run that
spent 20% of wall-clock replaying a poison window look identical. The pieces:

  - events.py  — structured, monotonic-timestamped run events (an EventBus
                 with in-process subscribers and an optional JSONL sink);
                 everything else in this package is a fold over the stream.
  - spans.py   — nested host-side context-manager timers exporting Chrome
                 trace-event JSON (open in Perfetto next to the XLA xplane
                 dumps from ``--profile``). Recording is an append to a
                 list — no device syncs, safe anywhere on the host.
  - goodput.py — folds the event stream into a wall-clock decomposition
                 (productive / replay / eval / checkpoint / restore / idle /
                 other) and a single ``goodput`` fraction. Replay detection
                 is a step high-water mark: re-run steps after a rollback
                 are never productive time.
  - device.py  — per-device HBM sampling (``Device.memory_stats()``) and a
                 jax.monitoring compile listener that turns post-warmup
                 backend compiles into ``recompile`` events, so a recompile
                 storm shows up in the stream instead of only as lost MFU.
  - export.py  — Prometheus textfile exporter (no server dependency): one
                 atomic write per log boundary for a node-exporter-style
                 scrape.
  - capacity.py — serving capacity accounting: per-window occupancy
                 samples (rows/tokens/pool/queue at the reap sync point)
                 and a typed scheduler decision log (reject/shed/preempt/
                 evict/reclaim), both ring-buffered and bus-emitted;
                 scripts/obs_report.py --capacity folds them into a
                 slot-second waterfall naming the binding constraint.

scripts/obs_report.py is the offline half: metrics/events JSONL in, goodput
breakdown + step-time histogram + event timeline out (run in CI over the
smoke run, making the JSONL schema a checked contract).

Everything here is host-side; recording between log boundaries performs no
device→host syncs (tested). The hub below is what the trainer wires in.
"""

from pretraining_llm_tpu.observability.capacity import (
    DECISION_KINDS,
    CapacitySampler,
    DecisionLog,
)
from pretraining_llm_tpu.observability.events import EVENT_KINDS, EventBus, sanitize_record
from pretraining_llm_tpu.observability.goodput import CATEGORIES, GoodputAccountant
from pretraining_llm_tpu.observability.spans import SpanRecorder, get_recorder, span
from pretraining_llm_tpu.observability.export import (
    lint_exposition,
    prometheus_lines,
    write_textfile,
)
from pretraining_llm_tpu.observability.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_buckets,
)
from pretraining_llm_tpu.observability.tracing import (
    RequestTrace,
    SpanContext,
    Tracer,
    format_traceparent,
    parse_traceparent,
)
from pretraining_llm_tpu.observability.device import CompileWatcher, DeviceTelemetry
from pretraining_llm_tpu.observability.hub import ObservabilityHub

__all__ = [
    "DECISION_KINDS",
    "CapacitySampler",
    "DecisionLog",
    "EVENT_KINDS",
    "EventBus",
    "sanitize_record",
    "CATEGORIES",
    "GoodputAccountant",
    "SpanRecorder",
    "get_recorder",
    "span",
    "lint_exposition",
    "prometheus_lines",
    "write_textfile",
    "DEFAULT_LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "log_buckets",
    "RequestTrace",
    "SpanContext",
    "Tracer",
    "format_traceparent",
    "parse_traceparent",
    "CompileWatcher",
    "DeviceTelemetry",
    "ObservabilityHub",
]
