"""Capacity observability for the serving engine: where do decode-window
slots and KV-pool blocks actually go?

Two host-only instruments, both installed by the frontend (EngineLoop) and
both riding EXISTING sync points — the reap's ``np.asarray`` is the only
device pull on the decode hot path, and nothing here adds another (the
``np.asarray``-spy test in tests/test_capacity.py enforces it):

``CapacitySampler``
    One occupancy record per reaped decode window: rows active vs. batch
    capacity, tokens committed vs. slot capacity (rows * steps), the pool
    split live / cold-cache / free, admission queue depth and outstanding-
    token budget, and host-blocked readback seconds. Records are plain
    host ints/floats, ring-buffered (bounded memory for long-lived
    servers), optionally emitted as ``cap_window`` run events, and
    mirrored into typed Gauges/Histograms on the metrics registry.

``DecisionLog``
    Every scheduler decision that costs a request something — admission
    reject (busy/infeasible), EWMA deadline shed, preemption (victim,
    why youngest-first chose it, blocks reclaimed), cold-cache eviction,
    spec-page reclaim, in-flight deadline expiry — becomes one typed
    record carrying ``trace_id`` so "why was trace X preempted/shed" is
    answerable offline by joining against the ``req_*`` event stream
    (scripts/obs_report.py --capacity).

Timestamps: records carry explicit ``time.perf_counter`` fields
(``t_dispatch_s``/``t_reap_s`` on windows, ``t_s`` on decisions) so the
offline waterfall does interval math on ONE clock; the bus's own
``t_mono``/``t_wall`` stamps are for cross-stream ordering only.

Thread safety: producers run on the engine/scheduling thread; the gateway
debug endpoints read ``tail()``/``counts`` from HTTP threads. A lock per
instrument covers the ring mutations; records themselves are immutable
once appended.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

# The decision vocabulary. obs_report --capacity labels segments and the
# strict CI gate joins these against traces, so producers keep to this
# list (mirrors EVENT_KINDS' role for run events).
DECISION_KINDS = (
    "reject_busy",        # admission: queue/budget full -> 429
    "reject_infeasible",  # admission: EWMA says deadline can't be met
    "preempt",            # pool dry: youngest victim recomputes on resume
    "evict_cold",         # cold prefix-cache blocks reclaimed for a live row
    "reclaim_spec",       # speculative page grants rolled back under pressure
    "expire_inflight",    # deadline passed mid-decode -> cancelled (504)
    "defer_prefill_chunk",  # chunk budget spent this tick; prompt waits a window

    # Fleet-tier decisions (frontend/router.py): each costs a request a
    # retry, a re-prefill, or its slot, so they live in the same ledger.
    "eject_replica",      # router declared a replica dead/wedged and stopped routing to it
    "redrive",            # an in-flight request failed over to a surviving replica
    "brownout_shed",      # fleet degraded: low-priority work shed at the router
    "fleet_drain",        # graceful shutdown: router stopped admitting (503s)
    "upgrade_refused",    # rolling upgrade failed probe vetting; rolled back

    # Output-integrity sentinel (resilience/integrity.py): a quarantine
    # costs every in-flight request on the replica a redrive, and a
    # dropped cache block costs its next hit a private re-prefill.
    "quarantine",          # sentinel pulled a divergent replica from service
    "drop_corrupt_block",  # cached KV block failed verify-on-acquire; dropped

    # Disaggregated prefill/decode (frontend/kv_transfer.py): migrating
    # a prefix's KV pages saves the decode tier that prefill; a rejected
    # page costs only a re-prefill, never a wrong token.
    "kv_migrate",           # prefill-tier pages pushed to a decode worker
    "kv_migration_reject",  # decode worker refused migrated pages (checksum/capacity/fence)

    # Live SLO engine (observability/slo.py): a fired burn-rate alert is
    # a decision — it is what an autoscaler or operator acts on — and
    # the record's alert_id joins it to the slo_alert event pair that
    # brackets the incident (trace_id, when present, is the request
    # that tipped the burn over threshold).
    "slo_alert",          # burn-rate alert fired: alert_id, slo_class, rule
)


class DecisionLog:
    """Bounded, typed log of scheduler decisions.

    ``record()`` appends one immutable dict to a ring buffer, bumps the
    per-kind count (counts survive ring eviction — they are the totals),
    and emits a ``decision`` run event when a bus is attached.
    """

    def __init__(self, maxlen: int = 256, bus: Optional[Any] = None) -> None:
        if maxlen < 1:
            raise ValueError(f"DecisionLog maxlen must be >= 1, got {maxlen}")
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.bus = bus
        self.counts: Dict[str, int] = {}

    def record(
        self,
        kind: str,
        *,
        rid: Optional[int] = None,
        trace_id: Optional[str] = None,
        **fields: Any,
    ) -> Dict[str, Any]:
        if kind not in DECISION_KINDS:
            raise ValueError(
                f"unknown decision kind {kind!r}; expected one of "
                f"{DECISION_KINDS}"
            )
        rec: Dict[str, Any] = {"decision": kind, "t_s": time.perf_counter()}
        if rid is not None:
            rec["rid"] = int(rid)
        if trace_id:
            rec["trace_id"] = trace_id
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)
            self.counts[kind] = self.counts.get(kind, 0) + 1
        if self.bus is not None:
            self.bus.emit("decision", **rec)
        return rec

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-n:]

    def counts_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)


class CapacitySampler:
    """Per-window occupancy accounting, sampled at the reap sync point.

    The engine calls ``observe_window()`` once per reaped window with
    values it ALREADY holds on the host (row count, committed-token delta,
    allocator free count, queue depth) — no device access, no new syncs.
    """

    def __init__(
        self,
        rows_capacity: int,
        pool_total: int,
        *,
        maxlen: int = 512,
        bus: Optional[Any] = None,
        admission_snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        pool_layout: Optional[Dict[str, Any]] = None,
    ) -> None:
        if maxlen < 1:
            raise ValueError(
                f"CapacitySampler maxlen must be >= 1, got {maxlen}"
            )
        self.rows_capacity = int(rows_capacity)
        self.pool_total = int(pool_total)
        # Static pool byte/dtype identity (ServingEngine.pool_info()):
        # block COUNTS alone can't be compared across quantize modes — the
        # same HBM budget holds ~2x the int8-kv blocks — so every window
        # record carries the dtype and bytes-per-block it was sampled
        # under, and the offline waterfall can normalize to bytes.
        self.pool_layout = dict(pool_layout or {})
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.bus = bus
        # Injected by the frontend: () -> AdmissionController.snapshot().
        # Optional so the offline engine can sample without a frontend.
        self.admission_snapshot_fn = admission_snapshot_fn
        self.windows_sampled = 0
        # Typed series, bound via bind(); None until a registry exists.
        self._g_rows = None
        self._g_waiting = None
        self._g_pool: Dict[str, Any] = {}
        self._g_adm_depth = None
        self._g_adm_tokens = None
        self._h_occupancy = None
        self._h_slot_util = None

    def bind(self, registry: Any) -> None:
        """Create the cap_* typed series on ``registry`` and keep handles.
        Idempotent per registry (the registry dedupes by name+labels)."""
        self._g_rows = registry.gauge(
            "capacity_rows_active", "decode rows active at last reap"
        )
        registry.gauge(
            "capacity_rows_limit", "decode row slots (max_batch)"
        ).set(self.rows_capacity)
        self._g_waiting = registry.gauge(
            "capacity_waiting_requests",
            "requests queued in the engine awaiting a row",
        )
        for state in ("live", "cold", "free"):
            self._g_pool[state] = registry.gauge(
                "capacity_pool_blocks",
                "KV pool blocks by state at last reap",
                state=state,
            )
        registry.gauge(
            "capacity_pool_blocks_limit", "allocatable KV pool blocks"
        ).set(self.pool_total)
        self._h_occupancy = registry.histogram(
            "capacity_window_occupancy",
            "fraction of row slots active per reaped window",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        )
        self._h_slot_util = registry.histogram(
            "capacity_slot_utilization",
            "tokens committed / slot capacity per reaped window",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        )

    def observe_window(
        self,
        *,
        window: int,
        kind: str,
        t_dispatch_s: float,
        t_reap_s: float,
        steps: int,
        rows: int,
        tokens_committed: int,
        waiting: int,
        pool_free: int,
        pool_cold: int,
        host_blocked_s: float,
        cum_tokens: int,
        cum_prefill_tokens: int,
        cum_rework_prefill_tokens: int,
        cum_preemptions: int,
    ) -> Dict[str, Any]:
        pool_live = self.pool_total - pool_free - pool_cold
        slot_tokens = rows * steps
        rec: Dict[str, Any] = {
            "window": int(window),
            # "window_kind" not "kind": the bus reserves "kind" for the
            # event kind itself ("cap_window").
            "window_kind": kind,
            "t_dispatch_s": float(t_dispatch_s),
            "t_reap_s": float(t_reap_s),
            "dur_s": float(t_reap_s) - float(t_dispatch_s),
            "steps": int(steps),
            "rows": int(rows),
            "rows_capacity": self.rows_capacity,
            "slot_tokens": int(slot_tokens),
            "tokens_committed": int(tokens_committed),
            "waiting": int(waiting),
            "pool_free": int(pool_free),
            "pool_cold": int(pool_cold),
            "pool_live": int(pool_live),
            "pool_total": self.pool_total,
            "host_blocked_s": float(host_blocked_s),
            # Cumulative engine counters at this reap: the offline
            # waterfall diffs consecutive records to attribute gaps (e.g.
            # rework prefill between windows) without a second event kind.
            "cum_tokens": int(cum_tokens),
            "cum_prefill_tokens": int(cum_prefill_tokens),
            "cum_rework_prefill_tokens": int(cum_rework_prefill_tokens),
            "cum_preemptions": int(cum_preemptions),
        }
        if self.pool_layout:
            rec["kv_dtype"] = self.pool_layout.get("kv_dtype")
            rec["pool_bytes_per_block"] = self.pool_layout.get(
                "bytes_per_block"
            )
        if self.admission_snapshot_fn is not None:
            snap = self.admission_snapshot_fn()
            rec["admission_depth"] = int(snap.get("live_requests", 0))
            rec["admission_outstanding_tokens"] = int(
                snap.get("outstanding_tokens", 0)
            )
            if "max_queue_depth" in snap:
                rec["admission_depth_limit"] = int(snap["max_queue_depth"])
            if "max_outstanding_tokens" in snap:
                rec["admission_tokens_limit"] = int(
                    snap["max_outstanding_tokens"]
                )
        with self._lock:
            self._ring.append(rec)
            self.windows_sampled += 1
        if self._g_rows is not None:
            self._g_rows.set(rows)
            self._g_waiting.set(waiting)
            self._g_pool["live"].set(pool_live)
            self._g_pool["cold"].set(pool_cold)
            self._g_pool["free"].set(pool_free)
            self._h_occupancy.observe(
                rows / self.rows_capacity if self.rows_capacity else 0.0
            )
            self._h_slot_util.observe(
                tokens_committed / slot_tokens if slot_tokens else 0.0
            )
        if self.bus is not None:
            self.bus.emit("cap_window", **rec)
        return rec

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-n:]
