"""Cristian-style clock alignment between router and worker processes.

Every process has its own ``time.perf_counter`` epoch (an arbitrary
boot-relative zero), so a worker's span timestamps are meaningless in
the router's timeline until an offset is estimated. The estimator here
is the classic minimum-RTT filter over request/reply clock samples:

- the router stamps its own clock before sending an RPC (``t_send``)
  and after the reply lands (``t_recv``);
- the worker stamps ITS clock while building the reply (``t_remote``);
- assuming the remote stamp was taken near the midpoint of the round
  trip, ``offset = (t_send + t_recv) / 2 - t_remote`` maps remote time
  into local time, with the unavoidable error bounded by half the
  round-trip time (the stamp could have been taken anywhere between
  the request arriving and the reply leaving).

Samples taken during a congested round trip carry a large bound, so the
estimate is the MIN-RTT sample over a sliding window of recent
heartbeats: re-estimating every heartbeat tracks drift (perf_counter
rates differ slightly across hosts) while the window keeps one lucky
tight sample from pinning a stale offset forever. ``reset()`` discards
everything — called per connection generation, because a re-attach may
land on a different process with an unrelated epoch.

Pure arithmetic over caller-supplied timestamps: no sockets, no JAX,
fully deterministic under injected clocks (tier-1 unit-testable).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional, Tuple


class ClockSync:
    """Min-RTT offset estimator for one remote peer.

    ``window`` bounds how many recent samples compete for the estimate;
    it is the drift horizon — with heartbeats every ``h`` seconds the
    offset is never older than ``window * h``.
    """

    def __init__(self, window: int = 16) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        # (rtt_s, offset_s) most-recent-last; the estimate is the min-rtt
        # entry, ties broken toward the newest sample (drift tracking).
        self._samples: Deque[Tuple[float, float]] = deque(maxlen=self.window)
        self._n_observed = 0
        self._lock = threading.Lock()

    def observe(self, t_send: float, t_recv: float, t_remote: float) -> None:
        """Fold in one round trip. ``t_send``/``t_recv`` are LOCAL clock
        reads bracketing the RPC; ``t_remote`` is the peer's clock read
        from the reply. Samples with a non-positive RTT (a caller bug or
        a clock step mid-call) are discarded rather than poisoning the
        estimate."""
        rtt = t_recv - t_send
        if rtt < 0:
            return
        offset = (t_send + t_recv) / 2.0 - t_remote
        with self._lock:
            self._samples.append((rtt, offset))
            self._n_observed += 1

    def _best_locked(self) -> Optional[Tuple[float, float]]:
        best = None
        for rtt, offset in self._samples:  # newest-last wins ties
            if best is None or rtt <= best[0]:
                best = (rtt, offset)
        return best

    @property
    def n_samples(self) -> int:
        with self._lock:
            return self._n_observed

    @property
    def offset_s(self) -> Optional[float]:
        """Local = remote + offset; None until the first sample."""
        with self._lock:
            best = self._best_locked()
        return best[1] if best is not None else None

    @property
    def error_bound_s(self) -> Optional[float]:
        """Worst-case |true - estimated| offset: half the RTT of the
        sample the estimate came from."""
        with self._lock:
            best = self._best_locked()
        return best[0] / 2.0 if best is not None else None

    def to_local(self, t_remote: float) -> Optional[float]:
        """Map a remote perf_counter timestamp into the local timeline;
        None when no sample has been observed yet."""
        with self._lock:
            best = self._best_locked()
        if best is None:
            return None
        return t_remote + best[1]

    def reset(self) -> None:
        """Discard all samples (new connection generation: the peer —
        and therefore its clock epoch — may have been replaced)."""
        with self._lock:
            self._samples.clear()

    def snapshot(self) -> dict:
        with self._lock:
            best = self._best_locked()
            n = self._n_observed
        if best is None:
            return {"offset_s": None, "error_bound_s": None, "n_samples": n}
        return {
            "offset_s": best[1],
            "error_bound_s": best[0] / 2.0,
            "n_samples": n,
        }
