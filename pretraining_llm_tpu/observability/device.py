"""Device + compile telemetry: HBM occupancy samples and recompile events.

Two failure classes that are invisible in a loss/MFU stream:

  - HBM creep: fragmentation or a leaked buffer marching ``bytes_in_use``
    toward the ceiling until step N OOMs. ``DeviceTelemetry.sample`` reads
    ``Device.memory_stats()`` — a host-side runtime query against the
    allocator, NOT a device sync — per local device, at log boundaries only.
    Backends without the API (CPU, some plugins) return None and the sample
    is simply empty.

  - recompile storms: a shape leak (python int step in the carry, a
    data-dependent bucket) silently re-traces the step function, and MFU
    craters with no event to explain it. ``CompileWatcher`` registers a
    ``jax.monitoring`` duration listener for backend_compile events;
    compiles before ``mark_warm()`` are the expected initial jit, every one
    after becomes a ``recompile`` event on the bus with its compile seconds.

jax imports live inside methods: this module (and the offline analyzer that
imports the package) must stay importable without pulling in jax.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Iterator, List, Optional

# Fired once per XLA backend compilation (probed on jax 0.4.x; the watcher
# degrades to manual note_compile() calls if the name ever changes).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class DeviceTelemetry:
    """Per-device memory sampling onto the event bus."""

    def __init__(self, bus: Any = None) -> None:
        self.bus = bus

    def sample(self, step: Optional[int] = None) -> Dict[str, Dict[str, float]]:
        """One ``memory_stats`` read per local device; emits a
        ``device_memory`` event when any device reports. Returns
        ``{device_label: stats}`` (empty when unsupported)."""
        import jax

        per_device: Dict[str, Dict[str, float]] = {}
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            keep = {
                k: float(v)
                for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                and isinstance(v, (int, float))
            }
            if keep:
                per_device[f"{dev.platform}:{dev.id}"] = keep
        if per_device and self.bus is not None:
            worst = max(d.get("bytes_in_use", 0.0) for d in per_device.values())
            self.bus.emit(
                "device_memory",
                step=step,
                max_bytes_in_use=worst,
                devices=per_device,
            )
        return per_device


class CompileWatcher:
    """Counts backend compiles/seconds; post-warmup compiles become events.

    ``start`` registers the listener (idempotent), ``mark_warm`` draws the
    line between expected first-compile and anomalous recompile, ``stop``
    deactivates — unregistration uses a private jax hook when available,
    but the listener also self-gates on ``_active`` so a stale registration
    is harmless (jax has no public unregister).
    """

    def __init__(self, bus: Any = None) -> None:
        self.bus = bus
        self._lock = threading.Lock()
        self._active = False
        self._registered = False
        self._warm = False
        self.compiles = 0
        self.compile_s = 0.0
        self.recompiles = 0
        self.recompile_s = 0.0
        self._recompile_steps: List[Optional[int]] = []
        self._current_step: Optional[int] = None
        self._suppressed = 0

    # -- wiring --------------------------------------------------------

    def start(self) -> "CompileWatcher":
        self._active = True
        if self._registered:
            return self
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_duration_secs_listener(self._listener)
            self._registered = True
        except Exception:
            pass  # no monitoring API: note_compile() remains usable manually
        return self

    def stop(self) -> None:
        self._active = False
        if not self._registered:
            return
        try:
            from jax._src import monitoring as _monitoring

            _monitoring._unregister_event_duration_listener_by_callback(
                self._listener
            )
            self._registered = False
        except Exception:
            pass  # private API moved: _active gate keeps the stale hook inert

    def mark_warm(self, step: Optional[int] = None) -> None:
        """The initial jit is done; further compiles are recompiles."""
        self._warm = True
        self._current_step = step

    def at_step(self, step: int) -> None:
        """Label subsequent recompile events with the loop's position
        (called at log boundaries; compiles land between them)."""
        self._current_step = step

    @contextlib.contextmanager
    def suppress(self) -> Iterator[None]:
        """Treat compiles inside the block as expected (counted, no event).

        Known-first-time off-path programs — the eval loop's jit at the
        first eval boundary, a restore's device_put layout program — compile
        AFTER the train step warmed up; without this they'd masquerade as
        step-loop recompile storms. The hub wraps ``timed_event`` bodies in
        it, so only compiles landing on the bare step path classify as
        recompiles."""
        with self._lock:
            self._suppressed += 1
        try:
            yield
        finally:
            with self._lock:
                self._suppressed -= 1

    # -- accounting ----------------------------------------------------

    def _listener(self, name: str, dur: float, **kw: Any) -> None:
        if not self._active or name != _COMPILE_EVENT:
            return
        self.note_compile(dur)

    def note_compile(self, dur_s: float) -> None:
        """Record one backend compile (the listener body; public so tests
        and monitoring-less environments can feed it directly)."""
        with self._lock:
            self.compiles += 1
            self.compile_s += dur_s
            if not self._warm or self._suppressed:
                return
            self.recompiles += 1
            self.recompile_s += dur_s
            step = self._current_step
        if self.bus is not None:
            self.bus.emit("recompile", step=step, dur_s=dur_s)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return {
                "compiles": self.compiles,
                "compile_s": round(self.compile_s, 4),
                "recompiles": self.recompiles,
                "recompile_s": round(self.recompile_s, 4),
            }
