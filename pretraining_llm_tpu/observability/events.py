"""Structured run events: the spine of the observability subsystem.

An event is a flat JSON-serializable dict stamped with both clocks:

  ``t_wall``  epoch seconds — comparable ACROSS processes and relaunches
              (the goodput accountant orders multi-run streams by it);
  ``t_mono``  monotonic seconds — immune to NTP steps WITHIN a process
              (durations are always measured on this clock by the caller
              and shipped as an explicit ``dur_s`` field);
  ``seq``     per-bus emission counter — a total order for events landing
              inside the same wall-clock tick.

Duration-carrying events (``eval``, ``ckpt_save``, ``ckpt_restore``,
``rollback``, ``step_window``) are emitted at the END of the activity they
measure; the goodput fold relies on that convention.

The bus is deliberately tiny: ``emit`` appends one line to an optional JSONL
sink and calls the in-process subscribers (the live goodput accountant; a
test capture). Emission happens only at log boundaries and around off-path
work (eval/checkpoint/rollback), never per step — there is nothing here that
could touch a device. A lock makes ``emit`` safe from the watchdog thread.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# The vocabulary of run events. Producers outside this package (supervisor,
# tests) keep to this list so the offline analyzer can label everything.
EVENT_KINDS = (
    "run_start",      # train() entered: step=start step, total=target step
    "run_end",        # train() exiting: exit_reason + goodput/compile summary
    "step_window",    # a log window of pure step time: step, steps, dur_s
    "eval",           # one evaluate() call: step, dur_s, val_loss
    "ckpt_save",      # one checkpoint save: step, dur_s, async
    "ckpt_restore",   # one restore (resume or rollback): step, dur_s
    "rollback",       # anomaly rollback executed: from_step, to_step, dur_s
    "recompile",      # post-warmup backend compile: dur_s
    "wedge",          # watchdog fired: stalled_s
    "preempt",        # SIGTERM stop requested: step
    "relaunch",       # supervisor relaunched the child: attempt, backoff_s
    "failure",        # step loop raised: step, error
    "device_memory",  # HBM sample: per-device bytes_in_use/peak
    "fault_injected", # drill fault fired: kind, step
    # Serving-frontend request lifecycle (frontend/engine_loop.py). The
    # terminal kinds carry queue_wait_s/ttft_s/e2e_s + n_tokens, and every
    # req_* record carries trace_id when the request is traced, so the
    # event stream doubles as the per-request serving audit log and joins
    # against the Chrome-trace span tree in obs_report --slo.
    "req_submit",     # accepted past validation+admission: n_prompt, max_new
    "req_rejected",   # refused at admission: reason=busy|infeasible|invalid
    "req_done",       # generated to completion (HTTP 200)
    "req_cancelled",  # client cancelled / disconnected (HTTP 499)
    "req_expired",    # deadline passed mid-flight (HTTP 504)
    "req_error",      # engine failure or shutdown (HTTP 500)
    # Capacity observability (observability/capacity.py). One cap_window
    # record per reaped decode window (occupancy, pool split, admission
    # depth; t_dispatch_s/t_reap_s are perf_counter so offline interval
    # math stays on one clock); one decision record per scheduler action
    # that costs a request something (decision= one of DECISION_KINDS,
    # trace_id joins it to the req_* stream).
    "cap_window",     # per-window occupancy sample: rows, tokens, pool, queue
    "decision",       # scheduler decision: reject/shed/preempt/evict/reclaim
    # Serving fleet (frontend/router.py). Replica-scoped events carry a
    # ``replica`` index (replica-local req_* events carry it too, via the
    # router's tagging bus proxy); fleet_req_* events carry ``frid`` — the
    # router-level request id that stays stable across redrives, which is
    # what lets obs_report --fleet prove no accepted request was lost.
    "replica_state",      # lifecycle transition: replica, state, reason
    "redrive",            # in-flight failover: frid, from/to replica, committed tokens
    "brownout",           # fleet brownout entered/left: active, healthy, total
    "fleet_req_submit",   # router accepted a request: frid, replica, n_prompt
    "fleet_req_terminal", # router delivered a terminal: frid, status, redrives
    # Output-integrity sentinel (resilience/integrity.py + router). Probe
    # events carry the replica they exercised and whether the greedy
    # output matched the pinned reference; mismatch events are the
    # checksum detectors firing; quarantine is the sentinel's verdict
    # (the matching ``quarantine`` decision carries the probe trace_id).
    # Out-of-process workers (frontend/worker.py + remote_replica.py).
    # worker_* events carry the replica index and, where known, the
    # worker pid — obs_report --fleet joins a worker death (worker_exit
    # with clean=false / worker_conn_lost) to the redrives and the
    # replica_state recovery that followed it.
    "worker_spawn",       # worker process launched: replica, pid, port, reason
    "worker_exit",        # worker stopped: replica, pid, clean, returncode
    "worker_conn_lost",   # parent<->worker socket died: replica, reason
    "rpc_retry",          # idempotent worker RPC retried: replica, op, attempt
    # Rolling weight upgrades (Router.upgrade_replica). The vetting
    # verdict events are what proves traffic never reached an unvetted
    # checkpoint: upgrade_vetted precedes the replica_state active
    # transition, and a refusal carries the probe-divergence reason.
    "upgrade_start",        # replica drained for upgrade: replica, generation
    "upgrade_vetted",       # new weights passed golden probes: replica, detail
    "upgrade_refused",      # probes failed; upgrade rejected: replica, reason
    "upgrade_rolled_back",  # old weights restored (or ejected): replica, restored
    # Disaggregated prefill/decode (frontend/kv_transfer.py + router).
    # kv_migrate records each prefill-tier page push (frid, from/to
    # replica, pages, bytes, saved_tokens); a nonzero reject count also
    # emits kv_migration_reject with the decode worker's refusal reason
    # (checksum_mismatch/capacity/stale fence) — the proof that corrupt
    # pages were dropped rather than served.
    "kv_migrate",           # KV pages migrated prefill->decode: frid, pages, bytes
    "kv_migration_reject",  # decode worker refused migrated pages: replica, reason
    "fault_fired",               # armed corruption actually mutated engine state
    "integrity_probe",           # probe completed: replica, ok, probe, n_tokens
    "integrity_quarantine",      # replica pulled from service: replica, reason
    "integrity_kv_mismatch",     # cached KV page failed verify-on-acquire: block
    "integrity_weight_mismatch", # live weight fingerprint drifted: replica
    "integrity_invalid_token",   # out-of-vocab token id reached reap: rid, token
    # Live SLO engine (observability/slo.py). One record per alert
    # transition: state="firing" carries alert_id + burn rates over the
    # rule's short/long windows (and trigger_trace_id when the tipping
    # request was traced — the join to the req_* stream); the matching
    # state="resolved" record reuses the SAME alert_id, so the pair
    # brackets the incident in the replayable timeline. Every firing
    # also lands an ``slo_alert`` decision with the same alert_id.
    "slo_alert",      # burn-rate alert transition: alert_id, state, rule
)


def sanitize_record(record: Dict[str, Any]) -> Dict[str, Any]:
    """Make a record strictly-JSON serializable: non-finite floats become
    ``null`` plus a ``<key>_nonfinite`` string ('nan' | 'inf' | '-inf').

    Bare ``NaN``/``Infinity`` tokens (json.dumps' default) are invalid JSON
    and corrupt a JSONL stream exactly when it matters most — the anomaly
    detector logging a NaN loss. Downstream parsers get a valid line AND
    keep the information.
    """
    out: Dict[str, Any] = {}
    for key, val in record.items():
        if isinstance(val, float) and not math.isfinite(val):
            out[key] = None
            out[key + "_nonfinite"] = repr(val)  # 'nan' | 'inf' | '-inf'
        else:
            out[key] = val
    return out


def json_line(record: Dict[str, Any]) -> str:
    """One strict-JSON line (no trailing newline) for a JSONL sink."""
    return json.dumps(sanitize_record(record), allow_nan=False)


class EventBus:
    """Append-only run-event stream: JSONL sink + in-process subscribers.

    ``jsonl_path=""`` keeps the bus in-memory only (subscribers still fire).
    Like MetricsLogger, the file handle reopens on demand after ``close`` so
    the trainer can release the fd on every exit path while repeated
    ``train()`` calls on one Trainer keep appending.
    """

    def __init__(
        self,
        jsonl_path: str = "",
        *,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self._path = jsonl_path
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._clock = clock
        self._wall = wall
        self._subs: List[Callable[[Dict[str, Any]], None]] = []
        self._seq = 0
        self._lock = threading.Lock()

    def __enter__(self) -> "EventBus":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        self._subs.append(fn)

    def emit(self, kind: str, *, step: Optional[int] = None, **fields: Any) -> Dict[str, Any]:
        """Record one event; returns the full stamped record.

        Unknown kinds are allowed (forward compatibility for out-of-package
        producers) — EVENT_KINDS is the documented vocabulary, not a gate.
        """
        with self._lock:
            record: Dict[str, Any] = {
                "event": kind,
                "seq": self._seq,
                "t_wall": self._wall(),
                "t_mono": self._clock(),
            }
            self._seq += 1
            if step is not None:
                record["step"] = int(step)
            record.update(fields)
            if self._file is None and self._path:
                self._file = open(self._path, "a")
            if self._file is not None:
                self._file.write(json_line(record) + "\n")
                self._file.flush()
        # Subscribers run outside the lock: a subscriber that emits (e.g. a
        # telemetry sampler reacting to a window) must not deadlock.
        for fn in self._subs:
            fn(record)
        return record

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
