"""Prometheus textfile exporter — no server, no client library.

Writes the node-exporter "textfile collector" format: a flat file of
``# TYPE`` headers and ``name{labels} value`` samples that node_exporter
(or any file-scraping agent) picks up. One atomic replace per write, so a
scraper never reads a torn file. This is the lowest-dependency way to get
live run metrics (loss, MFU, goodput, HBM) onto a dashboard from a TPU VM:
no port to open, no endpoint to keep alive while the host is busy driving
the chips.
"""

from __future__ import annotations

import math
import os
import re
import time
from typing import Any, Dict, Mapping, Optional

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(key: str, prefix: str) -> str:
    name = prefix + _NAME_FIX.sub("_", key)
    if not _NAME_OK.match(name):
        name = "_" + name
    return name


def _format_value(val: float) -> str:
    if math.isnan(val):
        return "NaN"
    if math.isinf(val):
        return "+Inf" if val > 0 else "-Inf"
    return repr(float(val))


def _format_labels(labels: Optional[Mapping[str, Any]]) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in sorted(labels.items()):
        sval = str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
        parts.append(f'{_NAME_FIX.sub("_", k)}="{sval}"')
    return "{" + ",".join(parts) + "}"


def prometheus_lines(
    metrics: Mapping[str, Any],
    *,
    prefix: str = "pllm_",
    labels: Optional[Mapping[str, Any]] = None,
    timestamp: Optional[float] = None,
) -> str:
    """Render numeric metrics as Prometheus text exposition (gauges).

    Non-numeric values are skipped (the textfile format has no strings);
    bools export as 0/1. Keys are sanitized into valid metric names.
    """
    label_str = _format_labels(labels)
    ts = ""
    if timestamp is not None:
        ts = f" {int(timestamp * 1000)}"
    lines = []
    for key in sorted(metrics):
        val = metrics[key]
        if isinstance(val, bool):
            val = float(val)
        if not isinstance(val, (int, float)):
            continue
        name = _metric_name(key, prefix)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{label_str} {_format_value(float(val))}{ts}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_textfile(
    path: str,
    metrics: Mapping[str, Any],
    *,
    prefix: str = "pllm_",
    labels: Optional[Mapping[str, Any]] = None,
    stamp: bool = True,
) -> str:
    """Atomically write the textfile; returns the path.

    ``stamp`` adds a ``<prefix>last_write_seconds`` gauge so dashboards can
    alert on a run that stopped updating (the watchdog's out-of-band twin).
    """
    body = prometheus_lines(metrics, prefix=prefix, labels=labels)
    if stamp:
        body += prometheus_lines(
            {"last_write_seconds": time.time()}, prefix=prefix, labels=labels
        )
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)
    return path
