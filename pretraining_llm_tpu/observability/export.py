"""Prometheus textfile exporter — no server, no client library.

Writes the node-exporter "textfile collector" format: a flat file of
``# TYPE`` headers and ``name{labels} value`` samples that node_exporter
(or any file-scraping agent) picks up. One atomic replace per write, so a
scraper never reads a torn file. This is the lowest-dependency way to get
live run metrics (loss, MFU, goodput, HBM) onto a dashboard from a TPU VM:
no port to open, no endpoint to keep alive while the host is busy driving
the chips.
"""

from __future__ import annotations

import math
import os
import re
import time
from typing import Any, Dict, Mapping, Optional

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(key: str, prefix: str) -> str:
    name = prefix + _NAME_FIX.sub("_", key)
    if not _NAME_OK.match(name):
        name = "_" + name
    return name


def _format_value(val: float) -> str:
    if math.isnan(val):
        return "NaN"
    if math.isinf(val):
        return "+Inf" if val > 0 else "-Inf"
    return repr(float(val))


def _format_labels(labels: Optional[Mapping[str, Any]]) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in sorted(labels.items()):
        sval = str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
        parts.append(f'{_NAME_FIX.sub("_", k)}="{sval}"')
    return "{" + ",".join(parts) + "}"


def prometheus_lines(
    metrics: Mapping[str, Any],
    *,
    prefix: str = "pllm_",
    labels: Optional[Mapping[str, Any]] = None,
    timestamp: Optional[float] = None,
    types: Optional[Mapping[str, str]] = None,
) -> str:
    """Render numeric metrics as Prometheus text exposition.

    Non-numeric values are skipped (the textfile format has no strings);
    bools export as 0/1. Keys are sanitized into valid metric names.

    ``types`` maps input keys to ``"counter"`` or ``"gauge"`` (default
    gauge — the historical behavior). A key typed counter whose name does
    not already end ``_total`` is renamed ``<name>_total`` so the output
    satisfies the Prometheus counter-naming contract; full typed series
    (histograms, labeled children) live in metrics.MetricsRegistry — this
    stays the flat-dict renderer.
    """
    label_str = _format_labels(labels)
    ts = ""
    if timestamp is not None:
        ts = f" {int(timestamp * 1000)}"
    lines = []
    for key in sorted(metrics):
        val = metrics[key]
        if isinstance(val, bool):
            val = float(val)
        if not isinstance(val, (int, float)):
            continue
        kind = (types or {}).get(key, "gauge")
        if kind not in ("counter", "gauge"):
            raise ValueError(f"unsupported series type {kind!r} for {key!r}")
        name = _metric_name(key, prefix)
        if kind == "counter" and not name.endswith("_total"):
            name += "_total"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{label_str} {_format_value(float(val))}{ts}")
    return "\n".join(lines) + ("\n" if lines else "")


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?"
    r" (?P<value>[^ ]+)(?: [0-9]+)?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_sample(line: str):
    m = _SAMPLE_RE.match(line)
    if m is None:
        return None
    labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
    raw = m.group("value")
    try:
        value = float(raw)
    except ValueError:
        return None
    return m.group("name"), labels, value


def _series_base(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def lint_exposition(text: str) -> "list[str]":
    """In-tree Prometheus exposition lint; returns a list of problems
    (empty = clean). CI runs this over the live ``/metrics`` body so the
    format is a checked contract, not a convention. Checks:

      - every sample line parses (name, optional labels, float value);
      - at most one ``# TYPE`` per metric name, emitted before its samples;
      - counters end ``_total`` and gauges don't claim to;
      - histogram children are complete and coherent per label set:
        ``_bucket`` series cumulative and non-decreasing in ``le`` order,
        a ``+Inf`` bucket present and equal to ``_count``, ``_sum``/
        ``_count`` present;
      - no sample under a name that was never typed when any name was.
    """
    problems: list[str] = []
    types: Dict[str, str] = {}
    seen_samples: Dict[str, bool] = {}
    hist: Dict[str, Dict[str, Dict[str, Any]]] = {}

    def _label_key(labels: Mapping[str, str]) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ):
                problems.append(f"line {lineno}: malformed TYPE line {line!r}")
                continue
            name, kind = parts[2], parts[3]
            if name in types:
                problems.append(f"line {lineno}: duplicate TYPE for {name}")
            if seen_samples.get(name):
                problems.append(
                    f"line {lineno}: TYPE for {name} after its samples"
                )
            types[name] = kind
            if kind == "counter" and not name.endswith("_total"):
                problems.append(
                    f"line {lineno}: counter {name} does not end '_total'"
                )
            if kind == "gauge" and name.endswith("_total"):
                problems.append(
                    f"line {lineno}: gauge {name} ends '_total' (counter name)"
                )
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        parsed = _parse_sample(line)
        if parsed is None:
            problems.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name, labels, value = parsed
        base = _series_base(name)
        typed = types.get(name) or types.get(base)
        if types and typed is None:
            problems.append(f"line {lineno}: sample {name} has no TYPE")
        seen_samples[name] = True
        seen_samples[base] = True
        if types.get(base) == "histogram":
            slot = hist.setdefault(base, {}).setdefault(
                _label_key({k: v for k, v in labels.items() if k != "le"}),
                {"buckets": [], "sum": None, "count": None},
            )
            if name.endswith("_bucket"):
                if "le" not in labels:
                    problems.append(
                        f"line {lineno}: {name} bucket without le label"
                    )
                else:
                    slot["buckets"].append((labels["le"], value))
            elif name.endswith("_sum"):
                slot["sum"] = value
            elif name.endswith("_count"):
                slot["count"] = value
            else:
                problems.append(
                    f"line {lineno}: stray sample {name} under histogram {base}"
                )
    for base, children in hist.items():
        for label_key, slot in children.items():
            where = f"{base}{{{label_key}}}" if label_key else base
            buckets = slot["buckets"]
            if not buckets:
                problems.append(f"{where}: histogram with no _bucket series")
                continue
            if slot["sum"] is None:
                problems.append(f"{where}: histogram missing _sum")
            if slot["count"] is None:
                problems.append(f"{where}: histogram missing _count")
            les = [le for le, _ in buckets]
            if les[-1] != "+Inf":
                problems.append(f"{where}: last bucket le={les[-1]!r}, not +Inf")
            try:
                bounds = [float("inf") if le == "+Inf" else float(le) for le in les]
            except ValueError:
                problems.append(f"{where}: unparseable le value in {les}")
                continue
            if bounds != sorted(bounds):
                problems.append(f"{where}: bucket le values not ascending")
            counts = [c for _, c in buckets]
            if any(b > a for a, b in zip(counts[1:], counts)):
                problems.append(f"{where}: bucket counts not cumulative")
            if slot["count"] is not None and counts and counts[-1] != slot["count"]:
                problems.append(
                    f"{where}: +Inf bucket {counts[-1]} != _count {slot['count']}"
                )
    return problems


def write_textfile(
    path: str,
    metrics: Mapping[str, Any],
    *,
    prefix: str = "pllm_",
    labels: Optional[Mapping[str, Any]] = None,
    stamp: bool = True,
    registry: Optional[Any] = None,
) -> str:
    """Atomically write the textfile; returns the path.

    ``stamp`` adds a ``<prefix>last_write_seconds`` gauge so dashboards can
    alert on a run that stopped updating (the watchdog's out-of-band twin).
    ``registry`` (observability.metrics.MetricsRegistry) renders its typed
    series first, with ``metrics`` merged in as plain gauges — the path by
    which training metrics and the typed registry share one exposition.
    """
    if registry is not None:
        body = registry.render(extra_gauges=metrics)
        prefix = registry.prefix or prefix
    else:
        body = prometheus_lines(metrics, prefix=prefix, labels=labels)
    if stamp:
        body += prometheus_lines(
            {"last_write_seconds": time.time()}, prefix=prefix, labels=labels
        )
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)
    return path
