"""Goodput accounting: fold the run-event stream into a wall-clock budget.

Peak-window MFU says how fast the step loop runs *while it runs*; goodput
says how much of the run's wall-clock was that loop making NEW progress.
The decomposition:

  productive  step time spent on steps the run had not reached before —
              measured per ``step_window`` event, split by a step
              high-water mark;
  replay      step time re-running steps at or below the high-water mark
              (the poison window after a rollback, or the resume gap after
              a relaunch — compute burned to stand still);
  eval        evaluate() calls;
  checkpoint  checkpoint saves;
  restore     checkpoint restores + the whole rollback procedure (restore,
              RNG skip-ahead, feed teardown);
  idle        gaps between a run's last event and the next ``run_start``
              (supervisor backoff, scheduler queue time, relaunch exec);
  other       everything unaccounted: compile/init time before the first
              window, host overhead between events. Computed as the
              remainder, so the categories sum to total wall-clock exactly.

The high-water-mark rule is what makes rollbacks visible: a rolled-back run
re-earns steps it already had, so those windows are replay, not progress —
``goodput = productive / total`` drops accordingly.

Events may come from several processes/relaunches (trainer + supervisor
JSONLs); ``fold`` orders them by wall time, the one clock they share.
Durations ride inside events (``dur_s``, measured on each producer's
monotonic clock), so cross-host NTP skew only smears category BOUNDARIES,
never the measured durations themselves.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

CATEGORIES = (
    "productive", "replay", "eval", "checkpoint", "restore", "idle", "other",
)

# Event kind -> whole-duration category (events whose dur_s lands in one
# bucket unsplit; step_window is handled specially by the high-water mark).
_DUR_CATEGORY = {
    "eval": "eval",
    "ckpt_save": "checkpoint",
    "ckpt_restore": "restore",
    "rollback": "restore",
}


class GoodputAccountant:
    """Streaming fold over run events; also usable offline via ``fold``."""

    def __init__(self) -> None:
        self._totals: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._hwm: Optional[int] = None  # highest step ever completed
        self._first_wall: Optional[float] = None
        self._last_wall: Optional[float] = None
        self._in_run = False
        self.runs = 0
        self.rollbacks = 0
        self.recompiles = 0
        self.exit_reason: Optional[str] = None

    # -- streaming interface (EventBus subscriber) ---------------------

    def observe(self, event: Dict[str, Any]) -> None:
        t = event.get("t_wall")
        if not isinstance(t, (int, float)):
            return  # not a stamped event record
        kind = event.get("event")
        if self._first_wall is None:
            self._first_wall = t
        if kind == "run_start":
            self.runs += 1
            # The gap back to the previous run's last sign of life is idle
            # time (supervisor backoff, queueing, process startup).
            if self._last_wall is not None and not self._in_run:
                self._totals["idle"] += max(0.0, t - self._last_wall)
            self._in_run = True
            step = event.get("step")
            if isinstance(step, int):
                self._hwm = step if self._hwm is None else max(self._hwm, step)
        elif kind == "run_end":
            self._in_run = False
            reason = event.get("exit_reason")
            if isinstance(reason, str):
                self.exit_reason = reason
        elif kind == "step_window":
            self._observe_window(event)
        elif kind == "rollback":
            self.rollbacks += 1
            self._add_dur(kind, event)
        elif kind == "recompile":
            self.recompiles += 1
        elif kind in _DUR_CATEGORY:
            self._add_dur(kind, event)
        self._last_wall = max(self._last_wall or t, t)

    def _add_dur(self, kind: str, event: Dict[str, Any]) -> None:
        dur = event.get("dur_s")
        if isinstance(dur, (int, float)) and dur > 0:
            self._totals[_DUR_CATEGORY[kind]] += float(dur)

    def _observe_window(self, event: Dict[str, Any]) -> None:
        dur = event.get("dur_s")
        steps = event.get("steps")
        end_step = event.get("step")
        if not (isinstance(dur, (int, float)) and dur > 0):
            return
        if not (isinstance(steps, (int, float)) and steps > 0):
            self._totals["other"] += float(dur)
            return
        if isinstance(end_step, int) and self._hwm is not None:
            # Steps past the high-water mark are new ground; the rest of
            # the window re-ran already-earned steps (post-rollback replay
            # or post-relaunch catch-up).
            new = min(float(steps), float(max(0, end_step - self._hwm)))
        else:
            new = float(steps)
        frac = new / float(steps)
        self._totals["productive"] += float(dur) * frac
        self._totals["replay"] += float(dur) * (1.0 - frac)
        if isinstance(end_step, int):
            self._hwm = end_step if self._hwm is None else max(self._hwm, end_step)

    # -- views ---------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Decomposition + goodput fraction over the observed stream.

        ``other`` is the remainder, so the categories sum to ``total_s``
        exactly (unless explicit durations over-count total wall time —
        then ``accounting_error_s`` carries the overshoot instead of a
        negative bucket).
        """
        totals = dict(self._totals)
        total = 0.0
        if self._first_wall is not None and self._last_wall is not None:
            total = max(0.0, self._last_wall - self._first_wall)
        explicit = sum(v for k, v in totals.items() if k != "other") + totals["other"]
        remainder = total - explicit
        error = 0.0
        if remainder >= 0:
            totals["other"] += remainder
        else:
            error = -remainder
        return {
            "total_s": total,
            "goodput": (totals["productive"] / total) if total > 0 else 0.0,
            "categories": totals,
            "accounting_error_s": error,
            "runs": self.runs,
            "rollbacks": self.rollbacks,
            "recompiles": self.recompiles,
            "max_step": self._hwm,
            "exit_reason": self.exit_reason,
        }

    @classmethod
    def fold(cls, events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
        """Offline: order a (possibly multi-file) stream by wall time and
        fold it. Stable sort keeps same-tick events in file order."""
        acc = cls()
        stamped: List[Dict[str, Any]] = [
            e for e in events if isinstance(e.get("t_wall"), (int, float))
        ]
        for event in sorted(stamped, key=lambda e: e["t_wall"]):
            acc.observe(event)
        return acc.summary()
