"""ObservabilityHub: the one handle the trainer (and scripts) wire in.

Composes the event bus, span recorder, goodput accountant, device telemetry
and compile watcher behind a small surface shaped around the trainer's
boundaries:

    hub.start_run(start_step, total)        train() entered
    hub.mark_warm(step)                     first step done (compile is over)
    hub.on_log_boundary(step, window, m)    once per log interval
    hub.timed_event(kind, step=...)         context manager: span + event
                                            with dur_s around off-path work
    hub.end_run(exit_reason)                train() exiting

File sinks (events JSONL, Chrome trace, Prometheus textfile) are config-
gated and host0-only; the in-memory pieces (bus subscribers, goodput,
compile counters) always run — they are a few dict updates per LOG BOUNDARY,
nothing per step, and never touch a device.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

from pretraining_llm_tpu.observability import spans as spans_mod
from pretraining_llm_tpu.observability.device import CompileWatcher, DeviceTelemetry
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import write_textfile
from pretraining_llm_tpu.observability.goodput import GoodputAccountant
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder


class ObservabilityHub:
    def __init__(self, cfg: Any, *, is_host0: bool = True) -> None:
        self.cfg = cfg
        self.is_host0 = is_host0
        self.bus = EventBus(cfg.events_path if is_host0 else "")
        self.spans = SpanRecorder()
        # Adopt the module default slot so layers without a hub reference
        # (the checkpoint module's spans) land in the same export.
        spans_mod.set_recorder(self.spans)
        self.goodput = GoodputAccountant()
        self.bus.subscribe(self.goodput.observe)
        self.device = DeviceTelemetry(self.bus)
        self.compile_watcher: Optional[CompileWatcher] = (
            CompileWatcher(self.bus) if cfg.compile_telemetry else None
        )
        self._boundaries = 0
        # Typed registry behind the textfile export: the flat per-boundary
        # metrics still ride along as gauges, but the step-window latency
        # becomes a real histogram and the span-recorder drop count a real
        # counter — same module the serving gateway's /metrics uses.
        self.registry = MetricsRegistry(prefix="pllm_")
        self._h_window = self.registry.histogram(
            "step_window_seconds", "wall seconds per log window")
        self._c_dropped = self.registry.counter(
            "spans_dropped_total", "span-recorder events lost to saturation")
        self._dropped_seen = 0

    # -- run lifecycle -------------------------------------------------

    def start_run(self, start_step: int, total: int) -> None:
        if self.compile_watcher is not None:
            self.compile_watcher.start()
        self.bus.emit("run_start", step=start_step, total=total)

    def mark_warm(self, step: int) -> None:
        """First step completed: the initial jit compile is behind us; any
        later backend compile is a recompile worth an event."""
        if self.compile_watcher is not None:
            self.compile_watcher.mark_warm(step)

    def end_run(self, exit_reason: str, **fields: Any) -> Dict[str, Any]:
        """Emit ``run_end`` with the goodput + compile summary, flush the
        file sinks, detach the compile listener. Returns the summary."""
        summary = self.goodput.summary()
        record: Dict[str, Any] = {
            "exit_reason": exit_reason,
            "goodput": summary["goodput"],
            "goodput_categories_s": {
                k: round(v, 4) for k, v in summary["categories"].items()
            },
            "total_s": round(summary["total_s"], 4),
            "rollbacks": summary["rollbacks"],
            **fields,
        }
        if self.compile_watcher is not None:
            record["compile"] = self.compile_watcher.summary()
            self.compile_watcher.stop()
        record["spans"] = {
            name: {"count": agg["count"], "total_s": round(agg["total_s"], 4)}
            for name, agg in sorted(self.spans.summary().items())
        }
        self.bus.emit("run_end", **record)
        if self.is_host0 and self.cfg.spans_path:
            try:
                self.spans.export(self.cfg.spans_path)
            except OSError:
                pass  # a full disk must not mask the run's own exit path
        self._sync_dropped()
        self._write_prometheus({"goodput": summary["goodput"]})
        self.bus.close()
        return record

    # -- per-boundary work ---------------------------------------------

    def on_log_boundary(
        self,
        step: int,
        window: Dict[str, float],
        metrics: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, float]:
        """Once per log interval: emit the window event, run the interval
        samplers, export Prometheus. Returns extra metrics (goodput) for
        the caller to merge into its log record."""
        self._boundaries += 1
        if self.compile_watcher is not None:
            self.compile_watcher.at_step(step)
        if window.get("window_s"):
            self.bus.emit(
                "step_window",
                step=step,
                steps=int(window.get("window_steps", 0)),
                dur_s=window["window_s"],
            )
            self._h_window.observe(window["window_s"])
        self._sync_dropped()
        interval = self.cfg.device_memory_interval
        if interval > 0 and self._boundaries % interval == 0:
            self.device.sample(step)
        extra = {"goodput": self.goodput.summary()["goodput"]}
        if metrics is not None:
            merged = dict(metrics)
            merged.update(extra)
            merged["step"] = step
            self._write_prometheus(merged)
        return extra

    @contextlib.contextmanager
    def suppressed_compiles(self) -> Iterator[None]:
        """Compiles inside the block are expected first-time programs (a
        rollback restore's device_put layouts), not step-loop recompiles."""
        cm = (
            self.compile_watcher.suppress()
            if self.compile_watcher is not None
            else contextlib.nullcontext()
        )
        with cm:
            yield

    @contextlib.contextmanager
    def timed_event(self, kind: str, *, step: Optional[int] = None, **fields: Any) -> Iterator[Dict[str, Any]]:
        """Span + end-of-activity event with measured ``dur_s`` around a
        block of off-path host work. The yielded dict lets the body attach
        result fields (e.g. val_loss) to the event."""
        out: Dict[str, Any] = dict(fields)
        t0 = time.perf_counter()
        suppress = (
            self.compile_watcher.suppress()
            if self.compile_watcher is not None
            else contextlib.nullcontext()
        )
        try:
            # Off-path work compiling its own program (the eval loop's first
            # jit) is expected — suppress() keeps it out of the recompile
            # classification.
            with suppress, self.spans.span(kind):
                yield out
        finally:
            self.bus.emit(kind, step=step, dur_s=time.perf_counter() - t0, **out)

    # ------------------------------------------------------------------

    def _sync_dropped(self) -> None:
        """Fold the recorder's drop count into the counter (a counter can
        only be advanced, so track the delta since last sync)."""
        dropped = self.spans.dropped
        if dropped > self._dropped_seen:
            self._c_dropped.inc(dropped - self._dropped_seen)
            self._dropped_seen = dropped

    def _write_prometheus(self, metrics: Dict[str, Any]) -> None:
        if not (self.is_host0 and self.cfg.prometheus_path):
            return
        try:
            write_textfile(
                self.cfg.prometheus_path, metrics, registry=self.registry
            )
        except OSError:
            pass  # metrics export must never take down the run
