"""Typed live metrics: counters, gauges, and log-bucketed histograms.

The textfile exporter (export.py) renders a flat ``{name: value}`` dict —
every series becomes ``# TYPE ... gauge``, which is wrong for anything
monotonic (Prometheus clients cannot ``rate()`` a gauge safely across
restarts) and cannot express a latency distribution at all. This module is
the typed half: a small registry of

  Counter     monotonically increasing; names MUST end ``_total``
              (enforced — the Prometheus naming contract, not a style nit);
  Gauge       a value that goes both ways (queue depth, EWMA);
  Histogram   fixed log-spaced buckets with ``_bucket{le=...}``/``_sum``/
              ``_count`` exposition and a quantile estimator, so TTFT/TPOT
              tails are live at ``/metrics`` instead of only in offline
              nearest-rank reports.

Hot-path cost model: one ``observe``/``inc`` is a bisect over ~20 floats
plus a few attribute writes under a per-metric lock — no allocation on the
histogram path, no global registry lock after creation, and nothing here
can ever touch a device. The serving engine records per-WINDOW (not
per-token) histograms and per-token counter increments; both are noise
next to a device dispatch.

Label support is deliberately minimal: labels are fixed per series at
creation (``registry.counter("http_responses_total", code="200")``), and
the registry keys series by (name, labels) so one ``# TYPE`` header covers
every labeled child, as the exposition format requires.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_right
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from pretraining_llm_tpu.observability.export import (
    _format_labels,
    _format_value,
    _metric_name,
)

# Default latency buckets: log-spaced, factor 2, 100us .. ~105s. 21 finite
# bounds cover everything from a per-token host callback to a queue wait
# that already blew any SLO; the +Inf bucket is implicit.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    1e-4 * (2.0 ** i) for i in range(21)
)


def log_buckets(lo: float, hi: float, *, factor: float = 2.0) -> Tuple[float, ...]:
    """Log-spaced bucket bounds from ``lo`` up to at least ``hi``."""
    if lo <= 0 or hi <= lo or factor <= 1.0:
        raise ValueError(f"need 0 < lo < hi and factor > 1, got {lo}, {hi}, {factor}")
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


class Counter:
    """Monotonic counter. ``inc`` only accepts non-negative deltas."""

    __slots__ = ("name", "labels", "help", "_value", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str], help: str = "") -> None:
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        return [(self.name, self.labels, self.value)]


class Gauge:
    """A value that can go both ways; ``set``/``inc``/``dec``."""

    __slots__ = ("name", "labels", "help", "_value", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str], help: str = "") -> None:
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        return [(self.name, self.labels, self.value)]


class Histogram:
    """Fixed-bucket histogram with Prometheus cumulative exposition.

    ``bounds`` are the finite upper bounds (sorted ascending); the +Inf
    overflow bucket is implicit. ``observe`` is the hot path: one bisect +
    three writes under the per-metric lock. Values below the first bound
    (including 0 and any negative clock artifact) land in the first
    bucket — a latency can never be lost to a bounds check.
    """

    __slots__ = (
        "name", "labels", "help", "bounds", "_counts", "_sum", "_count",
        "_min", "_max", "_lock",
    )

    def __init__(
        self,
        name: str,
        labels: Mapping[str, str],
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
    ) -> None:
        bounds = tuple(buckets if buckets is not None else DEFAULT_LATENCY_BUCKETS)
        if not bounds or list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram {name}: bounds must be sorted and unique, got {bounds}")
        if any(not math.isfinite(b) for b in bounds):
            raise ValueError(f"histogram {name}: bounds must be finite (+Inf is implicit)")
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect_right(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
            }

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile (q in [0, 1]) from the buckets: find the
        bucket holding the target rank, interpolate linearly inside it, and
        clamp to the observed min/max so the estimate never leaves the data
        range. The error bound is the width of the bucket the true value
        fell in — the property the bucket-vs-nearest-rank test checks."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            counts = list(self._counts)
            n = self._count
            vmin, vmax = self._min, self._max
        if n == 0:
            return float("nan")
        target = max(1, math.ceil(q * n))
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else min(vmin, self.bounds[0])
                hi = self.bounds[i] if i < len(self.bounds) else vmax
                lo = max(lo, vmin)
                hi = min(hi, vmax) if hi >= lo else lo
                frac = (target - cum) / c
                return min(max(lo + frac * (hi - lo), vmin), vmax)
            cum += c
        return vmax  # unreachable unless counts were mutated mid-iteration

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        snap = self.snapshot()
        out: List[Tuple[str, Dict[str, str], float]] = []
        cum = 0
        for bound, c in zip(snap["bounds"], snap["counts"]):
            cum += c
            out.append(
                (self.name + "_bucket", {**self.labels, "le": _le_str(bound)}, float(cum))
            )
        out.append(
            (self.name + "_bucket", {**self.labels, "le": "+Inf"}, float(snap["count"]))
        )
        out.append((self.name + "_sum", dict(self.labels), snap["sum"]))
        out.append((self.name + "_count", dict(self.labels), float(snap["count"])))
        return out


def _le_str(bound: float) -> str:
    """Canonical ``le`` label value: integral bounds render without the
    trailing .0 (Prometheus convention), others as repr."""
    f = float(bound)
    return str(int(f)) if f == int(f) else repr(f)


_RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")


class MetricsRegistry:
    """Get-or-create registry of typed series; renders valid exposition.

    ``prefix`` is prepended to every metric name at registration (one
    registry per exposition namespace: ``pllm_serving_`` for the gateway,
    ``pllm_`` for training). Series are keyed by (name, labels): the same
    call site gets the same object back, and distinct label sets under one
    name share a single ``# TYPE`` header at render time.

    ``const_labels`` are merged into every series registered here (call-site
    labels win on collision). This is how a fleet of engine replicas shares
    one metric vocabulary without stomping each other: each replica gets its
    own registry carrying ``{"replica": "i"}``, the SAME registration code
    runs unchanged inside each, and ``render_merged`` joins the registries
    into one exposition where the label tells the series apart.
    """

    def __init__(
        self,
        prefix: str = "",
        const_labels: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self.prefix = prefix
        self.const_labels = {
            k: str(v) for k, v in (const_labels or {}).items()
        }
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}
        self._kinds: Dict[str, str] = {}  # name -> counter|gauge|histogram
        self._helps: Dict[str, str] = {}

    def _get(self, kind: str, cls: Any, name: str, help: str, labels: Dict[str, str], **kw: Any) -> Any:
        full = _metric_name(name, self.prefix)
        if self.const_labels:
            labels = {**self.const_labels, **labels}
        key = (full, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            existing_kind = self._kinds.get(full)
            if existing_kind is not None and existing_kind != kind:
                raise ValueError(
                    f"metric {full} already registered as {existing_kind}, "
                    f"requested {kind}"
                )
            m = self._series.get(key)
            if m is None:
                m = cls(full, {k: str(v) for k, v in labels.items()}, help=help, **kw)
                self._series[key] = m
                self._kinds[full] = kind
                if help:
                    self._helps[full] = help
            return m

    def counter(self, name: str, help: str = "", **labels: Any) -> Counter:
        if not name.endswith("_total"):
            raise ValueError(
                f"counter names must end '_total' (Prometheus counter "
                f"naming contract), got {name!r}"
            )
        return self._get("counter", Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels: Any) -> Gauge:
        return self._get("gauge", Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
        **labels: Any,
    ) -> Histogram:
        if name.endswith(_RESERVED_SUFFIXES) or name.endswith("_total"):
            raise ValueError(
                f"histogram name {name!r} collides with a generated series "
                f"suffix (_bucket/_sum/_count) or the counter suffix"
            )
        return self._get("histogram", Histogram, name, help, labels, buckets=buckets)

    # -- exposition ---------------------------------------------------------

    def render(self, extra_gauges: Optional[Mapping[str, float]] = None) -> str:
        """Full Prometheus text exposition: ``# HELP``/``# TYPE`` once per
        metric name, then every labeled sample. ``extra_gauges`` lets a
        caller merge untyped legacy values in as gauges under the
        registry's prefix (the gateway's engine-stats snapshot)."""
        with self._lock:
            series = list(self._series.values())
            kinds = dict(self._kinds)
            helps = dict(self._helps)
        by_name: Dict[str, List[Any]] = {}
        for m in series:
            by_name.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name in sorted(by_name):
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} {kinds[name]}")
            samples: List[Tuple[str, Dict[str, str], float]] = []
            for m in by_name[name]:
                samples.extend(m.samples())
            for sname, slabels, sval in samples:
                lines.append(f"{sname}{_format_labels(slabels)} {_format_value(sval)}")
        if extra_gauges:
            for key in sorted(extra_gauges):
                val = extra_gauges[key]
                if isinstance(val, bool):
                    val = float(val)
                if not isinstance(val, (int, float)):
                    continue
                name = _metric_name(key, self.prefix)
                if name in kinds:
                    continue  # a typed series owns this name
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_format_value(float(val))}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump (obs_report / tests): flat values for counters
        and gauges, full bucket state for histograms."""
        with self._lock:
            series = list(self._series.items())
            kinds = dict(self._kinds)
        out: Dict[str, Any] = {}
        for (name, labelkey), m in series:
            label_str = ",".join(f"{k}={v}" for k, v in labelkey)
            key = f"{name}{{{label_str}}}" if label_str else name
            if kinds[name] == "histogram":
                out[key] = m.snapshot()
            else:
                out[key] = m.value
        return out


def render_merged(
    registries: Sequence[MetricsRegistry],
    extra_gauges: Optional[Mapping[str, float]] = None,
) -> str:
    """One valid exposition over several registries (the fleet case: one
    fleet-level registry + one per replica, all sharing a prefix and metric
    names distinguished by const_labels). Metric names may repeat ACROSS
    registries — they get one ``# TYPE`` header and their samples are
    concatenated — but a name registered as different kinds in different
    registries is a programming error and raises. ``extra_gauges`` follow
    ``MetricsRegistry.render`` semantics against the merged name set, using
    the first registry's prefix."""
    if not registries:
        raise ValueError("render_merged needs at least one registry")
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    by_name: Dict[str, List[Any]] = {}
    for reg in registries:
        with reg._lock:
            series = list(reg._series.values())
            for name, kind in reg._kinds.items():
                prior = kinds.get(name)
                if prior is not None and prior != kind:
                    raise ValueError(
                        f"metric {name} registered as {prior} in one "
                        f"registry and {kind} in another"
                    )
                kinds[name] = kind
            for name, help in reg._helps.items():
                helps.setdefault(name, help)
        for m in series:
            by_name.setdefault(m.name, []).append(m)
    lines: List[str] = []
    for name in sorted(by_name):
        if name in helps:
            lines.append(f"# HELP {name} {helps[name]}")
        lines.append(f"# TYPE {name} {kinds[name]}")
        for m in by_name[name]:
            for sname, slabels, sval in m.samples():
                lines.append(
                    f"{sname}{_format_labels(slabels)} {_format_value(sval)}"
                )
    if extra_gauges:
        prefix = registries[0].prefix
        for key in sorted(extra_gauges):
            val = extra_gauges[key]
            if isinstance(val, bool):
                val = float(val)
            if not isinstance(val, (int, float)):
                continue
            name = _metric_name(key, prefix)
            if name in kinds:
                continue  # a typed series owns this name
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_format_value(float(val))}")
    return "\n".join(lines) + ("\n" if lines else "")
